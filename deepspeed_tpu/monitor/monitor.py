"""Metrics monitors.

Analogue of reference ``deepspeed/monitor/`` (``Monitor`` ABC :13,
``MonitorMaster`` :29, TensorBoard/WandB/csv backends), rank-0-gated via
``jax.process_index``.
"""

import os
from abc import ABC, abstractmethod

import jax

from ..utils.logging import logger


class Monitor(ABC):

    def __init__(self, monitor_config):
        self.monitor_config = monitor_config

    @abstractmethod
    def write_events(self, event_list):
        ...


class csvMonitor(Monitor):

    def __init__(self, csv_config):
        super().__init__(csv_config)
        self.filenames = {}
        self.enabled = csv_config.enabled
        self.output_path = csv_config.output_path or "csv_monitor_output"
        self.job_name = csv_config.job_name

    def _file(self, name):
        if name not in self.filenames:
            path = os.path.join(self.output_path, self.job_name)
            os.makedirs(path, exist_ok=True)
            fname = os.path.join(path, "".join(c if (c.isalnum() or c in "._-") else "_" for c in name) + ".csv")
            self.filenames[name] = fname
        return self.filenames[name]

    def write_events(self, event_list):
        if not self.enabled or jax.process_index() != 0:
            return
        # group by metric so each csv file is opened once per call, not once
        # per event
        by_file = {}
        for event in event_list:
            name, value, step = event[0], event[1], event[2]
            by_file.setdefault(self._file(name), []).append(f"{step},{value}\n")
        for fname, lines in by_file.items():
            with open(fname, "a") as f:
                f.writelines(lines)


class TensorBoardMonitor(Monitor):

    def __init__(self, tensorboard_config):
        super().__init__(tensorboard_config)
        self.enabled = tensorboard_config.enabled
        self.summary_writer = None
        if self.enabled and jax.process_index() == 0:
            output_path = os.path.join(tensorboard_config.output_path or "tensorboard_output",
                                       tensorboard_config.job_name)
            try:
                from torch.utils.tensorboard import SummaryWriter
                os.makedirs(output_path, exist_ok=True)
                self.summary_writer = SummaryWriter(log_dir=output_path)
            except Exception as e:
                logger.warning(f"TensorBoard monitor disabled (writer unavailable: {e})")
                self.enabled = False

    def write_events(self, event_list, flush=True):
        if self.summary_writer is None:
            return
        for event in event_list:
            self.summary_writer.add_scalar(*event)
        if flush:
            self.summary_writer.flush()


class WandbMonitor(Monitor):

    def __init__(self, wandb_config):
        super().__init__(wandb_config)
        self.enabled = wandb_config.enabled
        if self.enabled and jax.process_index() == 0:
            try:
                import wandb
                wandb.init(project=wandb_config.project, group=wandb_config.group, entity=wandb_config.team)
                self._wandb = wandb
            except Exception as e:
                logger.warning(f"wandb monitor disabled ({e})")
                self.enabled = False

    def write_events(self, event_list):
        if not self.enabled or jax.process_index() != 0:
            return
        for event in event_list:
            label, value, step = event[0], event[1], event[2]
            self._wandb.log({label: value}, step=step)


class MonitorMaster(Monitor):
    """Dispatches to every enabled backend (reference ``monitor.py:29``)."""

    def __init__(self, ds_config):
        self.tb_monitor = None
        self.wandb_monitor = None
        self.csv_monitor = None
        self.enabled = False
        if jax.process_index() == 0:
            if ds_config.tensorboard.enabled:
                self.tb_monitor = TensorBoardMonitor(ds_config.tensorboard)
                self.enabled = True
            if ds_config.wandb.enabled:
                self.wandb_monitor = WandbMonitor(ds_config.wandb)
                self.enabled = True
            if ds_config.csv_monitor.enabled:
                self.csv_monitor = csvMonitor(ds_config.csv_monitor)
                self.enabled = True

    def write_events(self, event_list):
        if jax.process_index() != 0:
            return
        if self.tb_monitor is not None:
            self.tb_monitor.write_events(event_list)
        if self.wandb_monitor is not None:
            self.wandb_monitor.write_events(event_list)
        if self.csv_monitor is not None:
            self.csv_monitor.write_events(event_list)
