"""Nebula config shim (reference ``deepspeed/nebula/config.py`` +
``constants.py``).

The reference's Nebula integration is an Azure-hosted async tiered
checkpoint service: the config block selects the
``NebulaCheckpointEngine`` (``runtime/checkpoint_engine/
nebula_checkpoint_engine.py:20``), which hands torch saves to the
``torch_nebula`` SDK for background persistence with version retention.

On TPU the capability is NATIVE: the Orbax checkpoint engine
(``runtime/checkpoint_engine/engine.py``) already saves asynchronously
(``checkpoint.async_save``) with commit/latest semantics and no external
service. This module keeps the reference's CONFIG SURFACE so configs
carrying a ``nebula`` block parse, map onto the native async engine where
meaningful, and warn where they cannot.
"""

from ..utils.logging import logger

NEBULA = "nebula"
NEBULA_ENABLED = "enabled"
NEBULA_ENABLED_DEFAULT = False
NEBULA_ENABLE_NEBULA_LOAD = "enable_nebula_load"
NEBULA_ENABLE_NEBULA_LOAD_DEFAULT = True
NEBULA_LOAD_PATH = "nebula_load_path"
NEBULA_LOAD_PATH_DEFAULT = None
NEBULA_PERSISTENT_STORAGE_PATH = "persistent_storage_path"
NEBULA_PERSISTENT_STORAGE_PATH_DEFAULT = None
NEBULA_PERSISTENT_TIME_INTERVAL = "persistent_time_interval"
NEBULA_PERSISTENT_TIME_INTERVAL_DEFAULT = 100
NEBULA_NUM_OF_VERSION_IN_RETENTION = "num_of_version_in_retention"
NEBULA_NUM_OF_VERSION_IN_RETENTION_DEFAULT = 2


class DeepSpeedNebulaConfig:
    """Parse the reference's ``nebula`` block; ``enabled`` maps onto the
    native async (Orbax) checkpoint path."""

    def __init__(self, param_dict=None):
        nd = dict((param_dict or {}).get(NEBULA, {}) or {})
        self.enabled = bool(nd.get(NEBULA_ENABLED, NEBULA_ENABLED_DEFAULT))
        self.enable_nebula_load = bool(nd.get(NEBULA_ENABLE_NEBULA_LOAD,
                                              NEBULA_ENABLE_NEBULA_LOAD_DEFAULT))
        self.load_path = nd.get(NEBULA_LOAD_PATH, NEBULA_LOAD_PATH_DEFAULT)
        self.persistent_storage_path = nd.get(NEBULA_PERSISTENT_STORAGE_PATH,
                                              NEBULA_PERSISTENT_STORAGE_PATH_DEFAULT)
        self.persistent_time_interval = int(nd.get(NEBULA_PERSISTENT_TIME_INTERVAL,
                                                   NEBULA_PERSISTENT_TIME_INTERVAL_DEFAULT))
        self.num_of_version_in_retention = int(nd.get(
            NEBULA_NUM_OF_VERSION_IN_RETENTION, NEBULA_NUM_OF_VERSION_IN_RETENTION_DEFAULT))
        if self.enabled:
            logger.info("nebula.enabled: mapping onto the native async checkpoint "
                        "engine (checkpoint.async_save=true) — there is no external "
                        "Nebula service on TPU; persistence is Orbax commit/latest")
        if self.persistent_storage_path:
            logger.warning("nebula.persistent_storage_path is accepted for config "
                           "parity but tiered persistence is handled by the native "
                           "checkpoint dir; the value is not used")
