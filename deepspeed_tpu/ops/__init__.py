from . import op_builder  # noqa: F401
