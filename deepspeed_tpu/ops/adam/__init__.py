from .cpu_adam import DeepSpeedCPUAdam, cpu_adam_available  # noqa: F401
