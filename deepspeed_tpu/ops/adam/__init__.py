from .cpu_adam import DeepSpeedCPUAdam, cpu_adam_available  # noqa: F401
from .onebit_adam import (OneBitAdamState, onebit_adam, onebit_lamb,  # noqa: F401
                          zero_one_adam)
