from .cpu_adam import DeepSpeedCPUAdam, cpu_adam_available  # noqa: F401
from .onebit_adam import (OneBitAdamState, ZeroOneAdamState,  # noqa: F401
                          onebit_adam, onebit_lamb, zero_one_adam)
