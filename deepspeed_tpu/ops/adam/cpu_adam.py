"""Host (CPU) Adam/AdamW over numpy buffers.

TPU-native analogue of the reference's ``DeepSpeedCPUAdam``
(``deepspeed/ops/adam/cpu_adam.py:13`` over ``csrc/adam/cpu_adam.cpp``): the
ZeRO-Offload optimizer step runs on the host CPU against optimizer state
resident in host DRAM, freeing HBM for parameters/activations. The native
kernel (``ops/csrc/cpu_adam.c``) is AOT-compiled on first use with
``-O3 -march=native -fopenmp`` and bound via ctypes — the reference's JIT
``OpBuilder`` machinery (op_builder/builder.py:434) collapses to one cached
``cc`` invocation because there is no CUDA-arch matrix to probe. A pure-numpy
fallback keeps the optimizer functional where no C compiler exists.
"""

import ctypes
import hashlib
import os
import subprocess
import tempfile

import numpy as np

from ...utils.logging import logger

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "csrc", "cpu_adam.c")
_lib = None
_build_failed = False


def _build_lib():
    """Compile (once, cached by source hash) and dlopen the host kernel."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    try:
        with open(_SRC, "rb") as f:
            src = f.read()
        tag = hashlib.sha256(src).hexdigest()[:16]
        cache_dir = os.environ.get("DSTPU_CACHE_DIR") or os.path.join(
            os.path.expanduser("~"), ".cache", "deepspeed_tpu")
        os.makedirs(cache_dir, exist_ok=True)
        so_path = os.path.join(cache_dir, f"cpu_adam_{tag}.so")
        if not os.path.exists(so_path):
            cc = os.environ.get("CC", "cc")
            with tempfile.TemporaryDirectory() as td:
                tmp_so = os.path.join(td, "cpu_adam.so")
                cmd = [cc, "-O3", "-march=native", "-fopenmp", "-shared", "-fPIC",
                       _SRC, "-o", tmp_so, "-lm"]
                subprocess.run(cmd, check=True, capture_output=True)
                os.replace(tmp_so, so_path)
            logger.info(f"cpu_adam: built native host kernel -> {so_path}")
        lib = ctypes.CDLL(so_path)
        i64, f32, fp, u16p = ctypes.c_int64, ctypes.c_float, ctypes.POINTER(ctypes.c_float), \
            ctypes.POINTER(ctypes.c_uint16)
        lib.ds_adamw_step.argtypes = [fp, fp, fp, fp, i64, f32, f32, f32, f32, f32, i64, f32,
                                      ctypes.c_int]
        lib.ds_adamw_step_bf16g.argtypes = [fp, fp, fp, u16p, i64, f32, f32, f32, f32, f32, i64,
                                            f32, ctypes.c_int]
        lib.ds_f32_to_bf16.argtypes = [fp, u16p, i64]
        lib.ds_adagrad_step.argtypes = [fp, fp, fp, i64, f32, f32, f32, f32]
        _lib = lib
    except Exception as e:  # no compiler / unsupported flags: numpy fallback
        logger.warning(f"cpu_adam: native build failed ({e}); using numpy fallback")
        _build_failed = True
    return _lib


def cpu_adam_available():
    return _build_lib() is not None


def _as_f32_ptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _as_u16_ptr(a):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16))


class DeepSpeedCPUAdam:
    """Fused host AdamW over a flat fp32 buffer triple (param, m, v).

    Reference API parity is intentionally loose: the torch version mutates
    ``torch.nn.Parameter``s; here state lives in plain numpy arrays owned by
    the ZeRO-Offload host optimizer (``runtime/zero/offload.py``).
    """

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0, adamw_mode=True):
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self._lib = _build_lib()

    def step(self, p, m, v, grad, step, lr=None, grad_coef=1.0):
        """In-place AdamW update. ``p``/``m``/``v``: contiguous fp32 numpy
        arrays; ``grad``: fp32 or bfloat16(uint16-viewed via ml_dtypes) numpy
        array of the same size; ``step`` is 1-based."""
        lr = self.lr if lr is None else lr
        n = p.size
        b1, b2 = self.betas
        grad_is_bf16 = grad.dtype.itemsize == 2 and grad.dtype != np.float16  # bfloat16
        if not grad_is_bf16 and grad.dtype != np.float32:
            grad = grad.astype(np.float32)  # e.g. fp16 parity mode
        if self._lib is not None:
            if grad_is_bf16:
                self._lib.ds_adamw_step_bf16g(
                    _as_f32_ptr(p), _as_f32_ptr(m), _as_f32_ptr(v),
                    _as_u16_ptr(grad.view(np.uint16)), n, lr, b1, b2, self.eps,
                    self.weight_decay, step, grad_coef, int(self.adamw_mode))
            else:
                self._lib.ds_adamw_step(
                    _as_f32_ptr(p), _as_f32_ptr(m), _as_f32_ptr(v), _as_f32_ptr(grad), n,
                    lr, b1, b2, self.eps, self.weight_decay, step, grad_coef,
                    int(self.adamw_mode))
            return
        # numpy fallback (same math)
        g = grad.astype(np.float32) * grad_coef
        if not self.adamw_mode and self.weight_decay:
            g += self.weight_decay * p
        m *= b1
        m += (1 - b1) * g
        v *= b2
        v += (1 - b2) * np.square(g)
        mhat = m / (1 - b1**step)
        vhat = v / (1 - b2**step)
        upd = mhat / (np.sqrt(vhat) + self.eps)
        if self.adamw_mode and self.weight_decay:
            upd += self.weight_decay * p
        p -= lr * upd


def f32_to_bf16(src, out=None):
    """Round-to-nearest-even fp32 -> bfloat16 on the host (native when
    available)."""
    import ml_dtypes
    lib = _build_lib()
    if out is None:
        out = np.empty(src.shape, dtype=ml_dtypes.bfloat16)
    if lib is not None:
        lib.ds_f32_to_bf16(_as_f32_ptr(src), _as_u16_ptr(out.view(np.uint16)), src.size)
        return out
    out[...] = src.astype(ml_dtypes.bfloat16)
    return out
