"""1-bit Adam: error-compensated compressed-momentum data parallelism.

Counterpart of reference ``runtime/fp16/onebit/adam.py`` (``OnebitAdam``,
paper "1-bit Adam: communication efficient large-scale training with Adam's
convergence speed"). Two phases:

- warmup (``step < freeze_step``): exact dense Adam — gradients are averaged
  across the data-parallel group and both moments update normally.
- compression (``step >= freeze_step``): the variance ``v`` freezes; each
  worker updates its *local* momentum and the group exchanges only the
  1-bit-compressed momentum (sign plane + scalar scale, with error feedback
  carried between steps — ``runtime/comm/compressed.onebit_all_reduce``).

Expressed as an ``optax.GradientTransformation`` over per-shard (UNREDUCED)
gradients inside ``shard_map`` with ``axis_name`` bound on the data axis —
the TPU-native form of the reference's cupy/NCCL compressed allreduce. The
engine's default pjit path lets XLA reduce gradients densely (the right call
on ICI); this optimizer is for DCN-bound multislice loops where momentum
bytes dominate.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from ...runtime.comm.compressed import chunk_len, onebit_all_reduce


class OneBitAdamState(NamedTuple):
    count: jnp.ndarray
    m: optax.Updates
    v: optax.Updates
    error: optax.Updates  # worker compression error feedback
    server_error: optax.Updates  # server error on this worker's owned chunk


class ZeroOneAdamState(NamedTuple):
    count: jnp.ndarray
    v_count: jnp.ndarray  # number of actual v updates (exponentially spaced)
    m: optax.Updates
    v: optax.Updates
    error: optax.Updates
    server_error: optax.Updates


def _group_size(axis_name):
    """DP group size at trace/init time (the mesh is already installed when
    the engine builds the optimizer; single-process tests default to 1)."""
    from ...comm import comm as dist
    if dist.has_mesh():
        return int(dist.get_mesh().shape[axis_name])
    return 1


def _init_onebit_state(params, n):
    # NOTE: server_error leaves are sized chunk_len(size, n) with the DP
    # group size n baked in, so a OneBit/ZeroOne checkpoint can only be
    # restored at the SAME data-parallel size — unlike the repo's
    # layout-free fused/offload states (a resize restore fails with a
    # shape mismatch; resume such runs with load_optimizer_states=False
    # for a fresh optimizer). The reference has the same restriction
    # (onebit/adam.py keeps per-worker server chunks).
    zeros = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p, jnp.float32), params)
    server = jax.tree_util.tree_map(
        lambda p: jnp.zeros((chunk_len(_size(p), n), ), jnp.float32), params)
    return OneBitAdamState(count=jnp.zeros((), jnp.int32), m=zeros,
                           v=jax.tree_util.tree_map(jnp.copy, zeros),
                           error=jax.tree_util.tree_map(jnp.copy, zeros),
                           server_error=server)


def _size(p):
    out = 1
    for d in p.shape:
        out *= int(d)
    return out


def onebit_adam(learning_rate, axis_name, b1=0.9, b2=0.999, eps=1e-8,
                weight_decay=0.0, freeze_step=100, group_size=None):
    """Build the transformation. ``learning_rate``: float or schedule(count).
    Apply with per-shard gradients inside ``shard_map``; updates come out
    replicated across ``axis_name`` (all workers apply the same step).
    ``group_size``: DP group size (resolved from the mesh when omitted) —
    sizes the server-error state of the two-phase compressed exchange."""

    def init(params):
        return _init_onebit_state(params, group_size or _group_size(axis_name))

    def _leaf_update(count, g, m, v, err, serr):
        g = g.astype(jnp.float32)

        def warm(_):
            g_avg = jax.lax.pmean(g, axis_name)
            m2 = b1 * m + (1 - b1) * g_avg
            v2 = b2 * v + (1 - b2) * jnp.square(g_avg)
            return m2, v2, err, serr

        def compressed(_):
            m_local = b1 * m + (1 - b1) * g
            m2, err2, serr2 = onebit_all_reduce(m_local, err, serr, axis_name)
            return m2, v, err2, serr2  # v frozen

        if freeze_step <= 0:
            # static specialization: lax.cond compiles BOTH branches, so a
            # never-taken warm branch would still put a dense fp32 pmean in
            # the program (and in any wire-bytes audit of its HLO)
            return compressed(None)
        # compression begins at step >= freeze_step (paper schedule)
        return jax.lax.cond(count < freeze_step, warm, compressed, None)

    def update(grads, state, params=None):
        if weight_decay and params is None:
            raise ValueError("onebit_adam with weight_decay requires params in update()")
        count = state.count + 1
        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_m = jax.tree_util.tree_leaves(state.m)
        flat_v = jax.tree_util.tree_leaves(state.v)
        flat_e = jax.tree_util.tree_leaves(state.error)
        flat_s = jax.tree_util.tree_leaves(state.server_error)
        new_m, new_v, new_e, new_s, upd = [], [], [], [], []
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        flat_p = jax.tree_util.tree_leaves(params) if params is not None else [None] * len(flat_g)
        for g, m, v, e, s, p in zip(flat_g, flat_m, flat_v, flat_e, flat_s, flat_p):
            m2, v2, e2, s2 = _leaf_update(count, g, m, v, e, s)
            mhat = m2 / (1 - b1**count.astype(jnp.float32))
            vhat = v2 / (1 - b2**count.astype(jnp.float32))
            step = mhat / (jnp.sqrt(vhat) + eps)
            if weight_decay and p is not None:
                step = step + weight_decay * p.astype(jnp.float32)
            new_m.append(m2)
            new_v.append(v2)
            new_e.append(e2)
            new_s.append(s2)
            upd.append((-lr * step).astype(g.dtype))
        unf = lambda leaves: jax.tree_util.tree_unflatten(treedef, leaves)
        return unf(upd), OneBitAdamState(count=count, m=unf(new_m), v=unf(new_v),
                                         error=unf(new_e), server_error=unf(new_s))

    return optax.GradientTransformation(init, update)


def zero_one_adam(learning_rate, axis_name, b1=0.9, b2=0.999, eps=1e-8,
                  weight_decay=0.0, var_freeze_step=100, var_update_scaler=16,
                  local_step_scaler=1000, local_step_clipper=16, group_size=None):
    """0/1 Adam (reference ``runtime/fp16/onebit/zoadam.py``; paper "0/1
    Adam: accelerating distributed training with adaptive compression"): the
    variance updates only at exponentially-spaced steps (doubling intervals
    of base ``var_update_scaler``) and freezes at ``var_freeze_step``; the
    momentum exchange is 1-bit-compressed from the first step.

    Deliberate simplification, documented: the paper's *local-step* policy
    (skipping synchronization entirely between intermittent barriers) makes
    per-worker parameters diverge between syncs, which does not compose with
    a replicated-parameter optax update contract — so this implementation
    synchronizes the compressed momentum every step (``local_step_scaler``/
    ``local_step_clipper`` are accepted for signature parity and recorded
    only). The adaptive-variance policy, the primary convergence mechanism,
    is implemented faithfully."""
    del local_step_scaler, local_step_clipper  # parity knobs; see docstring

    def init(params):
        base = _init_onebit_state(params, group_size or _group_size(axis_name))
        return ZeroOneAdamState(count=base.count, v_count=jnp.zeros((), jnp.int32),
                                m=base.m, v=base.v, error=base.error,
                                server_error=base.server_error)

    def _v_update_due(count):
        # doubling intervals: update at k, k + 2k, + 4k, ... until freeze
        k = jnp.float32(var_update_scaler)
        c = count.astype(jnp.float32)
        # count sits on a boundary iff log2(1 + c/k) is integral
        lev = jnp.log2(1.0 + c / k)
        on_boundary = jnp.abs(lev - jnp.round(lev)) < 1e-6
        return (count < var_freeze_step) & ((count <= var_update_scaler) | on_boundary)

    def update(grads, state, params=None):
        if weight_decay and params is None:
            raise ValueError("zero_one_adam with weight_decay requires params in update()")
        count = state.count + 1
        due = _v_update_due(count)
        v_count = state.v_count + due.astype(jnp.int32)
        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_m = jax.tree_util.tree_leaves(state.m)
        flat_v = jax.tree_util.tree_leaves(state.v)
        flat_e = jax.tree_util.tree_leaves(state.error)
        flat_s = jax.tree_util.tree_leaves(state.server_error)
        flat_p = jax.tree_util.tree_leaves(params) if params is not None else [None] * len(flat_g)
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        new_m, new_v, new_e, new_s, upd = [], [], [], [], []
        for g, m, v, e, s, p in zip(flat_g, flat_m, flat_v, flat_e, flat_s, flat_p):
            g = g.astype(jnp.float32)
            m_local = b1 * m + (1 - b1) * g
            m2, e2, s2 = onebit_all_reduce(m_local, e, s, axis_name)
            # the dense gradient pmean only runs at the (exponentially rare)
            # due steps — cond, not where, so the wire stays compressed
            v2 = jax.lax.cond(
                due,
                lambda vg: b2 * vg[0] + (1 - b2) * jnp.square(jax.lax.pmean(vg[1], axis_name)),
                lambda vg: vg[0], (v, g))
            mhat = m2 / (1 - b1**count.astype(jnp.float32))
            # bias-correct v by the number of times it actually updated (the
            # exponentially-spaced schedule), not the step count
            vhat = v2 / (1 - b2**jnp.maximum(v_count, 1).astype(jnp.float32))
            step = mhat / (jnp.sqrt(vhat) + eps)
            if weight_decay and p is not None:
                step = step + weight_decay * p.astype(jnp.float32)
            new_m.append(m2)
            new_v.append(v2)
            new_e.append(e2)
            new_s.append(s2)
            upd.append((-lr * step).astype(g.dtype))
        unf = lambda leaves: jax.tree_util.tree_unflatten(treedef, leaves)
        return unf(upd), ZeroOneAdamState(count=count, v_count=v_count, m=unf(new_m),
                                          v=unf(new_v), error=unf(new_e),
                                          server_error=unf(new_s))

    return optax.GradientTransformation(init, update)


def onebit_lamb(learning_rate, axis_name, b1=0.9, b2=0.999, eps=1e-8,
                weight_decay=0.0, freeze_step=100, min_trust=0.01, max_trust=10.0):
    """1-bit LAMB (reference ``runtime/fp16/onebit/lamb.py``): the 1-bit Adam
    step followed by a per-layer trust-ratio rescale."""
    inner = onebit_adam(1.0, axis_name, b1=b1, b2=b2, eps=eps,
                        weight_decay=weight_decay, freeze_step=freeze_step)

    def init(params):
        return inner.init(params)

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("onebit_lamb requires params in update() (trust ratio needs |w|)")
        raw, new_state = inner.update(grads, state, params)
        lr = learning_rate(new_state.count) if callable(learning_rate) else learning_rate

        def scaled(u, p):
            pn = jnp.linalg.norm(p.astype(jnp.float32).reshape(-1))
            un = jnp.linalg.norm(u.astype(jnp.float32).reshape(-1))
            trust = jnp.clip(pn / jnp.maximum(un, 1e-12), min_trust, max_trust)
            trust = jnp.where(pn == 0, 1.0, trust)
            return (lr * trust * u.astype(jnp.float32)).astype(u.dtype)

        return jax.tree_util.tree_map(scaled, raw, params), new_state

    return optax.GradientTransformation(init, update)
