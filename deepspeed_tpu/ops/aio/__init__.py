"""Async file I/O handle over the native worker pool.

Python surface of the NVMe tier's I/O engine — the counterpart of the
reference's ``AsyncIOBuilder().load().aio_handle(...)`` (``csrc/aio/py_lib/
py_ds_aio.cpp``: ``async_pread``/``async_pwrite``/``wait``). Requests larger
than ``block_size`` are split into parallel block reads/writes across the
pool's threads (the reference splits inside its C++ engine; here the split
lives in Python and the C side stays a flat request queue).
"""

import ctypes
import hashlib
import os
import subprocess
import tempfile

import numpy as np

from ...utils.logging import logger

_SRC = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "csrc", "aio.c")
_lib = None
_build_failed = False


def _build_lib():
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    try:
        with open(_SRC, "rb") as f:
            src = f.read()
        tag = hashlib.sha256(src).hexdigest()[:16]
        cache_dir = os.environ.get("DSTPU_CACHE_DIR") or os.path.join(
            os.path.expanduser("~"), ".cache", "deepspeed_tpu")
        os.makedirs(cache_dir, exist_ok=True)
        so_path = os.path.join(cache_dir, f"aio_{tag}.so")
        if not os.path.exists(so_path):
            cc = os.environ.get("CC", "cc")
            with tempfile.TemporaryDirectory() as td:
                tmp_so = os.path.join(td, "aio.so")
                subprocess.run([cc, "-O2", "-shared", "-fPIC", _SRC, "-o", tmp_so, "-lpthread"],
                               check=True, capture_output=True)
                os.replace(tmp_so, so_path)
            logger.info(f"aio: built native IO pool -> {so_path}")
        lib = ctypes.CDLL(so_path)
        lib.ds_aio_create.restype = ctypes.c_void_p
        lib.ds_aio_create.argtypes = [ctypes.c_int]
        lib.ds_aio_submit.restype = ctypes.c_int
        lib.ds_aio_submit.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                                      ctypes.c_int64, ctypes.c_int64, ctypes.c_int]
        lib.ds_aio_wait.restype = ctypes.c_int64
        lib.ds_aio_wait.argtypes = [ctypes.c_void_p]
        lib.ds_aio_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
    except Exception as e:
        logger.warning(f"aio: native build failed ({e}); using synchronous numpy IO fallback")
        _build_failed = True
    return _lib


def aio_available():
    return _build_lib() is not None


def aligned_empty(shape, dtype=np.float32, align=4096):
    """Uninitialized array whose data pointer is ``align``-byte aligned —
    buffers allocated this way let the native pool's O_DIRECT fast path fire
    (the analogue of the reference's pinned aio buffers,
    ``csrc/aio/py_lib/deepspeed_pin_tensor.cpp``)."""
    dtype = np.dtype(dtype)
    nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    raw = np.empty(nbytes + align, np.uint8)
    off = (-raw.ctypes.data) % align
    return raw[off:off + nbytes].view(dtype).reshape(shape)


class AsyncIOHandle:
    """``async_pread``/``async_pwrite``/``wait`` over host numpy buffers.

    One handle owns one native thread pool. Buffers passed to the async calls
    MUST stay alive (and unmodified, for writes) until ``wait()`` returns —
    the same contract as the reference's pinned-tensor handle.

    ``queue_depth``/``overlap_events`` are recorded for reference config
    parity but advisory: the pool's queue is unbounded and overlap comes
    from its threads (see ``runtime/swap_tensor/aio_config.py``).
    """

    def __init__(self, block_size=1048576, queue_depth=8, single_submit=False,
                 overlap_events=True, thread_count=4):
        self.block_size = int(block_size)
        self.thread_count = int(thread_count)
        self.queue_depth = int(queue_depth)
        self.single_submit = bool(single_submit)
        self.overlap_events = bool(overlap_events)
        lib = _build_lib()
        self._lib = lib
        self._h = lib.ds_aio_create(self.thread_count) if lib is not None else None
        self._pending_sync = []  # fallback mode: deferred synchronous ops
        self._keepalive = []  # buffers (and write copies) pinned until wait()

    # -- core ------------------------------------------------------------
    def _submit(self, arr, filename, is_write, file_offset=0):
        buf = np.ascontiguousarray(arr)
        if not is_write and (buf is not arr and not np.shares_memory(buf, arr)):
            # a read into a temp copy would be silently dropped
            raise ValueError("async read target must be a contiguous array")
        self._keepalive.append(buf)
        view = buf.view(np.uint8).reshape(-1)
        nbytes = view.nbytes
        if self._h is None:  # fallback: run at wait() time, still one-shot
            self._pending_sync.append((arr, filename, is_write, file_offset))
            return
        ptr = view.ctypes.data_as(ctypes.c_char_p)
        base = ctypes.cast(ptr, ctypes.c_void_p).value
        path = os.fsencode(filename)
        if self.single_submit or nbytes <= self.block_size:
            rc = self._lib.ds_aio_submit(self._h, path, ctypes.c_char_p(base), nbytes,
                                         file_offset, int(is_write))
            if rc != 0:
                raise OSError(f"aio submit failed for {filename}")
            return
        off = 0
        while off < nbytes:
            chunk = min(self.block_size, nbytes - off)
            rc = self._lib.ds_aio_submit(self._h, path, ctypes.c_char_p(base + off), chunk,
                                         file_offset + off, int(is_write))
            if rc != 0:
                raise OSError(f"aio submit failed for {filename}")
            off += chunk

    def async_pread(self, buffer, filename, file_offset=0):
        self._submit(buffer, filename, is_write=False, file_offset=file_offset)

    def async_pwrite(self, buffer, filename, file_offset=0):
        self._submit(buffer, filename, is_write=True, file_offset=file_offset)

    def wait(self):
        if self._h is None:
            first_err = None
            for arr, filename, is_write, off in self._pending_sync:
                try:
                    view = np.ascontiguousarray(arr).view(np.uint8).reshape(-1)
                    if is_write:
                        with open(filename, "r+b" if os.path.exists(filename) else "wb") as f:
                            f.seek(off)
                            f.write(view.tobytes())
                    else:
                        with open(filename, "rb") as f:
                            f.seek(off)
                            data = f.read(view.nbytes)
                        if len(data) != view.nbytes:
                            raise OSError(f"short read from {filename}: got {len(data)} of "
                                          f"{view.nbytes} bytes at offset {off}")
                        view[:] = np.frombuffer(data, np.uint8)
                except Exception as e:  # always-drain invariant: no failure may wedge the handle
                    first_err = first_err or e
            # always drain: a failed request must not wedge the handle
            self._pending_sync.clear()
            self._keepalive.clear()
            if first_err is not None:
                raise OSError(f"async IO request failed: {first_err}") from first_err
            return 0  # native-contract parity: number of FAILED requests
        failed = self._lib.ds_aio_wait(self._h)
        self._keepalive.clear()
        if failed:
            raise OSError(f"{failed} async IO request(s) failed")
        return 0

    # -- sync convenience (reference parity) -----------------------------
    def sync_pread(self, buffer, filename, file_offset=0):
        self.async_pread(buffer, filename, file_offset)
        return self.wait()

    def sync_pwrite(self, buffer, filename, file_offset=0):
        self.async_pwrite(buffer, filename, file_offset)
        return self.wait()

    def close(self):
        if self._h is not None:
            self.wait()
            self._lib.ds_aio_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
