/* Asynchronous file I/O worker pool for the NVMe offload tier.
 *
 * Native analogue of the reference's libaio-based engine (csrc/aio/py_lib/
 * deepspeed_aio_thread.cpp, deepspeed_py_aio_handle.cpp): a pool of POSIX
 * threads services pread/pwrite requests from a mutex+condvar queue so
 * device<->host<->disk stages overlap. Buffered pread/pwrite instead of
 * io_submit: the swap working set is stream-shaped (large sequential leaf
 * blocks), where the page cache either helps or is bypassed by O_DIRECT-
 * capable deployments at mount level; the scheduling benefit (overlap with
 * the host Adam step and the TPU transfers) comes from the thread pool, not
 * the kernel AIO interface.
 *
 * API (ctypes-bound in deepspeed_tpu/ops/aio/__init__.py):
 *   ds_aio_create(threads) -> handle
 *   ds_aio_submit(h, path, buf, nbytes, file_offset, is_write) -> 0/-1
 *   ds_aio_wait(h) -> number of failed requests since last wait
 *   ds_aio_destroy(h)
 */

#define _GNU_SOURCE
#include <fcntl.h>
#include <pthread.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

typedef struct req {
    char *path;
    char *buf;
    int64_t nbytes;
    int64_t offset;
    int is_write;
    struct req *next;
} req_t;

typedef struct {
    pthread_mutex_t mu;
    pthread_cond_t work_cv;   /* signalled when a request is queued */
    pthread_cond_t done_cv;   /* signalled when in_flight drops */
    req_t *head, *tail;
    int64_t in_flight;        /* queued + executing */
    int64_t failed;
    int shutdown;
    int nthreads;
    pthread_t *threads;
} ds_aio_t;

static int run_request(req_t *r) {
    int fd = r->is_write ? open(r->path, O_WRONLY | O_CREAT, 0644)
                         : open(r->path, O_RDONLY);
    if (fd < 0) return -1;
    int64_t done = 0;
    while (done < r->nbytes) {
        ssize_t n = r->is_write
            ? pwrite(fd, r->buf + done, (size_t)(r->nbytes - done), r->offset + done)
            : pread(fd, r->buf + done, (size_t)(r->nbytes - done), r->offset + done);
        if (n <= 0) { close(fd); return -1; }
        done += n;
    }
    close(fd);
    return 0;
}

static void *worker(void *arg) {
    ds_aio_t *h = (ds_aio_t *)arg;
    for (;;) {
        pthread_mutex_lock(&h->mu);
        while (!h->head && !h->shutdown)
            pthread_cond_wait(&h->work_cv, &h->mu);
        if (!h->head && h->shutdown) {
            pthread_mutex_unlock(&h->mu);
            return NULL;
        }
        req_t *r = h->head;
        h->head = r->next;
        if (!h->head) h->tail = NULL;
        pthread_mutex_unlock(&h->mu);

        int rc = run_request(r);

        pthread_mutex_lock(&h->mu);
        if (rc != 0) h->failed++;
        h->in_flight--;
        pthread_cond_broadcast(&h->done_cv);
        pthread_mutex_unlock(&h->mu);
        free(r->path);
        free(r);
    }
}

ds_aio_t *ds_aio_create(int nthreads) {
    if (nthreads < 1) nthreads = 1;
    ds_aio_t *h = (ds_aio_t *)calloc(1, sizeof(ds_aio_t));
    pthread_mutex_init(&h->mu, NULL);
    pthread_cond_init(&h->work_cv, NULL);
    pthread_cond_init(&h->done_cv, NULL);
    h->nthreads = nthreads;
    h->threads = (pthread_t *)calloc((size_t)nthreads, sizeof(pthread_t));
    for (int i = 0; i < nthreads; i++)
        pthread_create(&h->threads[i], NULL, worker, h);
    return h;
}

int ds_aio_submit(ds_aio_t *h, const char *path, char *buf, int64_t nbytes,
                  int64_t offset, int is_write) {
    req_t *r = (req_t *)malloc(sizeof(req_t));
    if (!r) return -1;
    r->path = strdup(path);
    r->buf = buf;
    r->nbytes = nbytes;
    r->offset = offset;
    r->is_write = is_write;
    r->next = NULL;
    pthread_mutex_lock(&h->mu);
    if (h->tail) h->tail->next = r; else h->head = r;
    h->tail = r;
    h->in_flight++;
    pthread_cond_signal(&h->work_cv);
    pthread_mutex_unlock(&h->mu);
    return 0;
}

int64_t ds_aio_wait(ds_aio_t *h) {
    pthread_mutex_lock(&h->mu);
    while (h->in_flight > 0)
        pthread_cond_wait(&h->done_cv, &h->mu);
    int64_t failed = h->failed;
    h->failed = 0;
    pthread_mutex_unlock(&h->mu);
    return failed;
}

void ds_aio_destroy(ds_aio_t *h) {
    pthread_mutex_lock(&h->mu);
    h->shutdown = 1;
    pthread_cond_broadcast(&h->work_cv);
    pthread_mutex_unlock(&h->mu);
    for (int i = 0; i < h->nthreads; i++)
        pthread_join(h->threads[i], NULL);
    free(h->threads);
    pthread_mutex_destroy(&h->mu);
    pthread_cond_destroy(&h->work_cv);
    pthread_cond_destroy(&h->done_cv);
    free(h);
}
