/* Asynchronous file I/O worker pool for the NVMe offload tier.
 *
 * Native analogue of the reference's libaio-based engine (csrc/aio/py_lib/
 * deepspeed_aio_thread.cpp, deepspeed_py_aio_handle.cpp): a pool of POSIX
 * threads services pread/pwrite requests from a mutex+condvar queue so
 * device<->host<->disk stages overlap; aligned requests take O_DIRECT for
 * their bulk (see run_request) so swap working sets >> page cache avoid the
 * double copy. The scheduling benefit (overlap with the host Adam step and
 * the TPU transfers) comes from the thread pool; io_uring/io_submit would
 * only relocate the queue into the kernel.
 *
 * API (ctypes-bound in deepspeed_tpu/ops/aio/__init__.py):
 *   ds_aio_create(threads) -> handle
 *   ds_aio_submit(h, path, buf, nbytes, file_offset, is_write) -> 0/-1
 *   ds_aio_wait(h) -> number of failed requests since last wait
 *   ds_aio_destroy(h)
 */

#define _GNU_SOURCE
#include <fcntl.h>
#include <pthread.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

typedef struct req {
    char *path;
    char *buf;
    int64_t nbytes;
    int64_t offset;
    int is_write;
    struct req *next;
} req_t;

typedef struct {
    pthread_mutex_t mu;
    pthread_cond_t work_cv;   /* signalled when a request is queued */
    pthread_cond_t done_cv;   /* signalled when in_flight drops */
    req_t *head, *tail;
    int64_t in_flight;        /* queued + executing */
    int64_t failed;
    int shutdown;
    int nthreads;
    pthread_t *threads;
} ds_aio_t;

#define DS_AIO_ALIGN 4096

static int do_io(int fd, req_t *r, int64_t start, int64_t end) {
    int64_t done = start;
    while (done < end) {
        ssize_t n = r->is_write
            ? pwrite(fd, r->buf + done, (size_t)(end - done), r->offset + done)
            : pread(fd, r->buf + done, (size_t)(end - done), r->offset + done);
        if (n <= 0) return -1;
        done += n;
    }
    return 0;
}

/* O_DIRECT when the request allows it (reference csrc/aio uses libaio +
 * O_DIRECT; for swap working sets >> page cache, buffered IO double-copies
 * through it). Strategy: when buffer AND file offset are 4096-aligned, the
 * largest aligned PREFIX goes through an O_DIRECT fd and only the tail is
 * buffered — so arbitrary request lengths still bypass the cache for their
 * bulk. Any O_DIRECT failure (unsupported fs, tmpfs, misalignment raced by
 * the kernel) falls back to fully buffered, never to an error. */
static int run_request(req_t *r) {
    int flags = r->is_write ? (O_WRONLY | O_CREAT) : O_RDONLY;
    int64_t direct_end = 0;
    if ((((uintptr_t)r->buf | (uintptr_t)r->offset) & (DS_AIO_ALIGN - 1)) == 0)
        direct_end = r->nbytes & ~(int64_t)(DS_AIO_ALIGN - 1);
    if (direct_end > 0) {
        int dfd = open(r->path, flags | O_DIRECT, 0644);
        if (dfd >= 0) {
            int rc = do_io(dfd, r, 0, direct_end);
            close(dfd);
            if (rc != 0) direct_end = 0;  /* mid-stream EINVAL: redo buffered */
        } else {
            direct_end = 0;
        }
    }
    if (r->nbytes > 0 && direct_end >= r->nbytes) return 0;
    /* nbytes == 0 still opens with O_CREAT below: an empty write
     * must create the file (fallback-path parity) */
    int fd = open(r->path, flags, 0644);
    if (fd < 0) return -1;
    int rc = do_io(fd, r, direct_end, r->nbytes);
    close(fd);
    return rc;
}

static void *worker(void *arg) {
    ds_aio_t *h = (ds_aio_t *)arg;
    for (;;) {
        pthread_mutex_lock(&h->mu);
        while (!h->head && !h->shutdown)
            pthread_cond_wait(&h->work_cv, &h->mu);
        if (!h->head && h->shutdown) {
            pthread_mutex_unlock(&h->mu);
            return NULL;
        }
        req_t *r = h->head;
        h->head = r->next;
        if (!h->head) h->tail = NULL;
        pthread_mutex_unlock(&h->mu);

        int rc = run_request(r);

        pthread_mutex_lock(&h->mu);
        if (rc != 0) h->failed++;
        h->in_flight--;
        pthread_cond_broadcast(&h->done_cv);
        pthread_mutex_unlock(&h->mu);
        free(r->path);
        free(r);
    }
}

ds_aio_t *ds_aio_create(int nthreads) {
    if (nthreads < 1) nthreads = 1;
    ds_aio_t *h = (ds_aio_t *)calloc(1, sizeof(ds_aio_t));
    pthread_mutex_init(&h->mu, NULL);
    pthread_cond_init(&h->work_cv, NULL);
    pthread_cond_init(&h->done_cv, NULL);
    h->nthreads = nthreads;
    h->threads = (pthread_t *)calloc((size_t)nthreads, sizeof(pthread_t));
    for (int i = 0; i < nthreads; i++)
        pthread_create(&h->threads[i], NULL, worker, h);
    return h;
}

int ds_aio_submit(ds_aio_t *h, const char *path, char *buf, int64_t nbytes,
                  int64_t offset, int is_write) {
    req_t *r = (req_t *)malloc(sizeof(req_t));
    if (!r) return -1;
    r->path = strdup(path);
    r->buf = buf;
    r->nbytes = nbytes;
    r->offset = offset;
    r->is_write = is_write;
    r->next = NULL;
    pthread_mutex_lock(&h->mu);
    if (h->tail) h->tail->next = r; else h->head = r;
    h->tail = r;
    h->in_flight++;
    pthread_cond_signal(&h->work_cv);
    pthread_mutex_unlock(&h->mu);
    return 0;
}

int64_t ds_aio_wait(ds_aio_t *h) {
    pthread_mutex_lock(&h->mu);
    while (h->in_flight > 0)
        pthread_cond_wait(&h->done_cv, &h->mu);
    int64_t failed = h->failed;
    h->failed = 0;
    pthread_mutex_unlock(&h->mu);
    return failed;
}

void ds_aio_destroy(ds_aio_t *h) {
    pthread_mutex_lock(&h->mu);
    h->shutdown = 1;
    pthread_cond_broadcast(&h->work_cv);
    pthread_mutex_unlock(&h->mu);
    for (int i = 0; i < h->nthreads; i++)
        pthread_join(h->threads[i], NULL);
    free(h->threads);
    pthread_mutex_destroy(&h->mu);
    pthread_cond_destroy(&h->work_cv);
    pthread_cond_destroy(&h->done_cv);
    free(h);
}
