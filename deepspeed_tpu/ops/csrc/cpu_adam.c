/* Host (CPU) fused AdamW/Adam step over flat float buffers.
 *
 * TPU-native equivalent of the reference's vectorized CPU optimizer
 * (csrc/adam/cpu_adam.cpp, Adam_Optimizer::Step_AVX in
 * csrc/includes/cpu_adam.h:72): steps ZeRO-Offload'ed optimizer state
 * resident in host DRAM. Where the reference hand-writes AVX-512/AVX-256
 * intrinsics, this implementation is plain elementwise C compiled with
 * -O3 -march=native -fopenmp — the loops are exactly the shape the
 * auto-vectorizer turns into the same AVX code, across x86 *and* ARM
 * (TPU-VM hosts are x86 today; Axion hosts are NEON).
 *
 * Math matches optax.adamw bit-for-bit in fp32:
 *   m = b1*m + (1-b1)*g;  v = b2*v + (1-b2)*g^2
 *   p -= lr * ( (m/(1-b1^t)) / (sqrt(v/(1-b2^t)) + eps) + wd*p )
 * (plain Adam mode folds wd into the gradient instead).
 *
 * grad_coef folds loss-scale unscaling, gradient-accumulation averaging and
 * clipping into the single pass over the gradient.
 */

#include <math.h>
#include <stdint.h>
#include <string.h>

void ds_adamw_step(float *p, float *m, float *v, const float *g, int64_t n,
                   float lr, float beta1, float beta2, float eps,
                   float weight_decay, int64_t step, float grad_coef,
                   int adamw_mode) {
  const float bc1 = 1.0f - powf(beta1, (float)step);
  const float bc2 = 1.0f - powf(beta2, (float)step);
  const float inv_bc1 = 1.0f / bc1;
  const float inv_bc2 = 1.0f / bc2;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float gi = g[i] * grad_coef;
    if (!adamw_mode && weight_decay != 0.0f) gi += weight_decay * p[i];
    float mi = beta1 * m[i] + (1.0f - beta1) * gi;
    float vi = beta2 * v[i] + (1.0f - beta2) * gi * gi;
    m[i] = mi;
    v[i] = vi;
    float upd = (mi * inv_bc1) / (sqrtf(vi * inv_bc2) + eps);
    if (adamw_mode && weight_decay != 0.0f) upd += weight_decay * p[i];
    p[i] -= lr * upd;
  }
}

/* Same step but consuming bfloat16 gradients as produced on-device (ZeRO-
 * Offload ships compute-dtype gradients over the host link at half the
 * bytes; reference stage_1_and_2.py:1031 similarly accumulates fp16 grads
 * into fp32 on the host). */
void ds_adamw_step_bf16g(float *p, float *m, float *v, const uint16_t *g,
                         int64_t n, float lr, float beta1, float beta2,
                         float eps, float weight_decay, int64_t step,
                         float grad_coef, int adamw_mode) {
  const float bc1 = 1.0f - powf(beta1, (float)step);
  const float bc2 = 1.0f - powf(beta2, (float)step);
  const float inv_bc1 = 1.0f / bc1;
  const float inv_bc2 = 1.0f / bc2;
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    uint32_t u = ((uint32_t)g[i]) << 16;
    float gf;
    memcpy(&gf, &u, 4);
    float gi = gf * grad_coef;
    if (!adamw_mode && weight_decay != 0.0f) gi += weight_decay * p[i];
    float mi = beta1 * m[i] + (1.0f - beta1) * gi;
    float vi = beta2 * v[i] + (1.0f - beta2) * gi * gi;
    m[i] = mi;
    v[i] = vi;
    float upd = (mi * inv_bc1) / (sqrtf(vi * inv_bc2) + eps);
    if (adamw_mode && weight_decay != 0.0f) upd += weight_decay * p[i];
    p[i] -= lr * upd;
  }
}

/* fp32 -> bf16 with round-to-nearest-even: the device compute copy pushed
 * back after the host step (reference equivalent: the f32->f16 param-copy
 * kernel csrc/common/custom_cuda_kernel.cu). */
void ds_f32_to_bf16(const float *src, uint16_t *dst, int64_t n) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    uint32_t u;
    memcpy(&u, &src[i], 4);
    if ((u & 0x7fffffffu) > 0x7f800000u) { /* NaN: keep quiet, drop payload */
      dst[i] = (uint16_t)((u >> 16) | 0x0040);
    } else {
      uint32_t rounded = u + 0x7fffu + ((u >> 16) & 1u);
      dst[i] = (uint16_t)(rounded >> 16);
    }
  }
}

/* Host-side Adagrad (reference csrc/adagrad/cpu_adagrad.cpp). */
void ds_adagrad_step(float *p, float *acc, const float *g, int64_t n,
                     float lr, float eps, float weight_decay,
                     float grad_coef) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    float gi = g[i] * grad_coef;
    if (weight_decay != 0.0f) gi += weight_decay * p[i];
    float ai = acc[i] + gi * gi;
    acc[i] = ai;
    p[i] -= lr * gi / (sqrtf(ai) + eps);
  }
}
