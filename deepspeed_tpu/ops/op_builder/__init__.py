"""Op builders: discoverable native/kernel op surface.

Counterpart of reference ``op_builder/`` (``OpBuilder`` :94 with its CUDA
arch probing, JIT nvcc builds and ``.load()`` import protocol). The TPU
build matrix is radically simpler — Pallas kernels compile through XLA at
trace time and the two native host ops AOT-compile with one cached ``cc``
invocation — so a builder here resolves to (a) a compatibility probe and
(b) the already-importable module. The ``.load()`` protocol and builder
names are kept so reference code like
``deepspeed.ops.op_builder.CPUAdamBuilder().load()`` ports unchanged.
"""

import importlib


class OpBuilder:
    """name + module path + availability probe."""

    NAME = "base"
    MODULE = None

    def absolute_name(self):
        return self.MODULE

    def is_compatible(self, verbose=False):
        try:
            self.load()
            return True
        except Exception:
            return False

    def load(self, verbose=False):
        mod = importlib.import_module(self.MODULE)
        probe = getattr(mod, self.PROBE, None) if hasattr(self, "PROBE") else None
        if probe is not None and not probe():
            raise RuntimeError(f"{self.NAME}: native build unavailable")
        return mod

    def builder_name(self):
        return type(self).__name__


class CPUAdamBuilder(OpBuilder):
    NAME = "cpu_adam"
    MODULE = "deepspeed_tpu.ops.adam.cpu_adam"
    PROBE = "cpu_adam_available"


class CPUAdagradBuilder(OpBuilder):
    NAME = "cpu_adagrad"
    MODULE = "deepspeed_tpu.ops.adam.cpu_adam"  # shared native lib (ds_adagrad_step)
    PROBE = "cpu_adam_available"


class AsyncIOBuilder(OpBuilder):
    NAME = "async_io"
    MODULE = "deepspeed_tpu.ops.aio"
    PROBE = "aio_available"


class QuantizerBuilder(OpBuilder):
    NAME = "quantizer"
    MODULE = "deepspeed_tpu.ops.quantizer"


class FlashAttnBuilder(OpBuilder):
    NAME = "flash_attn"
    MODULE = "deepspeed_tpu.ops.pallas.flash_attention"


class InferenceBuilder(OpBuilder):
    """Decode-attention + quantized-matmul serving kernels."""
    NAME = "transformer_inference"
    MODULE = "deepspeed_tpu.ops.pallas.decode_attention"


class SparseAttnBuilder(OpBuilder):
    NAME = "sparse_attn"
    MODULE = "deepspeed_tpu.ops.sparse_attention"


class RandomLTDBuilder(OpBuilder):
    NAME = "random_ltd"
    MODULE = "deepspeed_tpu.runtime.data_pipeline.data_routing"


ALL_OPS = {
    b.NAME: b for b in (CPUAdamBuilder(), CPUAdagradBuilder(), AsyncIOBuilder(),
                        QuantizerBuilder(), FlashAttnBuilder(), InferenceBuilder(),
                        SparseAttnBuilder(), RandomLTDBuilder())
}


def get_default_compute_capabilities():
    """Reference API shape; on TPU the 'capability' is the platform kind."""
    import jax
    kinds = sorted({d.device_kind for d in jax.devices()})
    return ";".join(kinds)
