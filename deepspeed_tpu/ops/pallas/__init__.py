"""Pallas TPU kernels.

Shared compat: jax renamed ``pltpu.TPUCompilerParams`` to
``CompilerParams`` around 0.5 — kernels import the alias from here so the
version shim can't drift between files.
"""

from jax.experimental.pallas import tpu as _pltpu

CompilerParams = getattr(_pltpu, "CompilerParams", None) or _pltpu.TPUCompilerParams
