"""Pallas TPU kernels.

Shared compat: jax renamed ``pltpu.TPUCompilerParams`` to
``CompilerParams`` around 0.5 — kernels import the alias from here so the
version shim can't drift between files. ``shard_map_compat`` papers over
the ``jax.experimental.shard_map`` (0.4.x: ``check_rep``/``auto``) →
``jax.shard_map`` (``check_vma``/``axis_names``) API move the same way.
"""

import jax as _jax
from jax.experimental.pallas import tpu as _pltpu

CompilerParams = getattr(_pltpu, "CompilerParams", None) or _pltpu.TPUCompilerParams


def shard_map_compat(fn, mesh, in_specs, out_specs, manual_axes=None):
    """Version-tolerant shard_map: replication checking off (pallas_call
    outputs carry no vma/rep annotations), manual only over
    ``manual_axes`` (None = every mesh axis)."""
    if hasattr(_jax, "shard_map"):
        kw = {"check_vma": False}
        if manual_axes is not None:
            kw["axis_names"] = set(manual_axes)
        return _jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _sm

    def call(*args):
        kw = {"check_rep": False}
        if manual_axes is not None:
            auto = frozenset(mesh.axis_names) - frozenset(manual_axes)
            if auto:
                kw["auto"] = auto
        try:
            return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                       **kw)(*args)
        except NotImplementedError:
            # 0.4.x partial-auto shard_map is unimplemented for most mixes;
            # full-manual is equivalent for these kernel bodies (no inner
            # collectives over the would-be-auto axes — unmentioned spec
            # axes just replicate)
            return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                       check_rep=False)(*args)
    return call
