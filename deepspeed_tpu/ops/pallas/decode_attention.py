"""Pallas single-token decode attention (TPU).

TPU-native equivalent of the reference's fused KV-cache decode attention
(``softmax_context_*`` ops, csrc/transformer/inference/csrc/pt_binding.cpp:1745
-1805, and the softmax/attention kernels behind them): one query token per
sequence attends over a preallocated contiguous KV cache.

GQA-native: the cache keeps ``kv_heads`` heads and each program computes the
whole group of query heads sharing one KV head — no ``jnp.repeat`` expansion
of the cache. Grid is (B, kv_heads); K/V arrive as contiguous (S, D) slabs
per program (cache layout (B, kv_heads, S, D)), and an online-softmax
``fori_loop`` walks KV blocks, stopping at the cache write head (``end``) so
compute scales with the live context length.

Per-row window [start_i, end): ``start`` masks left-padding slots of batched
generation; ``end`` is the shared write head (prompts are left-aligned to a
common end by the inference engine).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _interpret():
    return jax.default_backend() == "cpu"


def _decode_kernel(start_ref, end_ref, q_ref, k_ref, v_ref, o_ref, *, scale, block_kv):
    b = pl.program_id(0)
    start = start_ref[b]
    end = end_ref[0]

    g = q_ref.shape[2]
    d = q_ref.shape[-1]
    q = q_ref[0, 0].astype(jnp.float32) * scale  # (G, D)

    m = jnp.full((g, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((g, 1), jnp.float32)
    acc = jnp.zeros((g, d), jnp.float32)

    num_blocks = pl.cdiv(end, block_kv)

    def body(j, carry):
        m, l, acc = carry
        kv_start = j * block_kv
        k = k_ref[0, 0, pl.ds(kv_start, block_kv), :].astype(jnp.float32)  # (bkv, D)
        v = v_ref[0, 0, pl.ds(kv_start, block_kv), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32)  # (G, bkv)
        kv_pos = kv_start + jax.lax.broadcasted_iota(jnp.int32, (g, block_kv), 1)
        mask = (kv_pos >= start) & (kv_pos < end)
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(p, v, (((1, ), (0, )), ((), ())),
                                                preferred_element_type=jnp.float32)
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, num_blocks, body, (m, l, acc))
    l_safe = jnp.where(l == 0, 1.0, l)
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)


def decode_attention(q, k_cache, v_cache, start, end, *, block_kv=256, scale=None):
    """q: (B, H, D) one query token per sequence; k_cache/v_cache:
    (B, kv_heads, S, D); start: (B,) int32 first attendable cache slot per
    row; end: scalar int32, one past the last written slot (shared).
    Returns (B, H, D)."""
    B, H, D = q.shape
    nkv, S = k_cache.shape[1], k_cache.shape[2]
    g = H // nkv
    scale = scale if scale is not None else 1.0 / (D**0.5)
    block_kv = min(block_kv, S)
    if S % block_kv:
        raise ValueError(f"cache length {S} must be a multiple of block_kv={block_kv}")

    qg = q.reshape(B, nkv, g, D)
    start = start.astype(jnp.int32)
    end = jnp.full((1, ), end, jnp.int32)

    kernel = functools.partial(_decode_kernel, scale=scale, block_kv=block_kv)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, nkv),
            in_specs=[
                pl.BlockSpec((1, 1, g, D), lambda b, h, *_: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, S, D), lambda b, h, *_: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, S, D), lambda b, h, *_: (b, h, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, g, D), lambda b, h, *_: (b, h, 0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((B, nkv, g, D), q.dtype),
        interpret=_interpret(),
    )(start, end, qg, k_cache, v_cache)
    return out.reshape(B, H, D)
