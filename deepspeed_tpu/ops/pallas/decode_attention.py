"""Pallas single-token decode attention (TPU).

TPU-native equivalent of the reference's fused KV-cache decode attention
(``softmax_context_*`` ops, csrc/transformer/inference/csrc/pt_binding.cpp:1745
-1805, and the softmax/attention kernels behind them): one query token per
sequence attends over a preallocated contiguous KV cache.

GQA-native: the cache keeps ``kv_heads`` heads and each program computes
whole groups of query heads sharing one KV head — no ``jnp.repeat``
expansion of the cache.

Kernel shape (v2): ALL (batch, kv_head) pairs fold into ONE batched dot per
grid step, and the grid walks KV blocks. The v1 design ran a (B, kv_heads)
program grid — 160 programs of (S, D)=32KB slabs at gpt2-large decode —
whose per-program fixed costs dominated: measured 77us/call vs ~20us for
this layout (the decode step is issued once per LAYER, so kernel fixed
costs multiply by depth). Blocks past the write head are skipped: the
index map clamps to the last live block (no re-DMA) and ``pl.when`` skips
the compute, so work scales with the live context length.

Per-row window [start_i, end_i): ``start`` masks left-padding slots of batched
generation; ``end`` is the write head. Two entry points share one kernel:

- :func:`decode_attention` — shared scalar ``end`` (the static-batch engine
  path: prompts are left-aligned to a common write head).
- :func:`paged_decode_attention` — per-row ``ends`` (the continuous-batching
  slot pool: every slot sits at its own sequence position, so each row
  attends its own live window). Blocks past the LONGEST live row are
  skipped, so a mostly-short batch still pays only for its max context.
- :func:`paged_span_attention` — per-row QUERY SPANS of ``T`` columns
  (chunked prefill fused into the decode step: decode rows carry one live
  query, the in-flight prefill row carries up to a chunk of them). Query
  column ``j`` of row ``i`` sits at absolute position ``base_i + j`` and
  attends ``[start_i, base_i + j]``; the span fold reuses the same kernel
  with the query columns folded into the head-group axis and a per-column
  offset added to the causal end.

Long-context extensions (multi-extent paged KV + seq-parallel prefill):

- :func:`extent_paged_decode_attention` / :func:`extent_paged_span_attention`
  — one request's KV spans SEVERAL pool slots ("extents") through a per-row
  extent table: logical position ``p`` of row ``i`` lives at physical pool
  row ``ext[i, p // S]``, offset ``p % S``. The kernel walks LOGICAL blocks
  (grid ``E * S/block_kv``) and gathers each row's physical slot for the
  current extent in-register, so the extent count stays an OPERAND (table
  values), never a shape — the O(1)-compiled-programs guard holds across
  any extent mix. With an identity table (``ext[i, 0] == i``) the math is
  bit-identical to the plain paged kernels row for row. Optional per-row
  ``sink``/``window`` operands add attention-sink + sliding-window masking
  (the LOSSY long-context mode — rows with ``window == 0`` keep the exact
  mask, so lossy and exact rows co-reside in one dispatch).
- :func:`seq_sharded_span_attention` — the span kernel shard_mapped over
  the SEQUENCE mesh axis: a wide seq-parallel prefill chunk splits its
  query columns across seq shards (shard ``s`` computes columns
  ``[s*Tl, (s+1)*Tl)`` with its causal base advanced by ``s*Tl``); per-row
  softmax is per COLUMN, so the gathered output is bit-identical to the
  unsharded span call.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import CompilerParams as _CompilerParams

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _interpret():
    return jax.default_backend() == "cpu"


def _decode_kernel(start_ref, end_ref, max_end_ref, q_ref, k_ref, v_ref, *rest,
                   scale, block_kv, B, nkv, g, D, span=1, quantized=False):
    """``g`` is the FOLDED query axis: head-groups x span columns. With
    ``span > 1`` the per-row ``end`` is the causal end of column 0 and each
    later column's window extends by its offset (column j of a row attends
    one more key than column j-1 — per-row mixed decode/prefill query
    spans share this one kernel).

    ``quantized``: the KV blocks are int8 with per-token-row scales (two
    extra (B, block_kv) scale operands); dequantization is the in-register
    multiply below — the bf16/f32 KV never exists in HBM, so the block
    walk's DMA bytes stay int8-sized."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_s, l_s, acc_s = rest
    else:
        o_ref, m_s, l_s, acc_s = rest
    j = pl.program_id(0)
    nj = pl.num_programs(0)
    max_end = max_end_ref[0]
    BH = B * nkv

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, -jnp.inf)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    kv_start = j * block_kv

    @pl.when(kv_start < max_end)
    def _block():
        q = q_ref[...].astype(jnp.float32).reshape(BH, g, D) * scale
        k = k_ref[...].astype(jnp.float32)  # (B, nkv, bkv, D)
        v = v_ref[...].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[...].astype(jnp.float32)[:, None, :, None]
            v = v * vs_ref[...].astype(jnp.float32)[:, None, :, None]
        k = k.reshape(BH, block_kv, D)
        v = v.reshape(BH, block_kv, D)
        s = jax.lax.dot_general(q, k, (((2, ), (2, )), ((0, ), (0, ))),
                                preferred_element_type=jnp.float32)  # (BH, g, bkv)
        # masking in 2-D folded form: Mosaic rejects lane-dim-1 vector
        # reshapes, so per-row starts/ends become full (rows, bkv) fills
        s2 = s.reshape(BH * g, block_kv)
        kv_pos = kv_start + jax.lax.broadcasted_iota(jnp.int32, (BH * g, block_kv), 1)
        start2d = jnp.concatenate(
            [jnp.full((nkv * g, block_kv), start_ref[i], jnp.int32) for i in range(B)])
        end2d = jnp.concatenate(
            [jnp.full((nkv * g, block_kv), end_ref[i], jnp.int32) for i in range(B)])
        if span > 1:
            # folded rows cycle through span columns fastest: column j of a
            # row sits j positions later, so its causal end advances by j
            col = jax.lax.broadcasted_iota(jnp.int32, (BH * g, block_kv), 0) % span
            end2d = end2d + col
        mask = (kv_pos >= start2d) & (kv_pos < end2d)
        s2 = jnp.where(mask, s2, DEFAULT_MASK_VALUE)

        m_prev = m_s[...].reshape(BH * g, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s2, axis=1, keepdims=True))
        p = jnp.exp(s2 - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_s[...] = (l_s[...].reshape(BH * g, 1) * alpha
                    + jnp.sum(p, axis=1, keepdims=True)).reshape(BH, g)
        pv = jax.lax.dot_general(p.reshape(BH, g, block_kv), v,
                                 (((2, ), (1, )), ((0, ), (0, ))),
                                 preferred_element_type=jnp.float32)  # (BH, g, D)
        acc3 = acc_s[...].reshape(BH, g, D)
        acc_s[...] = (acc3 * alpha.reshape(BH, g)[:, :, None] + pv).reshape(BH, g * D)
        m_s[...] = m_new.reshape(BH, g)

    @pl.when(j == nj - 1)
    def _flush():
        l = l_s[...].reshape(BH, g)
        l = jnp.where(l == 0, 1.0, l)
        out = acc_s[...].reshape(BH, g, D) / l[:, :, None]
        o_ref[...] = out.reshape(B, nkv, g, D).astype(o_ref.dtype)


def _decode_call(qg, k_cache, v_cache, start, ends, max_end, *, block_kv, scale,
                 span=1, k_scale=None, v_scale=None):
    """Shared pallas_call builder: per-row windows [start_i, ends_i), with
    ``max_end`` (scalar) bounding the walked KV blocks. ``qg``: queries
    pre-folded to (B, nkv, g, D) where ``g`` = head-groups x ``span``
    columns (span fastest). ``k_scale``/``v_scale``: optional (B, S)
    per-token-row dequant scales for int8 caches (walked in lockstep with
    the KV blocks; the lane axis is S, so scale blocks stay lane-aligned)."""
    B, nkv, g, D = qg.shape
    S = k_cache.shape[2]
    scale = scale if scale is not None else 1.0 / (D**0.5)
    block_kv = min(block_kv, S)
    if S % block_kv:
        raise ValueError(f"cache length {S} must be a multiple of block_kv={block_kv}")
    quantized = k_scale is not None

    start = start.astype(jnp.int32)
    ends = ends.astype(jnp.int32)
    max_end_arr = jnp.full((1, ), max_end, jnp.int32)
    nj = S // block_kv

    def kv_index(j, start_r, end_r, max_end_r):
        # clamp to the last block holding live keys (of the LONGEST row):
        # skipped steps keep the previous index so no extra DMA is issued
        last = jnp.maximum(max_end_r[0] - 1, 0) // block_kv
        return (0, 0, jnp.minimum(j, last), 0)

    def sc_index(j, start_r, end_r, max_end_r):
        last = jnp.maximum(max_end_r[0] - 1, 0) // block_kv
        return (0, jnp.minimum(j, last))

    in_specs = [
        pl.BlockSpec((B, nkv, g, D), lambda j, *_: (0, 0, 0, 0)),
        pl.BlockSpec((B, nkv, block_kv, D), kv_index),
        pl.BlockSpec((B, nkv, block_kv, D), kv_index),
    ]
    operands = [qg, k_cache, v_cache]
    if quantized:
        in_specs += [pl.BlockSpec((B, block_kv), sc_index)] * 2
        operands += [k_scale, v_scale]

    kernel = functools.partial(_decode_kernel, scale=scale, block_kv=block_kv,
                               B=B, nkv=nkv, g=g, D=D, span=span,
                               quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(nj, ),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((B, nkv, g, D), lambda j, *_: (0, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((B * nkv, g), jnp.float32),      # running max
                pltpu.VMEM((B * nkv, g), jnp.float32),      # running denom
                pltpu.VMEM((B * nkv, g * D), jnp.float32),  # running numerator
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, nkv, g, D), qg.dtype),
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary", )),
        interpret=_interpret(),
    )(start, ends, max_end_arr, *operands)
    return out


def _extent_kernel(ext_ref, start_ref, end_ref, max_end_ref, sink_ref, win_ref,
                   q_ref, k_ref, v_ref, *rest, scale, block_kv, B, E, nkv, g, D,
                   bpe, span=1, quantized=False):
    """Multi-extent variant of :func:`_decode_kernel`: the KV walk is over
    LOGICAL blocks — grid step ``j`` covers logical positions
    ``[j*block_kv, (j+1)*block_kv)``, which live in extent ``j // bpe`` at
    within-slot offset ``j % bpe``. The KV block spec streams the FULL pool
    column at that offset and each row gathers its own extent's slot
    (``ext_ref[i*E + e]``) in-register; windows, masks, and the span offset
    all stay in logical coordinates, so with an identity extent table every
    arithmetic op matches :func:`_decode_kernel` value for value.

    ``sink_ref``/``win_ref``: per-row lossy knobs — a row with ``win > 0``
    additionally masks logical positions in ``[sink, end - win)`` (keeps
    the attention-sink head and the sliding recent window; StreamingLLM
    shape). ``win == 0`` leaves the exact mask bit-untouched, so lossy and
    exact rows share one compiled program."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_s, l_s, acc_s = rest
    else:
        o_ref, m_s, l_s, acc_s = rest
    j = pl.program_id(0)
    nj = pl.num_programs(0)
    max_end = max_end_ref[0]
    BH = B * nkv

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, -jnp.inf)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    kv_start = j * block_kv  # LOGICAL position of this block's first key

    @pl.when(kv_start < max_end)
    def _block():
        e = j // bpe  # extent index of this logical block
        q = q_ref[...].astype(jnp.float32).reshape(BH, g, D) * scale
        # per-row physical slot for extent e; demoted/unreserved extents
        # carry -1 — clamp for a safe (masked-out) gather
        slots = jnp.stack([jnp.maximum(ext_ref[i * E + e], 0) for i in range(B)])
        k = jnp.take(k_ref[...], slots, axis=0).astype(jnp.float32)  # (B, nkv, bkv, D)
        v = jnp.take(v_ref[...], slots, axis=0).astype(jnp.float32)
        if quantized:
            ks = jnp.take(ks_ref[...], slots, axis=0).astype(jnp.float32)
            vs = jnp.take(vs_ref[...], slots, axis=0).astype(jnp.float32)
            k = k * ks[:, None, :, None]
            v = v * vs[:, None, :, None]
        k = k.reshape(BH, block_kv, D)
        v = v.reshape(BH, block_kv, D)
        s = jax.lax.dot_general(q, k, (((2, ), (2, )), ((0, ), (0, ))),
                                preferred_element_type=jnp.float32)  # (BH, g, bkv)
        s2 = s.reshape(BH * g, block_kv)
        kv_pos = kv_start + jax.lax.broadcasted_iota(jnp.int32, (BH * g, block_kv), 1)
        start2d = jnp.concatenate(
            [jnp.full((nkv * g, block_kv), start_ref[i], jnp.int32) for i in range(B)])
        end2d = jnp.concatenate(
            [jnp.full((nkv * g, block_kv), end_ref[i], jnp.int32) for i in range(B)])
        if span > 1:
            col = jax.lax.broadcasted_iota(jnp.int32, (BH * g, block_kv), 0) % span
            end2d = end2d + col
        mask = (kv_pos >= start2d) & (kv_pos < end2d)
        sink2d = jnp.concatenate(
            [jnp.full((nkv * g, block_kv), sink_ref[i], jnp.int32) for i in range(B)])
        win2d = jnp.concatenate(
            [jnp.full((nkv * g, block_kv), win_ref[i], jnp.int32) for i in range(B)])
        keep = (win2d == 0) | (kv_pos < sink2d) | (kv_pos >= end2d - win2d)
        mask = mask & keep
        s2 = jnp.where(mask, s2, DEFAULT_MASK_VALUE)

        m_prev = m_s[...].reshape(BH * g, 1)
        m_new = jnp.maximum(m_prev, jnp.max(s2, axis=1, keepdims=True))
        p = jnp.exp(s2 - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_s[...] = (l_s[...].reshape(BH * g, 1) * alpha
                    + jnp.sum(p, axis=1, keepdims=True)).reshape(BH, g)
        pv = jax.lax.dot_general(p.reshape(BH, g, block_kv), v,
                                 (((2, ), (1, )), ((0, ), (0, ))),
                                 preferred_element_type=jnp.float32)  # (BH, g, D)
        acc3 = acc_s[...].reshape(BH, g, D)
        acc_s[...] = (acc3 * alpha.reshape(BH, g)[:, :, None] + pv).reshape(BH, g * D)
        m_s[...] = m_new.reshape(BH, g)

    @pl.when(j == nj - 1)
    def _flush():
        l = l_s[...].reshape(BH, g)
        l = jnp.where(l == 0, 1.0, l)
        out = acc_s[...].reshape(BH, g, D) / l[:, :, None]
        o_ref[...] = out.reshape(B, nkv, g, D).astype(o_ref.dtype)


def _extent_call(qg, k_cache, v_cache, start, ends, max_end, ext, sink, win, *,
                 block_kv, scale, span=1, k_scale=None, v_scale=None):
    """pallas_call builder for the multi-extent kernel. ``ext``: (B, E)
    int32 per-row extent chains — physical pool slot of each S-row extent,
    -1 for unreserved/demoted entries. ``start``/``ends``/``max_end`` are
    LOGICAL positions (max ``E * S``). ``sink``/``win``: optional (B,)
    int32 lossy-mode knobs (None → zeros → exact masking). ``k_scale``/
    ``v_scale``: optional (Npool, S) per-token-row dequant scales covering
    the FULL pool (the kernel gathers scale rows with the KV rows).

    The walked-bytes tradeoff vs :func:`_decode_call`: each logical block
    streams the whole pool column (Npool rows) so rows can gather any slot
    — in serving the dispatch batch IS the pool (B == Npool), so per-block
    DMA matches the plain kernel and the extra cost is the E-fold longer
    logical walk, priced by ``CapacityModel.dispatch_cost``."""
    B, nkv, g, D = qg.shape
    Np, nkv_c, S, Dc = k_cache.shape
    E = ext.shape[1]
    scale = scale if scale is not None else 1.0 / (D**0.5)
    block_kv = min(block_kv, S)
    if S % block_kv:
        raise ValueError(f"cache length {S} must be a multiple of block_kv={block_kv}")
    quantized = k_scale is not None
    bpe = S // block_kv
    nj = E * bpe

    ext_flat = ext.reshape(B * E).astype(jnp.int32)
    start = start.astype(jnp.int32)
    ends = ends.astype(jnp.int32)
    max_end_arr = jnp.full((1, ), max_end, jnp.int32)
    sink = (jnp.zeros((B, ), jnp.int32) if sink is None
            else sink.astype(jnp.int32))
    win = (jnp.zeros((B, ), jnp.int32) if win is None
           else win.astype(jnp.int32))

    def kv_index(j, ext_r, start_r, end_r, max_end_r, sink_r, win_r):
        # clamp to the last LIVE logical block; skipped steps keep the
        # previous index so no extra DMA is issued
        last = jnp.maximum(max_end_r[0] - 1, 0) // block_kv
        return (0, 0, jnp.minimum(j, last) % bpe, 0)

    def sc_index(j, ext_r, start_r, end_r, max_end_r, sink_r, win_r):
        last = jnp.maximum(max_end_r[0] - 1, 0) // block_kv
        return (0, jnp.minimum(j, last) % bpe)

    in_specs = [
        pl.BlockSpec((B, nkv, g, D), lambda j, *_: (0, 0, 0, 0)),
        pl.BlockSpec((Np, nkv, block_kv, D), kv_index),
        pl.BlockSpec((Np, nkv, block_kv, D), kv_index),
    ]
    operands = [qg, k_cache, v_cache]
    if quantized:
        in_specs += [pl.BlockSpec((Np, block_kv), sc_index)] * 2
        operands += [k_scale, v_scale]

    kernel = functools.partial(_extent_kernel, scale=scale, block_kv=block_kv,
                               B=B, E=E, nkv=nkv, g=g, D=D, bpe=bpe, span=span,
                               quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=6,
            grid=(nj, ),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((B, nkv, g, D), lambda j, *_: (0, 0, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((B * nkv, g), jnp.float32),      # running max
                pltpu.VMEM((B * nkv, g), jnp.float32),      # running denom
                pltpu.VMEM((B * nkv, g * D), jnp.float32),  # running numerator
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, nkv, g, D), qg.dtype),
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary", )),
        interpret=_interpret(),
    )(ext_flat, start, ends, max_end_arr, sink, win, *operands)
    return out


def _group(q, nkv):
    B, H, D = q.shape
    return q.reshape(B, nkv, H // nkv, D)


def decode_attention(q, k_cache, v_cache, start, end, *, block_kv=256, scale=None):
    """q: (B, H, D) one query token per sequence; k_cache/v_cache:
    (B, kv_heads, S, D); start: (B,) int32 first attendable cache slot per
    row; end: scalar int32, one past the last written slot (shared).
    Returns (B, H, D)."""
    B, H, D = q.shape
    ends = jnp.full((B, ), end, jnp.int32)
    out = _decode_call(_group(q, k_cache.shape[1]), k_cache, v_cache, start, ends,
                       end, block_kv=block_kv, scale=scale)
    return out.reshape(B, H, D)


def _row_scales(k_scale, v_scale, B, S):
    """(B, 1, S, 1) stored per-token-row scale leaves -> the (B, S) layout
    the kernel walks (lane axis = S, so scale blocks stay lane-aligned)."""
    if k_scale is None:
        return None, None
    return k_scale.reshape(B, S), v_scale.reshape(B, S)


def _tp_shard_map(fn, mesh, axis, q_ndim, quantized, n_rep=3):
    """shard_map wrapper for the paged kernels over the ``axis`` (tensor)
    mesh dim: q and the KV cache split on their HEAD axes, window scalars
    and the per-token-row scale leaves stay replicated. Each shard's kernel
    then walks ONLY its local KV-head blocks (shard-local block walk — DMA
    and compute scale down tp-fold), and because every (batch, kv-head)
    pair is computed independently by the same kernel, the gathered output
    is BIT-identical to the unsharded call. ``n_rep``: replicated operands
    following (q, k, v) — 3 for the plain window scalars, 6 for the extent
    variants (ext table + sink/window knobs ride along replicated)."""
    from jax.sharding import PartitionSpec as SP
    from . import shard_map_compat
    head_q = SP(*(None, axis) + (None, ) * (q_ndim - 2))
    head_c = SP(None, axis, None, None)
    rep = SP()
    in_specs = [head_q, head_c, head_c] + [rep] * n_rep
    if quantized:
        in_specs += [rep, rep]
    return shard_map_compat(fn, mesh, tuple(in_specs), head_q)


def sharded_paged_decode_attention(q, k_cache, v_cache, start, ends, *, mesh,
                                   axis, block_kv=256, scale=None,
                                   k_scale=None, v_scale=None):
    """:func:`paged_decode_attention` shard_mapped over the ``axis`` mesh
    dim (tensor-parallel serving): the KV pool stays head-sharded in HBM
    and each shard walks only its local heads' blocks. Bit-identical to the
    unsharded call (per-head independence). ``k_cache.shape[1]`` (and the
    query head count) must divide by the axis size."""
    B, H, D = q.shape
    ends = ends.astype(jnp.int32)
    ks, vs = _row_scales(k_scale, v_scale, B, k_cache.shape[2])
    max_end = jnp.max(ends)

    def body(qg, kc, vc, st, en, me, *scales):
        kss, vss = scales if scales else (None, None)
        return _decode_call(qg, kc, vc, st, en, me[0], block_kv=block_kv,
                            scale=scale, k_scale=kss, v_scale=vss)

    out = _tp_shard_map(body, mesh, axis, 4, ks is not None)(
        *((_group(q, k_cache.shape[1]), k_cache, v_cache,
           start.astype(jnp.int32), ends, max_end[None])
          + ((ks, vs) if ks is not None else ())))
    return out.reshape(B, H, D)


def sharded_paged_span_attention(q, k_cache, v_cache, start, base, *, mesh,
                                 axis, block_kv=256, scale=None,
                                 k_scale=None, v_scale=None):
    """:func:`paged_span_attention` shard_mapped over the ``axis`` mesh dim
    — the fused chunked-prefill/decode (and speculative verify) step's
    kernel with a shard-local block walk. q: (B, H, T, D); the head axis
    (and the cache's kv-head axis) must divide by the axis size. The
    (head-group, column) fold happens INSIDE each shard, so per-column
    causal offsets see only local heads and results stay bit-identical."""
    B, H, T, D = q.shape
    nkv = k_cache.shape[1]
    base = base.astype(jnp.int32)
    ks, vs = _row_scales(k_scale, v_scale, B, k_cache.shape[2])
    max_end = jnp.max(base) + T
    g = H // nkv

    def body(qs, kc, vc, st, bs, me, *scales):
        nkv_l = kc.shape[1]
        qf = qs.reshape(B, nkv_l, g * T, D)
        kss, vss = scales if scales else (None, None)
        out = _decode_call(qf, kc, vc, st, bs + 1, me[0], block_kv=block_kv,
                           scale=scale, span=T, k_scale=kss, v_scale=vss)
        return out.reshape(B, nkv_l * g, T, D)

    out = _tp_shard_map(body, mesh, axis, 4, ks is not None)(
        *((q, k_cache, v_cache, start.astype(jnp.int32), base, max_end[None])
          + ((ks, vs) if ks is not None else ())))
    return out.reshape(B, H, T, D)


def paged_decode_attention(q, k_cache, v_cache, start, ends, *, block_kv=256,
                           scale=None, k_scale=None, v_scale=None):
    """Slot-pool variant: per-row ends. q: (B, H, D); k_cache/v_cache:
    (B, kv_heads, S, D) where B indexes cache SLOTS; ``ends``: (B,) int32 one
    past each slot's last written position (rows with ``ends == 0`` attend
    nothing — their output is unspecified; callers mask dead slots).
    The KV-block walk stops at ``max(ends)``, so compute and DMA
    scale with the longest LIVE context, not the pool capacity S.
    ``k_scale``/``v_scale``: optional (B, 1, S, 1) per-token-row dequant
    scales for int8 caches — dequantization fuses into the kernel.
    Returns (B, H, D)."""
    B, H, D = q.shape
    ends = ends.astype(jnp.int32)
    ks, vs = _row_scales(k_scale, v_scale, B, k_cache.shape[2])
    out = _decode_call(_group(q, k_cache.shape[1]), k_cache, v_cache, start, ends,
                       jnp.max(ends), block_kv=block_kv, scale=scale,
                       k_scale=ks, v_scale=vs)
    return out.reshape(B, H, D)


def paged_span_attention(q, k_cache, v_cache, start, base, *, block_kv=256,
                         scale=None, k_scale=None, v_scale=None):
    """Fused chunked-prefill/decode variant: per-row query SPANS. q:
    (B, H, T, D) — row ``i``'s query column ``j`` sits at absolute cache
    position ``base_i + j`` and attends keys in ``[start_i, base_i + j]``
    (its own freshly-written KV included). Decode rows put their one live
    token in column 0; the in-flight prefill row fills up to a chunk; columns
    past a row's live span compute garbage that the caller never reads.
    ``base``: (B,) int32 per-row write heads (== column 0's position).
    ``k_scale``/``v_scale``: optional (B, 1, S, 1) per-token-row dequant
    scales for int8 caches — dequantization fuses into the kernel. The
    KV-block walk stops at ``max(base) + T``. Returns (B, H, T, D)."""
    B, H, T, D = q.shape
    nkv = k_cache.shape[1]
    # fold (head-group, column) into one query axis, column fastest — the
    # kernel recovers the per-column causal offset from ``idx % span``
    qf = q.reshape(B, nkv, (H // nkv) * T, D)
    base = base.astype(jnp.int32)
    ks, vs = _row_scales(k_scale, v_scale, B, k_cache.shape[2])
    out = _decode_call(qf, k_cache, v_cache, start, base + 1, jnp.max(base) + T,
                       block_kv=block_kv, scale=scale, span=T, k_scale=ks,
                       v_scale=vs)
    return out.reshape(B, H, T, D)


# --------------------------------------------------------------------- extents
def extent_paged_decode_attention(q, k_cache, v_cache, start, ends, ext, *,
                                  block_kv=256, scale=None, k_scale=None,
                                  v_scale=None, sink=None, window=None):
    """:func:`paged_decode_attention` over multi-extent KV: row ``i``'s
    logical position ``p`` lives at pool row ``ext[i, p // S]`` offset
    ``p % S``. ``start``/``ends`` are LOGICAL (up to ``E * S``); ``ext`` is
    (B, E) int32 with -1 marking unreserved/demoted extents (which must lie
    entirely outside every attended window — the scheduler's detect-miss-
    and-restore guarantees it in exact mode, the sink/window mask in lossy
    mode). With an identity table this is bit-identical to the plain paged
    kernel row for row. Returns (B, H, D)."""
    B, H, D = q.shape
    Np, nkv, S, _ = k_cache.shape
    ends = ends.astype(jnp.int32)
    ks, vs = _row_scales(k_scale, v_scale, Np, S)
    out = _extent_call(_group(q, nkv), k_cache, v_cache, start.astype(jnp.int32),
                       ends, jnp.max(ends), ext, sink, window,
                       block_kv=block_kv, scale=scale, k_scale=ks, v_scale=vs)
    return out.reshape(B, H, D)


def extent_paged_span_attention(q, k_cache, v_cache, start, base, ext, *,
                                block_kv=256, scale=None, k_scale=None,
                                v_scale=None, sink=None, window=None):
    """:func:`paged_span_attention` over multi-extent KV (the fused chunked-
    prefill/decode step when any live row's context spans pool extents).
    ``base``: (B,) int32 LOGICAL write heads. Returns (B, H, T, D)."""
    B, H, T, D = q.shape
    Np, nkv, S, _ = k_cache.shape
    qf = q.reshape(B, nkv, (H // nkv) * T, D)
    base = base.astype(jnp.int32)
    ks, vs = _row_scales(k_scale, v_scale, Np, S)
    out = _extent_call(qf, k_cache, v_cache, start.astype(jnp.int32), base + 1,
                       jnp.max(base) + T, ext, sink, window, block_kv=block_kv,
                       scale=scale, span=T, k_scale=ks, v_scale=vs)
    return out.reshape(B, H, T, D)


def _lossy_args(B, sink, window):
    return (jnp.zeros((B, ), jnp.int32) if sink is None else sink.astype(jnp.int32),
            jnp.zeros((B, ), jnp.int32) if window is None else window.astype(jnp.int32))


def sharded_extent_paged_decode_attention(q, k_cache, v_cache, start, ends, ext,
                                          *, mesh, axis, block_kv=256,
                                          scale=None, k_scale=None,
                                          v_scale=None, sink=None, window=None):
    """:func:`extent_paged_decode_attention` shard_mapped over the tensor
    mesh axis — head-sharded pool, shard-local LOGICAL block walk, extent
    table replicated. Bit-identical to the unsharded extent call."""
    B, H, D = q.shape
    Np, nkv, S, _ = k_cache.shape
    ends = ends.astype(jnp.int32)
    ks, vs = _row_scales(k_scale, v_scale, Np, S)
    max_end = jnp.max(ends)
    sk, wn = _lossy_args(B, sink, window)

    def body(qg, kc, vc, st, en, me, ex, skr, wnr, *scales):
        kss, vss = scales if scales else (None, None)
        return _extent_call(qg, kc, vc, st, en, me[0], ex, skr, wnr,
                            block_kv=block_kv, scale=scale, k_scale=kss,
                            v_scale=vss)

    out = _tp_shard_map(body, mesh, axis, 4, ks is not None, n_rep=6)(
        *((_group(q, nkv), k_cache, v_cache, start.astype(jnp.int32), ends,
           max_end[None], ext.astype(jnp.int32), sk, wn)
          + ((ks, vs) if ks is not None else ())))
    return out.reshape(B, H, D)


def sharded_extent_paged_span_attention(q, k_cache, v_cache, start, base, ext,
                                        *, mesh, axis, block_kv=256, scale=None,
                                        k_scale=None, v_scale=None, sink=None,
                                        window=None):
    """:func:`extent_paged_span_attention` shard_mapped over the tensor mesh
    axis (fused chunk step with multi-extent rows under bitwise-tp)."""
    B, H, T, D = q.shape
    Np, nkv, S, _ = k_cache.shape
    base = base.astype(jnp.int32)
    ks, vs = _row_scales(k_scale, v_scale, Np, S)
    max_end = jnp.max(base) + T
    g = H // nkv
    sk, wn = _lossy_args(B, sink, window)

    def body(qs, kc, vc, st, bs, me, ex, skr, wnr, *scales):
        nkv_l = kc.shape[1]
        qf = qs.reshape(B, nkv_l, g * T, D)
        kss, vss = scales if scales else (None, None)
        out = _extent_call(qf, kc, vc, st, bs + 1, me[0], ex, skr, wnr,
                           block_kv=block_kv, scale=scale, span=T,
                           k_scale=kss, v_scale=vss)
        return out.reshape(B, nkv_l * g, T, D)

    out = _tp_shard_map(body, mesh, axis, 4, ks is not None, n_rep=6)(
        *((q, k_cache, v_cache, start.astype(jnp.int32), base, max_end[None],
           ext.astype(jnp.int32), sk, wn)
          + ((ks, vs) if ks is not None else ())))
    return out.reshape(B, H, T, D)


# ----------------------------------------------------------- seq-parallel span
def seq_sharded_span_attention(q, k_cache, v_cache, start, base, *, mesh, axis,
                               block_kv=256, scale=None, k_scale=None,
                               v_scale=None, ext=None, sink=None, window=None):
    """Span attention shard_mapped over the SEQUENCE mesh axis: the wide
    seq-parallel prefill chunk splits its ``T`` query columns across the
    ``axis`` shards — shard ``s`` computes columns ``[s*Tl, (s+1)*Tl)``
    against the REPLICATED pool with its causal base advanced by ``s*Tl``
    (``Tl = T / shards``). Every (row, head-group, column) softmax is
    independent and each shard's kernel runs the exact span math of the
    single-shard call at span ``Tl``, so the gathered (B, H, T, D) output
    is bit-identical to :func:`paged_span_attention` column for column.
    ``ext`` switches to the multi-extent walk (long prompts whose earlier
    chunks landed in other extents); tensor sharding does NOT compose here
    — the scheduler gates seq-parallel prefill to tp == 1."""
    from jax.sharding import PartitionSpec as SP
    from . import shard_map_compat
    B, H, T, D = q.shape
    Np, nkv, S, _ = k_cache.shape
    n = mesh.shape[axis]
    if T % n:
        raise ValueError(f"span width {T} must divide by the seq axis size {n}")
    Tl = T // n
    g = H // nkv
    base = base.astype(jnp.int32)
    ks, vs = _row_scales(k_scale, v_scale, Np, S)
    max_end = jnp.max(base) + T
    has_ext = ext is not None
    ext_arr = (ext.astype(jnp.int32) if has_ext
               else jnp.zeros((B, 1), jnp.int32))
    sk, wn = _lossy_args(B, sink, window)

    def body(qs, kc, vc, st, bs, me, ex, skr, wnr, *scales):
        sh = jax.lax.axis_index(axis)
        bl = bs + sh * Tl  # this shard's columns start Tl*sh later
        qf = qs.reshape(B, nkv, g * Tl, D)
        kss, vss = scales if scales else (None, None)
        if has_ext:
            out = _extent_call(qf, kc, vc, st, bl + 1, me[0], ex, skr, wnr,
                               block_kv=block_kv, scale=scale, span=Tl,
                               k_scale=kss, v_scale=vss)
        else:
            out = _decode_call(qf, kc, vc, st, bl + 1, me[0],
                               block_kv=block_kv, scale=scale, span=Tl,
                               k_scale=kss, v_scale=vss)
        return out.reshape(B, H, Tl, D)

    seq_q = SP(None, None, axis, None)
    rep = SP()
    in_specs = [seq_q, rep, rep, rep, rep, rep, rep, rep, rep]
    if ks is not None:
        in_specs += [rep, rep]
    out = shard_map_compat(body, mesh, tuple(in_specs), seq_q)(
        *((q, k_cache, v_cache, start.astype(jnp.int32), base, max_end[None],
           ext_arr, sk, wn) + ((ks, vs) if ks is not None else ())))
    return out.reshape(B, H, T, D)
