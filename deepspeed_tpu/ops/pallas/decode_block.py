"""Fused single-token decode layer (TPU Pallas).

The TPU equivalent of the reference's fused inference pass — ``qkv_gemm ->
softmax_context -> vector_matmul -> mlp_gemm`` (``csrc/transformer/
inference/csrc/pt_binding.cpp:1745-1805`` + ``inference_context.h``'s
workspace): a decode layer runs in THREE resident kernels, with int8
weights streamed block-by-block through the MXU and the layer's
norms/biases/activations/rotary folded in (no XLA glue between
projections).

Why: at decode the step is HBM-bound and the op count is the enemy — the
per-projection path costs ~190 kernel launches + ~340 XLA glue fusions per
token step, whose fixed costs roughly double the ideal weight-streaming
time. This brings a layer to 3 launches + 2 cache-commit
dynamic-update-slices:

    kernel A  norm1(x) folded into the fused [q;k;v] int8 matmul (+bias),
              with RoPE rotation of the q/k head segments on the final step
    kernel B  ``decode_attention`` over the committed KV cache (GQA-native:
              kv_heads may divide num_heads)
    kernel C  o-projection (+bias) -> residual -> norm2 -> up [and gate]
              (+bias, act) -> down (+bias) -> residual -> x_out

Everything inside the kernels stays 2-D (lane dim = feature dim): Mosaic
cannot lane-split ``(B, nh*hd) -> (B, nh, hd)`` in-kernel, so the head
reshape + cache commit happen in XLA where they are free (the HLO audit
shows zero copies in the decode loop body). RoPE needs no head reshape:
the rotation acts on static per-head column segments of the fused
[q;k;v] row, so it folds into kernel A's flush step.

Supported model shape (the engine gates on this): fused int8 qkv weights,
layernorm or rmsnorm norms, sequential residual, gelu/gelu_exact/
quick_gelu/relu MLP or a gated swiglu/geglu MLP (gate and up share the
norm2(x) tiles in kernel C), rope (full rotary only, ``rotary_dim in (0,
head_size)``) / learned / no positional embedding, and grouped KV heads
(``kv_heads`` dividing ``num_heads``). Still gated out: alibi, partial
rotary, local-attention layers, act-quant, MoE — see
``InferenceEngine._fused_decode_eligible`` for the reason strings.
Models without bias params (rmsnorm shapes) pass zero biases; the kernels
are uniform. Quantization groups follow ``CausalLMModel.quantize_params``.
Weight-block scales are applied to the (B, n-block) fp32 partial sums
after each dot — see ``quant_matmul.py`` for the design rationale and
microbenchmarks.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import CompilerParams as _CompilerParams


def _interpret():
    return jax.default_backend() == "cpu"


def _norm(x32, norms_ref, row, kind, eps):
    """Row ``row`` of the (4, H) norms block is the scale, ``row + 1`` the
    bias (a zero row for rmsnorm models, which have no bias param)."""
    scale = norms_ref[row, :][None, :]
    if kind == "rmsnorm":
        ms = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        return x32 * jax.lax.rsqrt(ms + eps) * scale
    bias = norms_ref[row + 1, :][None, :]
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    return (x32 - mu) * jax.lax.rsqrt(var + eps) * scale + bias


def _act(h, kind):
    if kind == "gelu":
        return jax.nn.gelu(h, approximate=True)
    if kind == "gelu_exact":
        return jax.nn.gelu(h, approximate=False)
    if kind == "quick_gelu":
        return h * jax.nn.sigmoid(1.702 * h)
    if kind == "silu":
        return h * jax.nn.sigmoid(h)
    return jnp.maximum(h, 0.0)


def _rope_rotate(y, sin, cos, rot_heads, hd):
    """Rotate the first ``rot_heads`` head segments of the fused [q;k;v]
    row ``y`` (f32 (B, Nqkv)); columns past ``rot_heads * hd`` (the v
    segment) pass through. ``sin``/``cos``: (B, hd // 2) f32 gathered at
    each row's position. Same half-split convention as ``apply_rope``."""
    half = hd // 2
    parts = []
    for i in range(rot_heads):
        off = i * hd
        a = y[:, off:off + half]
        b = y[:, off + half:off + hd]
        parts.append(a * cos - b * sin)
        parts.append(b * cos + a * sin)
    parts.append(y[:, rot_heads * hd:])
    return jnp.concatenate(parts, axis=-1)


def _qdot(x_bf16, w_ref, s_ref, k_idx, bk, gsize, col_off=None):
    """One k-block of an int8 matmul: widen to bf16, dot, scale partials.
    ``k_idx``: which k-block this grid step computes (python int or traced).
    ``col_off``: column offset into the (full-width) scales block when the
    weight block covers only a slice of N. Returns fp32 (B, bn)."""
    w = w_ref[...]
    bn = w.shape[1]
    ng = max(1, bk // gsize)
    span = min(gsize, bk)
    acc = None
    for t in range(ng):
        part = jax.lax.dot_general(
            x_bf16[:, t * span:(t + 1) * span],
            w[t * span:(t + 1) * span, :].astype(x_bf16.dtype),
            (((1, ), (0, )), ((), ())), preferred_element_type=jnp.float32)
        row = (k_idx * bk) // gsize + t
        if col_off is None:
            sl = s_ref[row, :]
        else:
            sl = s_ref[row, pl.ds(col_off, bn)]
        part = part * sl[None, :]
        acc = part if acc is None else acc + part
    return acc


from .quant_matmul import pick_block_k as _pick_bk


def _prep_scales(sc):
    sc = jnp.asarray(sc, jnp.float32)
    G = sc.shape[0]
    Gp = -(-G // 8) * 8
    return (jnp.pad(sc, ((0, Gp - G), (0, 0))) if Gp != G else sc), G


# --------------------------------------------------------------- kernel A
def _qkv_ln_kernel(x_ref, norms_ref, w_ref, s_ref, b_ref, *rest,
                   nk1, bk1, g1, eps, norm_kind, rot_heads, hd):
    if rot_heads:
        sin_ref, cos_ref, o_ref, xln_s, acc_s = rest
    else:
        o_ref, xln_s, acc_s = rest
    s = pl.program_id(0)

    @pl.when(s == 0)
    def _ln1():
        x32 = x_ref[...].astype(jnp.float32)
        xln_s[...] = _norm(x32, norms_ref, 0, norm_kind, eps).astype(x_ref.dtype)

    part = _qdot(xln_s[:, pl.ds(s * bk1, bk1)], w_ref, s_ref, s, bk1, g1)

    @pl.when(s == 0)
    def _init():
        acc_s[...] = part

    @pl.when(s > 0)
    def _acc():
        acc_s[...] += part

    @pl.when(s == nk1 - 1)
    def _done():
        y = acc_s[...] + b_ref[0, :][None, :]
        if rot_heads:
            y = _rope_rotate(y, sin_ref[...], cos_ref[...], rot_heads, hd)
        o_ref[...] = y.astype(o_ref.dtype)


def fused_qkv_ln(x, norms, qkv, *, eps=1e-5, norm="layernorm", rope=None):
    """norm1(x) @ dequant(Wqkv) + bias (+ rope) in one kernel. x: (B, H)
    bf16; norms: (4, H) f32 (rows 0/1 used; bias row is zeros for
    rmsnorm); qkv: (W int8 (H, Nqkv), scales, bias). ``rope``: optional
    ``(sin2d, cos2d, rot_heads, head_dim)`` — (B, head_dim // 2) f32
    tables gathered at each row's position; the first ``rot_heads`` head
    segments (the q and k heads of the fused layout) are rotated on the
    flush step, the v tail passes through. Returns (B, Nqkv) bf16."""
    B, H = x.shape
    w, sc, b = qkv
    Nq = w.shape[1]
    sc, G = _prep_scales(sc)
    g1 = H // G
    bk1 = _pick_bk(H, g1)
    nk1 = H // bk1
    if rope is not None:
        sin2d, cos2d, rot_heads, hd = rope
    else:
        sin2d = cos2d = None
        rot_heads, hd = 0, 0
    kernel = functools.partial(_qkv_ln_kernel, nk1=nk1, bk1=bk1, g1=g1, eps=eps,
                               norm_kind=norm, rot_heads=rot_heads, hd=hd)
    in_specs = [
        pl.BlockSpec((B, H), lambda s: (0, 0)),
        pl.BlockSpec(norms.shape, lambda s: (0, 0)),
        pl.BlockSpec((bk1, Nq), lambda s: (s, 0)),
        pl.BlockSpec(sc.shape, lambda s: (0, 0)),
        pl.BlockSpec((1, Nq), lambda s: (0, 0)),
    ]
    operands = [x, norms, w, sc, b.reshape(1, -1)]
    if rot_heads:
        half = hd // 2
        in_specs += [pl.BlockSpec((B, half), lambda s: (0, 0)),
                     pl.BlockSpec((B, half), lambda s: (0, 0))]
        operands += [jnp.asarray(sin2d, jnp.float32),
                     jnp.asarray(cos2d, jnp.float32)]
    return pl.pallas_call(
        kernel,
        grid=(nk1, ),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((B, Nq), lambda s: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, Nq), x.dtype),
        scratch_shapes=[pltpu.VMEM((B, H), x.dtype), pltpu.VMEM((B, Nq), jnp.float32)],
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary", )),
        interpret=_interpret(),
    )(*operands)


# --------------------------------------------------------------- kernel C
def _out_mlp_kernel(attn_ref, x_ref, norms_ref,
                    o_w, o_s, o_b, up_w, up_s, up_b, *rest,
                    nko, nju, nku, nkd, bko, bk1, bnu, bkd, go, gu, gd,
                    eps, act, norm_kind, gated):
    if gated:
        (gt_w, gt_s, gt_b, dn_w, dn_s, dn_b,
         xo_ref, res2, ln2_s, up_h, g_h, acc_s) = rest
    else:
        dn_w, dn_s, dn_b, xo_ref, res2, ln2_s, up_h, acc_s = rest
        gt_w = gt_s = gt_b = g_h = None
    s = pl.program_id(0)
    A1 = nko
    A2 = A1 + nju * nku

    # ---- o projection + residual ----
    @pl.when(s < A1)
    def _o():
        part = _qdot(attn_ref[:, pl.ds(s * bko, bko)], o_w, o_s, s, bko, go)

        @pl.when(s == 0)
        def _():
            acc_s[...] = part

        @pl.when(s > 0)
        def _():
            acc_s[...] += part

    @pl.when(s == A1 - 1)
    def _o_done():
        r = acc_s[...] + o_b[0, :][None, :] + x_ref[...].astype(jnp.float32)
        res2[...] = r
        ln2_s[...] = _norm(r, norms_ref, 2, norm_kind, eps).astype(ln2_s.dtype)

    # ---- up (and gate) projection + activation ----
    @pl.when((s >= A1) & (s < A2))
    def _up():
        p_ = s - A1
        j, k = p_ // nku, p_ % nku
        xt = ln2_s[:, pl.ds(k * bk1, bk1)]
        part = _qdot(xt, up_w, up_s, k, bk1, gu, col_off=j * bnu)
        gpart = _qdot(xt, gt_w, gt_s, k, bk1, gu, col_off=j * bnu) if gated \
            else None

        def _combine(u, g):
            ub = u + up_b[0, pl.ds(j * bnu, bnu)][None, :]
            if gated:  # gated MLP: act(gate) * up (swiglu / geglu)
                return _act(g + gt_b[0, pl.ds(j * bnu, bnu)][None, :], act) * ub
            return _act(ub, act)

        @pl.when(k == 0)
        def _():
            upd = part
            if nku == 1:  # single k-block: this step completes the column
                upd = _combine(part, gpart)
            elif gated:
                g_h[:, pl.ds(j * bnu, bnu)] = gpart.astype(g_h.dtype)
            up_h[:, pl.ds(j * bnu, bnu)] = upd.astype(up_h.dtype)

        @pl.when(k > 0)
        def _():
            upd = up_h[:, pl.ds(j * bnu, bnu)].astype(jnp.float32) + part
            if nku > 1:  # tracing reaches here only when nku > 1
                gacc = None
                if gated:
                    gacc = g_h[:, pl.ds(j * bnu, bnu)].astype(jnp.float32) + gpart
                    g_h[:, pl.ds(j * bnu, bnu)] = gacc.astype(g_h.dtype)
                upd2 = _combine(upd, gacc)
                upd = jnp.where(k == nku - 1, upd2, upd)
            up_h[:, pl.ds(j * bnu, bnu)] = upd.astype(up_h.dtype)

    # ---- down projection + residual ----
    @pl.when(s >= A2)
    def _down():
        k = s - A2
        part = _qdot(up_h[:, pl.ds(k * bkd, bkd)], dn_w, dn_s, k, bkd, gd)

        @pl.when(k == 0)
        def _():
            acc_s[...] = part

        @pl.when(k > 0)
        def _():
            acc_s[...] += part

    @pl.when(s == pl.num_programs(0) - 1)
    def _finish():
        xo_ref[...] = (res2[...] + acc_s[...] + dn_b[0, :][None, :]).astype(xo_ref.dtype)


def fused_out_mlp(attn2d, x, norms, o, up, down, *, activation="gelu",
                  eps=1e-5, norm="layernorm", gate=None):
    """x + o_proj(attn) -> norm2 -> up [* act(gate)] -> down -> + residual,
    one kernel. attn2d: (B, nh*hd) bf16 flattened attention output; x:
    (B, H) residual stream; norms (4, H) f32 rows 2/3 used; o/up/down (and
    ``gate`` when the MLP is gated): (W int8, scales, bias). For
    ``activation`` in ("swiglu", "geglu") pass ``gate``; the gate
    contraction shares norm2(x)'s k-tiles with up and the activation
    applies to the gate (silu for swiglu, tanh-gelu for geglu), matching
    ``MLP``. Returns x_out (B, H) bf16."""
    B, H = x.shape
    o_w, o_s, o_b = o
    up_w, up_s, up_b = up
    dn_w, dn_s, dn_b = down
    Ko = o_w.shape[0]
    F = up_w.shape[1]
    o_s, Go = _prep_scales(o_s)
    up_s, Gu = _prep_scales(up_s)
    dn_s, Gd = _prep_scales(dn_s)
    go, gu, gd = Ko // Go, H // Gu, F // Gd
    bko = _pick_bk(Ko, go)
    bk1 = _pick_bk(H, gu)
    bkd = _pick_bk(F, gd)
    from .quant_matmul import pick_block
    bnu = pick_block(F, 2560, 128)
    nko, nkd = Ko // bko, F // bkd
    nju, nku = F // bnu, H // bk1
    nsteps = nko + nju * nku + nkd
    A1 = nko

    gated = gate is not None
    act = activation
    if gated:
        act = "silu" if activation == "swiglu" else "gelu"
        gt_w, gt_s, gt_b = gate
        gt_s, Gg = _prep_scales(gt_s)
        assert gt_w.shape == up_w.shape and Gg == Gu, \
            "gate/up projections must share shape and quant grouping"

    kernel = functools.partial(
        _out_mlp_kernel, nko=nko, nju=nju, nku=nku, nkd=nkd,
        bko=bko, bk1=bk1, bnu=bnu, bkd=bkd, go=go, gu=gu, gd=gd,
        eps=eps, act=act, norm_kind=norm, gated=gated)
    f32 = jnp.float32
    up_spec = pl.BlockSpec((bk1, bnu), lambda s: (
        jnp.clip(s - A1, 0, nju * nku - 1) % nku,
        jnp.clip(s - A1, 0, nju * nku - 1) // nku))
    in_specs = [
        pl.BlockSpec((B, Ko), lambda s: (0, 0)),
        pl.BlockSpec((B, H), lambda s: (0, 0)),
        pl.BlockSpec(norms.shape, lambda s: (0, 0)),
        pl.BlockSpec((bko, H), lambda s: (jnp.clip(s, 0, nko - 1), 0)),
        pl.BlockSpec(o_s.shape, lambda s: (0, 0)),
        pl.BlockSpec((1, H), lambda s: (0, 0)),
        up_spec,
        pl.BlockSpec(up_s.shape, lambda s: (0, 0)),
        pl.BlockSpec((1, F), lambda s: (0, 0)),
    ]
    operands = [attn2d, x, norms, o_w, o_s, o_b.reshape(1, -1),
                up_w, up_s, up_b.reshape(1, -1)]
    if gated:
        in_specs += [up_spec,  # gate walks the same tiles as up
                     pl.BlockSpec(gt_s.shape, lambda s: (0, 0)),
                     pl.BlockSpec((1, F), lambda s: (0, 0))]
        operands += [gt_w, gt_s, gt_b.reshape(1, -1)]
    in_specs += [
        pl.BlockSpec((bkd, H), lambda s: (jnp.clip(s - A1 - nju * nku, 0, nkd - 1), 0)),
        pl.BlockSpec(dn_s.shape, lambda s: (0, 0)),
        pl.BlockSpec((1, H), lambda s: (0, 0)),
    ]
    operands += [dn_w, dn_s, dn_b.reshape(1, -1)]
    scratch = [
        pltpu.VMEM((B, H), f32),       # res2
        pltpu.VMEM((B, H), x.dtype),   # ln2 out
        pltpu.VMEM((B, F), x.dtype),   # up_h
    ]
    if gated:
        scratch.append(pltpu.VMEM((B, F), x.dtype))  # gate partials
    scratch.append(pltpu.VMEM((B, H), f32))          # shared o/down accumulator
    return pl.pallas_call(
        kernel,
        grid=(nsteps, ),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((B, H), lambda s: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H), x.dtype),
        scratch_shapes=scratch,
        compiler_params=_CompilerParams(dimension_semantics=("arbitrary", )),
        interpret=_interpret(),
    )(*operands)


def fused_decode_block(x, norms, k_cache, v_cache, qkv, o, up, down,
                       start, pos, *, activation="gelu", eps=1e-5, block_kv=256,
                       norm="layernorm", rope=None, gate=None):
    """One fused transformer decode layer for a single token per row.

    x: (B, H) bf16 residual stream. norms: (4, H) f32 rows
    [norm1_scale, norm1_bias, norm2_scale, norm2_bias] (zero bias rows for
    rmsnorm). k_cache/v_cache: (B, kv_heads, S, hd) — ``kv_heads`` may be
    smaller than ``num_heads`` (GQA; attention groups q heads over the KV
    heads). qkv/o/up/down (and ``gate`` for swiglu/geglu): (weight_q int8,
    scales f32 (G, N), bias f32 (N,)) tuples in matmul layout (qkv fused
    [q;k;v]). start: (B,) int32 first attendable slot; pos: scalar int32
    cache write position. ``rope``: optional (sin2d, cos2d) — (B, hd // 2)
    f32 rotary tables gathered at each row's position, rotated in-kernel
    over the q and k head segments.

    Returns (x_out (B, H) bf16, new_k_cache, new_v_cache) — the caches are
    committed (dynamic_update_slice at ``pos``) before attention, exactly
    like the unfused model path.
    """
    from .decode_attention import decode_attention
    B, H = x.shape
    _, nkv, S, hd = k_cache.shape
    Nq = qkv[0].shape[1]
    nh = Nq // hd - 2 * nkv
    rope_op = None
    if rope is not None:
        sin2d, cos2d = rope
        rope_op = (sin2d, cos2d, nh + nkv, hd)
    qkv2d = fused_qkv_ln(x, norms, qkv, eps=eps, norm=norm, rope=rope_op)
    qf, kf, vf = jnp.split(qkv2d, [nh * hd, (nh + nkv) * hd], axis=-1)
    k3 = kf.reshape(B, nkv, 1, hd)
    v3 = vf.reshape(B, nkv, 1, hd)
    k_cache = jax.lax.dynamic_update_slice_in_dim(k_cache, k3.astype(k_cache.dtype),
                                                  pos, axis=2)
    v_cache = jax.lax.dynamic_update_slice_in_dim(v_cache, v3.astype(v_cache.dtype),
                                                  pos, axis=2)
    attn = decode_attention(qf.reshape(B, nh, hd), k_cache, v_cache,
                            start, pos + 1, block_kv=min(block_kv, S))
    x_out = fused_out_mlp(attn.reshape(B, nh * hd), x, norms, o, up, down,
                          activation=activation, eps=eps, norm=norm, gate=gate)
    return x_out, k_cache, v_cache
