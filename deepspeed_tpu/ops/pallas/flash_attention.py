"""Pallas flash attention (TPU).

TPU-native replacement for the reference's fused CUDA attention kernels
(training: ``csrc/transformer/softmax_kernels.cu`` + strided-batch-GEMM
attention in ``csrc/transformer/ds_transformer_cuda.cpp``; the Triton
block-sparse path in ``deepspeed/ops/sparse_attention/matmul.py``).

FlashAttention-2-style online softmax: O(T) memory, fp32 accumulators in
VMEM, bf16 MXU matmuls — operands stay in the input dtype (bf16) and every
``dot_general`` accumulates in fp32 via ``preferred_element_type``; softmax
probabilities are cast back to the operand dtype before the P·V / Pᵀ·dO
matmuls (the MXU contracts bf16×bf16→fp32 natively; an fp32 operand path
would run at ~1/4 rate). Operates natively on the model's ``(B, H, T, D)``
("bhtd") layout — blocks are carved by BlockSpec index maps over the
sequence dim, so no transposes/copies appear around the kernel (those
copies cost ~7% of a train step in the packed ``(B*H, T, D)`` formulation
this replaces; the model computes attention in bhtd end-to-end).

Grouped-query attention is native: K/V keep their ``kv_heads`` dimension and
the index maps point query head ``h`` at KV head ``h // group``; nothing is
repeated in HBM. The backward dk/dv kernel accumulates per *query* head and
the group-sum is folded outside (a cheap reduce over the group dim).

K/V for one (batch, head) program live in VMEM — ~2·T·D·2 bytes, which fits
tens-of-k tokens at D=64..128; beyond that, sequence parallelism (ring /
Ulysses over the ``seq`` axis) splits T across chips before the kernel runs.

Backward follows the standard two-kernel split (dq; dkv) with the saved
softmax log-sum-exp and delta = rowsum(dO * O).

Kernels run interpreted on CPU (tests) and compiled on TPU.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.sharding import PartitionSpec as P

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _interpret():
    return jax.default_backend() == "cpu"


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block_kv, causal, q_len,
                kv_len):
    """Grid: (B, H, num_q_blocks). Blocks: q/o (1, 1, bq, D);
    k/v (1, 1, Tkv, D) — the full (padded) KV head in VMEM; lse (1, 1, bq)."""
    block_q = q_ref.shape[2]
    d = q_ref.shape[-1]
    qi = pl.program_id(2)
    q_start = qi * block_q

    q = q_ref[0, 0]  # (bq, D) operand dtype; accumulation is fp32

    m = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, d), jnp.float32)

    num_kv = pl.cdiv(k_ref.shape[2], block_kv)
    if causal:
        num_kv_eff = jax.lax.min(num_kv, pl.cdiv(q_start + block_q, block_kv))
    else:
        num_kv_eff = num_kv
    # loop-invariant local iotas: mask = (ik - iq) <= q_start - kv_start —
    # one scalar-broadcast compare per iteration instead of two iota adds
    iq = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
    ik = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
    ikq = ik - iq

    def body(j, carry):
        m, l, acc = carry
        kv_start = j * block_kv
        k = k_ref[0, 0, pl.ds(kv_start, block_kv), :]
        v = v_ref[0, 0, pl.ds(kv_start, block_kv), :]
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale  # (bq, bkv)

        mask = ik < kv_len - kv_start
        if causal:
            mask = mask & (ikq <= q_start - kv_start)
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # rows whose every visited entry is masked exist only when the
        # sequence is padded (causal rows always see the diagonal): only then
        # pay for the explicit zero that yields l=0 -> zero output, -inf lse
        # (otherwise exp(MASK - m_new) underflows to 0 on its own)
        if kv_len % block_kv or q_len % block_q:
            p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        else:
            p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(p.astype(v.dtype), v, (((1, ), (0, )), ((), ())),
                                                preferred_element_type=jnp.float32)
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, num_kv_eff, body, (m, l, acc))

    l_safe = jnp.where(l == 0, 1.0, l)
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0] = jnp.where(l == 0, -jnp.inf, m + jnp.log(l_safe))  # (bq, 1)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, scale, block_kv, causal,
                   kv_len):
    block_q = q_ref.shape[2]
    d = q_ref.shape[-1]
    qi = pl.program_id(2)
    q_start = qi * block_q

    q = q_ref[0, 0]
    do = do_ref[0, 0]
    # -inf marks attended-nothing (padding) rows; neutralize so exp(s - lse)
    # stays finite — their dq is sliced away / masked out downstream
    lse = jnp.where(jnp.isfinite(lse_ref[0, 0]), lse_ref[0, 0], 0.0)  # (bq, 1)
    delta = delta_ref[0, 0]  # (bq, 1)

    num_kv = pl.cdiv(k_ref.shape[2], block_kv)
    num_kv_eff = jax.lax.min(num_kv, pl.cdiv(q_start + block_q, block_kv)) if causal else num_kv
    iq = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
    ik = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
    ikq = ik - iq

    def body(j, dq):
        kv_start = j * block_kv
        k = k_ref[0, 0, pl.ds(kv_start, block_kv), :]
        v = v_ref[0, 0, pl.ds(kv_start, block_kv), :]
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = ik < kv_len - kv_start
        if causal:
            mask = mask & (ikq <= q_start - kv_start)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())), preferred_element_type=jnp.float32)
        # fold the softmax scale into ds before the bf16 cast (dq = scale·dsᵀk)
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        return dq + jax.lax.dot_general(ds, k, (((1, ), (0, )), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_kv_eff, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, scale, block_q,
                    causal, q_len, kv_len):
    """Grid: (B, H, num_kv_blocks). k/v blocks (1, 1, bkv, D) come from the
    (possibly grouped) KV head for query head h; dk/dv are written per
    *query* head (into (B, H, Tkv, D)) and group-summed by the caller."""
    block_kv = k_ref.shape[2]
    d = k_ref.shape[-1]
    ki = pl.program_id(2)
    kv_start = ki * block_kv

    k = k_ref[0, 0]
    v = v_ref[0, 0]

    num_q = pl.cdiv(q_ref.shape[2], block_q)
    start_q = (kv_start // block_q) if causal else 0

    iq = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
    ik = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
    ikq = ik - iq

    def body(i, carry):
        dk, dv = carry
        q_start = i * block_q
        q = q_ref[0, 0, pl.ds(q_start, block_q), :]
        do = do_ref[0, 0, pl.ds(q_start, block_q), :]
        lse_raw = lse_ref[0, 0, pl.ds(q_start, block_q), :]  # (bq, 1)
        lse = jnp.where(jnp.isfinite(lse_raw), lse_raw, 0.0)
        delta = delta_ref[0, 0, pl.ds(q_start, block_q), :]  # (bq, 1)

        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = (ik < kv_len - kv_start) & (iq < q_len - q_start)
        if causal:
            mask = mask & (ikq <= q_start - kv_start)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        pb = p.astype(do.dtype)

        dv = dv + jax.lax.dot_general(pb, do, (((0, ), (0, )), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())),
                                  preferred_element_type=jnp.float32)
        # scale folds into ds (dk = scale·dsᵀq), matching the fwd s-scaling
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk = dk + jax.lax.dot_general(ds, q, (((0, ), (0, )), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    zero = jnp.zeros((block_kv, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_q, num_q, body, (zero, zero))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _pad_seq(x, block):
    t = x.shape[2]
    pad = (-t) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
    return x


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=True, block_q=512, block_kv=512, scale=None):
    """q: (B, H, T, D); k/v: (B, Hkv, T, D) with H divisible by Hkv (GQA
    native — no pre-expansion). Returns (B, H, T, D)."""
    out, _ = _flash_fwd(q, k, v, causal, block_q, block_kv, scale)
    return out


def _flash_call(q, k, v, causal, block_q, block_kv, scale):
    B, H, T, D = q.shape
    Hkv, T_kv = k.shape[1], k.shape[2]
    assert H % Hkv == 0, f"query heads {H} not a multiple of kv heads {Hkv}"
    g = H // Hkv
    scale = scale if scale is not None else 1.0 / (D**0.5)
    block_q = min(block_q, T)
    block_kv = min(block_kv, T_kv)

    qp = _pad_seq(q, block_q)
    kp = _pad_seq(k, block_kv)
    vp = _pad_seq(v, block_kv)
    Tq, Tkv = qp.shape[2], kp.shape[2]
    grid = (B, H, Tq // block_q)

    kernel = functools.partial(_fwd_kernel, scale=scale, block_kv=block_kv, causal=causal,
                               q_len=T, kv_len=T_kv)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, Tkv, D), lambda b, h, i: (b, h // g, 0, 0)),
            pl.BlockSpec((1, 1, Tkv, D), lambda b, h, i: (b, h // g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Tq, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(qp, kp, vp)
    return out, lse, (qp, kp, vp, Tq, Tkv)


def _flash_fwd(q, k, v, causal, block_q, block_kv, scale):
    from jax.ad_checkpoint import checkpoint_name
    T = q.shape[2]
    out_p, lse, (qp, kp, vp, Tq, Tkv) = _flash_call(q, k, v, causal, block_q, block_kv, scale)
    # name the kernel outputs so a remat policy can pin them: re-running the
    # forward kernel inside backward costs ~6% of step time under plain
    # dots_saveable (the custom-call is not a "dot"). Pair with
    # jax.checkpoint_policies.save_only_these_names("flash_out", "flash_lse")
    # (models.transformer exposes it as policy "dots_and_attn_saveable").
    out_p = checkpoint_name(out_p, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    # residuals keep the UNPADDED operands: backward re-pads (cheap) and the
    # logical q/kv lengths stay statically derivable from the shapes
    return out_p[:, :, :T], (q, k, v, out_p, lse)


def _flash_bwd(causal, block_q, block_kv, scale, res, g_out):
    return _flash_bwd_impl(causal, block_q, block_kv, scale, res, g_out)


def _flash_bwd_impl(causal, block_q, block_kv, scale, res, g_out, delta_shift=None):
    q, k, v, out_p, lse = res
    B, H, T, D = q.shape
    Hkv = k.shape[1]
    grp = H // Hkv
    T_kv_logical = k.shape[2]
    scale_v = scale if scale is not None else 1.0 / (D**0.5)
    bq = min(block_q, T)
    bkv = min(block_kv, T_kv_logical)
    qp = _pad_seq(q, bq)
    kp = _pad_seq(k, bkv)
    vp = _pad_seq(v, bkv)
    Tq, Tkv = qp.shape[2], kp.shape[2]

    dop = jnp.pad(g_out, ((0, 0), (0, 0), (0, Tq - T), (0, 0))) if Tq != T else g_out

    delta = jnp.einsum("bhtd,bhtd->bht", dop.astype(jnp.float32),
                       out_p.astype(jnp.float32))[..., None]  # (B, H, Tq, 1)
    if delta_shift is not None:
        delta = delta - delta_shift.astype(jnp.float32)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale_v, block_kv=bkv, causal=causal,
                          kv_len=T_kv_logical),
        grid=(B, H, Tq // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, Tkv, D), lambda b, h, i: (b, h // grp, 0, 0)),
            pl.BlockSpec((1, 1, Tkv, D), lambda b, h, i: (b, h // grp, 0, 0)),
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, Tq, D), qp.dtype),
        interpret=_interpret(),
    )(qp, kp, vp, dop, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale_v, block_q=bq, causal=causal,
                          q_len=T, kv_len=T_kv_logical),
        grid=(B, H, Tkv // bkv),
        in_specs=[
            pl.BlockSpec((1, 1, Tq, D), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, bkv, D), lambda b, h, j: (b, h // grp, j, 0)),
            pl.BlockSpec((1, 1, bkv, D), lambda b, h, j: (b, h // grp, j, 0)),
            pl.BlockSpec((1, 1, Tq, D), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, Tq, 1), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, Tq, 1), lambda b, h, j: (b, h, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bkv, D), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bkv, D), lambda b, h, j: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Tkv, D), kp.dtype),
            jax.ShapeDtypeStruct((B, H, Tkv, D), vp.dtype),
        ],
        interpret=_interpret(),
    )(qp, kp, vp, dop, lse, delta)

    if grp > 1:  # group-sum per-query-head dk/dv back onto the shared KV head
        dk = dk.reshape(B, Hkv, grp, Tkv, D).sum(axis=2)
        dv = dv.reshape(B, Hkv, grp, Tkv, D).sum(axis=2)
    return dq[:, :, :T], dk[:, :, :T_kv_logical], dv[:, :, :T_kv_logical]


flash_attention.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_with_lse(q, k, v, causal=True, block_q=512, block_kv=512, scale=None):
    """Flash attention that also returns the per-row log-sum-exp —
    the merge currency of ring attention (``ops/pallas/ring_attention.py``):
    two attention results over disjoint KV sets combine exactly from their
    (out, lse) pairs. lse shape (B, H, T); rows that attend nothing are -inf.
    """
    out, lse = _flash_lse_fwd(q, k, v, causal, block_q, block_kv, scale)[0]
    return out, lse


def _flash_lse_fwd(q, k, v, causal, block_q, block_kv, scale):
    T = q.shape[2]
    out_p, lse, (qp, kp, vp, Tq, Tkv) = _flash_call(q, k, v, causal, block_q, block_kv, scale)
    return (out_p[:, :, :T], lse[:, :, :T, 0]), (q, k, v, out_p, lse)


def _flash_lse_bwd(causal, block_q, block_kv, scale, res, g):
    """The lse cotangent folds into the existing dq/dkv kernels: with
    s-gradient ds = p∘(dp − delta), and dlse/ds = p, the combined cotangent
    is ds = p∘(dp − (delta − g_lse)) — so shifting delta by −g_lse reuses
    both kernels unchanged."""
    g_out, g_lse = g
    out_p = res[3]
    T = g_out.shape[2]
    Tq = out_p.shape[2]
    g_lse_p = jnp.pad(g_lse, ((0, 0), (0, 0), (0, Tq - T))) if Tq != T else g_lse
    return _flash_bwd_impl(causal, block_q, block_kv, scale, res, g_out,
                           delta_shift=g_lse_p[..., None])


flash_attention_with_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def sharded_flash_attention(q, k, v, causal=True, block_q=512, block_kv=512, scale=None):
    """Mesh-aware flash attention: q (B, H, T, D), k/v (B, Hkv, T, D) with
    full (or head-gathered) sequence per shard.

    A ``pallas_call`` cannot be split by the automatic SPMD partitioner, so on
    a non-trivial mesh the kernel runs inside ``shard_map``: batch over the
    data axes and heads over (seq, tensor) — the head-parallel placement
    Ulysses-style sequence parallelism hands us (DeepSpeed-Ulysses; the
    v0.9.2 reference's long-sequence surface is block-sparse attention,
    ``deepspeed/ops/sparse_attention/``). Falls back to a direct call on a
    trivial mesh or inside an enclosing manual region. When the KV head count
    doesn't divide the head-axis degree, KV is expanded to full heads first —
    every shard_map input must be sharded (a replicated input's cotangent
    would need a psum that check_vma=False disables).
    """
    from ...comm import comm as dist

    if not dist.has_mesh() or dist.in_manual_region():
        return flash_attention(q, k, v, causal, block_q, block_kv, scale)
    mesh = dist.get_mesh()
    B, H, T, D = q.shape
    Hkv = k.shape[1]
    dp_axes, head_axes = dist.attention_partition_axes(B, H)
    if not dp_axes and not head_axes:
        return flash_attention(q, k, v, causal, block_q, block_kv, scale)

    head_degree = int(np.prod([mesh.shape[a] for a in head_axes])) if head_axes else 1
    qspec = P(dp_axes or None, head_axes or None, None, None)
    if head_degree > 1 and Hkv % head_degree != 0:
        k = jnp.repeat(k, H // Hkv, axis=1)
        v = jnp.repeat(v, H // Hkv, axis=1)
    kvspec = qspec

    def fn(q, k, v):  # positional: custom_vjp rejects kwargs
        return flash_attention(q, k, v, causal, block_q, block_kv, scale)

    with dist.manual_axes(set(dp_axes) | set(head_axes)):
        # replication checking off: pallas_call out_shapes carry no
        # vma/rep annotations (shard_map_compat spans the jax API move)
        from . import shard_map_compat
        return shard_map_compat(fn, mesh, (qspec, kvspec, kvspec), qspec,
                                manual_axes=set(dp_axes) | set(head_axes))(q, k, v)
