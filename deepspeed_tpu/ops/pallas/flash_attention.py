"""Pallas flash attention (TPU).

TPU-native replacement for the reference's fused CUDA attention kernels
(training: ``csrc/transformer/softmax_kernels.cu`` + strided-batch-GEMM
attention in ``csrc/transformer/ds_transformer_cuda.cpp``; the Triton
block-sparse path in ``deepspeed/ops/sparse_attention/matmul.py``).

FlashAttention-2-style online softmax: O(T) memory, fp32 accumulators in
VMEM, bf16 MXU matmuls. Layout is ``(B, T, H, D)`` (the model's "bqhd").
K/V live fully in VMEM per (batch, head) program — fine for T up to ~4k at
D=128; longer sequences go through the ring-attention path (sequence
parallelism) rather than a single-chip kernel.

Backward follows the standard two-kernel split (dq; dkv) with the saved
softmax log-sum-exp and delta = rowsum(dO * O).

Kernels run interpreted on CPU (tests) and compiled on TPU.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.sharding import PartitionSpec as P

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _interpret():
    return jax.default_backend() == "cpu"


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block_kv, causal, seq_len):
    """Grid: (B*H, num_q_blocks). Blocks: q (1, bq, D); k/v (1, Tkv, D)."""
    block_q = q_ref.shape[1]
    d = q_ref.shape[-1]
    qi = pl.program_id(1)
    q_start = qi * block_q

    q = q_ref[0].astype(jnp.float32) * scale

    m = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, d), jnp.float32)

    num_kv = pl.cdiv(k_ref.shape[1], block_kv)
    if causal:
        num_kv_eff = jax.lax.min(num_kv, pl.cdiv(q_start + block_q, block_kv))
    else:
        num_kv_eff = num_kv

    def body(j, carry):
        m, l, acc = carry
        kv_start = j * block_kv
        k = k_ref[0, pl.ds(kv_start, block_kv), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kv_start, block_kv), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32)  # (bq, bkv)

        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
        kv_pos = kv_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
        mask = kv_pos < seq_len
        if causal:
            mask = mask & (kv_pos <= q_pos)
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(p, v, (((1, ), (0, )), ((), ())),
                                                preferred_element_type=jnp.float32)
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, num_kv_eff, body, (m, l, acc))

    l_safe = jnp.where(l == 0, 1.0, l)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)  # (bq, 1)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *, scale, block_kv, causal,
                   seq_len):
    block_q = q_ref.shape[1]
    d = q_ref.shape[-1]
    qi = pl.program_id(1)
    q_start = qi * block_q

    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]  # (bq, 1)
    delta = delta_ref[0]  # (bq, 1)

    num_kv = pl.cdiv(k_ref.shape[1], block_kv)
    num_kv_eff = jax.lax.min(num_kv, pl.cdiv(q_start + block_q, block_kv)) if causal else num_kv

    def body(j, dq):
        kv_start = j * block_kv
        k = k_ref[0, pl.ds(kv_start, block_kv), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(kv_start, block_kv), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())), preferred_element_type=jnp.float32)
        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
        kv_pos = kv_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
        mask = kv_pos < seq_len
        if causal:
            mask = mask & (kv_pos <= q_pos)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + jax.lax.dot_general(ds, k, (((1, ), (0, )), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_kv_eff, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, *, scale, block_q,
                    causal, seq_len):
    """Grid: (B*H, num_kv_blocks). Blocks: k/v (1, bkv, D); q/do (1, Tq, D)."""
    block_kv = k_ref.shape[1]
    d = k_ref.shape[-1]
    ki = pl.program_id(1)
    kv_start = ki * block_kv

    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)

    num_q = pl.cdiv(q_ref.shape[1], block_q)
    start_q = (kv_start // block_q) if causal else 0

    def body(i, carry):
        dk, dv = carry
        q_start = i * block_q
        q = q_ref[0, pl.ds(q_start, block_q), :].astype(jnp.float32) * scale
        do = do_ref[0, pl.ds(q_start, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(q_start, block_q)]  # (bq, 1)
        delta = delta_ref[0, pl.ds(q_start, block_q)]  # (bq, 1)

        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())), preferred_element_type=jnp.float32)
        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 0)
        kv_pos = kv_start + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_kv), 1)
        mask = (kv_pos < seq_len) & (q_pos < seq_len)
        if causal:
            mask = mask & (kv_pos <= q_pos)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)

        dv = dv + jax.lax.dot_general(p, do, (((0, ), (0, )), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk = dk + jax.lax.dot_general(ds, q, (((0, ), (0, )), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    zero = jnp.zeros((block_kv, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_q, num_q, body, (zero, zero))
    # q was pre-scaled inside the loop, so ds^T @ q_scaled already carries the
    # softmax scale — no extra factor here
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _pad_seq(x, block):
    t = x.shape[1]
    pad = (-t) % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))
    return x


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=True, block_q=512, block_kv=512, scale=None):
    """q,k,v: (B, T, H, D) with equal head counts (GQA pre-expanded).
    Returns (B, T, H, D)."""
    out, _ = _flash_fwd(q, k, v, causal, block_q, block_kv, scale)
    return out


def _flash_call(q, k, v, causal, block_q, block_kv, scale):
    B, T, H, D = q.shape
    scale = scale if scale is not None else 1.0 / (D**0.5)
    block_q = min(block_q, T)
    block_kv = min(block_kv, T)

    qp = _pad_seq(q, block_q)
    kp = _pad_seq(k, block_kv)
    vp = _pad_seq(v, block_kv)
    Tq, Tkv = qp.shape[1], kp.shape[1]

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    qb, kb, vb = to_bh(qp), to_bh(kp), to_bh(vp)
    grid = (B * H, Tq // block_q)

    kernel = functools.partial(_fwd_kernel, scale=scale, block_kv=block_kv, causal=causal, seq_len=T)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Tkv, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Tkv, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Tq, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(qb, kb, vb)
    return out, lse, (qb, kb, vb, Tq, Tkv)


def _flash_fwd(q, k, v, causal, block_q, block_kv, scale):
    B, T, H, D = q.shape
    out_b, lse, (qb, kb, vb, Tq, Tkv) = _flash_call(q, k, v, causal, block_q, block_kv, scale)
    out = out_b.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)[:, :T]
    return out, (qb, kb, vb, out_b, lse, q.shape)


def _flash_bwd(causal, block_q, block_kv, scale, res, g):
    qb, kb, vb, out_b, lse, q_shape = res
    B, T, H, D = q_shape
    scale_v = scale if scale is not None else 1.0 / (D**0.5)
    bq = min(block_q, T)
    bkv = min(block_kv, T)
    Tq, Tkv = qb.shape[1], kb.shape[1]

    gp = jnp.pad(g, ((0, 0), (0, Tq - T), (0, 0), (0, 0))) if Tq != T else g
    dob = gp.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)

    delta = jnp.sum(dob.astype(jnp.float32) * out_b.astype(jnp.float32), axis=-1,
                    keepdims=True)  # (BH, Tq, 1)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale_v, block_kv=bkv, causal=causal, seq_len=T),
        grid=(B * H, Tq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Tkv, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Tkv, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), qb.dtype),
        interpret=_interpret(),
    )(qb, kb, vb, dob, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale_v, block_q=bq, causal=causal, seq_len=T),
        grid=(B * H, Tkv // bkv),
        in_specs=[
            pl.BlockSpec((1, Tq, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, bkv, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bkv, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, Tq, D), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, Tq, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, Tq, 1), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bkv, D), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, bkv, D), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tkv, D), kb.dtype),
            jax.ShapeDtypeStruct((B * H, Tkv, D), vb.dtype),
        ],
        interpret=_interpret(),
    )(qb, kb, vb, dob, lse, delta)

    def from_bh(x, t_pad):
        return x.reshape(B, H, t_pad, D).transpose(0, 2, 1, 3)[:, :T]

    return from_bh(dq, Tq), from_bh(dk, Tkv), from_bh(dv, Tkv)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def sharded_flash_attention(q, k, v, causal=True, block_q=512, block_kv=512, scale=None):
    """Mesh-aware flash attention: q/k/v (B, T, H, D) with full (or
    head-gathered) sequence per shard.

    A ``pallas_call`` cannot be split by the automatic SPMD partitioner, so on
    a non-trivial mesh the kernel runs inside ``shard_map``: batch over the
    data axes and heads over (seq, tensor) — the head-parallel placement
    Ulysses-style sequence parallelism hands us (DeepSpeed-Ulysses; the
    v0.9.2 reference's long-sequence surface is block-sparse attention,
    ``deepspeed/ops/sparse_attention/``). Falls back to a direct call on a
    trivial mesh or inside an enclosing manual region.
    """
    from ...comm import comm as dist

    if not dist.has_mesh() or dist.in_manual_region():
        return flash_attention(q, k, v, causal, block_q, block_kv, scale)
    mesh = dist.get_mesh()
    B, T, H, D = q.shape
    dp_axes, head_axes = dist.attention_partition_axes(B, H)
    if not dp_axes and not head_axes:
        return flash_attention(q, k, v, causal, block_q, block_kv, scale)

    spec = P(dp_axes or None, None, head_axes or None, None)

    def fn(q, k, v):  # positional: custom_vjp rejects kwargs
        return flash_attention(q, k, v, causal, block_q, block_kv, scale)

    with dist.manual_axes(set(dp_axes) | set(head_axes)):
        # check_vma=False: pallas_call out_shapes carry no vma annotations
        return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                             axis_names=set(dp_axes) | set(head_axes), check_vma=False)(q, k, v)
