"""Pallas weight-only-quantized matmul (w8a16).

Serving counterpart of the reference's CUDA dequant+GEMM inference kernels
(``csrc/transformer/inference/csrc/pt_binding.cpp`` int8 ``qkv_gemm``/
``mlp_gemm`` variants and the ``ds_quantizer`` ops): activations stay bf16,
weights stream from HBM as int8 and hit the MXU straight after an
int8->bf16 widen — the bf16 weight matrix never exists in HBM, halving
weight bandwidth (the decode-time bottleneck).

Kernel design (microbenched on v5e, ``benchmarks/qmm_microbench.py``):
- The int8 block is converted bf16 in ONE VPU pass (no fp32 round-trip)
  and fed to the MXU; the per-group quantization scale is applied to the
  tiny ``(block_m, block_n)`` fp32 partial sum AFTER the dot — K*N scale
  multiplies become M*N (M is the batch, ~8 at decode). This measured
  ~2.8x the naive dequantize-then-dot tile loop (469 vs 169 GB/s of int8
  bytes at decode shapes; bf16 streaming roof ~690 GB/s).
- Scales load once per n-tile as a ``(G, block_n)`` block reused across
  the k grid, not replicated per k-step.
- ``block_k`` = one quantization group so each k-block sees exactly one
  scale row; ``block_n`` as large as divides N (fewer grid steps keep the
  DMA pipeline fed — block_n 2560 beat 512 by 1.7x).

Layout: x (M, K) bf16; qw (K, N) int8; scales (G, N) fp32 with group size
K/G along the contraction dim.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret():
    return jax.default_backend() == "cpu"


def _qmm_kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, nk, bk, gsize, ng):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # one-pass widen to the activation dtype; MXU does the heavy lifting.
    # A k-block spans ng quantization groups (big DMA blocks at group-level
    # quality): one dot per group, scale applied to the (bm, bn) partial.
    x = x_ref[...]
    w = w_ref[...]
    acc = jnp.zeros_like(acc_ref)
    span = min(gsize, bk)
    for t in range(ng):
        part = jax.lax.dot_general(x[:, t * span:(t + 1) * span],
                                   w[t * span:(t + 1) * span, :].astype(x.dtype),
                                   (((1, ), (0, )), ((), ())),
                                   preferred_element_type=jnp.float32)
        acc += part * s_ref[(k * bk) // gsize + t, :][None, :]
    acc_ref[...] += acc

    @pl.when(k == nk - 1)
    def _flush():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def pick_block(n, cap, mult=128):
    """Largest multiple of ``mult`` <= cap dividing n, else n itself (Mosaic
    tiling: blocks must tile ``mult``x128 unless they span the whole dim).
    Shared by this kernel's defaults and the model-side callers."""
    if n <= cap:
        return n
    d = cap - cap % mult
    while d >= mult:
        if n % d == 0:
            return d
        d -= mult
    return n


def pick_block_k(K, gsize, cap=1024):
    """Largest multiple of ``gsize`` dividing K under ``cap`` (>=1 group per
    block) — the k-block rule shared by this kernel's default and the fused
    decode blocks."""
    for cand in range(min(K, cap) // gsize * gsize, gsize - 1, -gsize):
        if K % cand == 0:
            return cand
    return gsize


def _pick_bn(n, cap=4096):
    return pick_block(n, cap, 128)


def quant_matmul(x, qw, scales, block_m=256, block_n=None, block_k=None, out_dtype=None):
    """``x @ dequantize(qw, scales)`` without materializing the bf16 weight.

    x: (M, K); qw: (K, N) int8; scales: (G, N) fp32, G | K. Returns (M, N)
    in ``out_dtype`` (defaults to x.dtype)."""
    M, K = x.shape
    K2, N = qw.shape
    scales = jnp.asarray(scales, jnp.float32)
    if scales.ndim == 3 and scales.shape[1] == 1:
        scales = scales[:, 0, :]  # accept quantize()'s (G, 1, N) directly
    if scales.ndim != 2:
        raise ValueError(f"scales must be (G, N), got shape {scales.shape}")
    G = scales.shape[0]
    if K != K2:
        raise ValueError(f"x K={K} != qw K={K2}")
    if scales.shape[1] != N:
        raise ValueError(f"scales N={scales.shape[1]} != weight N={N}")
    if K % G != 0:
        raise ValueError(f"groups {G} must divide K={K}")
    gsize = K // G
    bm = min(block_m, M)
    if block_k is None:
        if gsize <= 1024:
            # largest multiple of the group size dividing K under ~1MB blocks
            bk = pick_block_k(K, gsize)
        else:
            # huge groups (e.g. G==1): sub-group k-blocks — any divisor of
            # gsize works since consecutive blocks just reuse one scale row
            bk = gsize
            for cand in range(1024 - 1024 % 128, 127, -128):
                if gsize % cand == 0:
                    bk = cand
                    break
    else:
        bk = min(block_k, K)
    if bk % gsize and gsize % bk:
        raise ValueError(f"block_k {bk} must divide or be a multiple of group size {gsize}")
    if K % bk:
        raise ValueError(f"block_k {bk} must divide K={K}")
    ng = max(1, bk // gsize)
    bn = block_n or _pick_bn(N)
    if M % bm or N % bn:
        raise ValueError(f"shape ({M},{K})x({K},{N}) not divisible by blocks ({bm},{bk},{bn})")
    out_dtype = out_dtype or x.dtype
    nk = K // bk
    Gpad = -(-G // 8) * 8
    if Gpad != G:  # Mosaic block sublanes must be a multiple of 8
        scales = jnp.pad(scales, ((0, Gpad - G), (0, 0)))

    return pl.pallas_call(
        functools.partial(_qmm_kernel, nk=nk, bk=bk, gsize=gsize, ng=ng),
        grid=(M // bm, N // bn, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((Gpad, bn), lambda i, j, k: (0, j)),  # revisited, one DMA per j
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=_interpret(),
    )(x, qw, scales)
