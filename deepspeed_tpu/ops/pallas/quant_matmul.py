"""Pallas weight-only-quantized matmul (w8a16 / w4-ready).

Serving counterpart of the reference's CUDA dequant+GEMM inference kernels
(``csrc/transformer/inference/csrc/gelu.cu`` fused bias/dequant paths and the
``ds_quantizer`` ops): activations stay bf16, weights stream from HBM as
int8 and are dequantized block-by-block in VMEM right before the MXU — the
bf16 weight matrix never exists in HBM, halving weight bandwidth (the
decode-time bottleneck).

Layout: x (M, K) bf16; qw (K, N) int8; scales (G, N) fp32 with group size
K/G along the contraction dim. Requires block_k <= group size and
group_size % block_k == 0 so each k-block sees one scale row.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret():
    return jax.default_backend() == "cpu"


def _qmm_kernel(x_ref, w_ref, s_ref, o_ref, acc_ref, *, nk):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # dequantize the int8 block in VMEM: one fp32 scale row per k-block (the
    # scale rows arrive 8x-replicated to satisfy Mosaic's sublane tiling;
    # row 0 of the block is the group's scale)
    w = w_ref[...].astype(jnp.float32) * s_ref[0:1, :]
    acc_ref[...] += jax.lax.dot_general(x_ref[...], w.astype(x_ref.dtype),
                                        (((1, ), (0, )), ((), ())),
                                        preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _flush():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def quant_matmul(x, qw, scales, block_m=256, block_n=256, block_k=512, out_dtype=None):
    """``x @ dequantize(qw, scales)`` without materializing the bf16 weight.

    x: (M, K); qw: (K, N) int8; scales: (G, N) fp32, G | K. Returns (M, N)
    in ``out_dtype`` (defaults to x.dtype)."""
    M, K = x.shape
    K2, N = qw.shape
    scales = jnp.asarray(scales, jnp.float32)
    if scales.ndim == 3 and scales.shape[1] == 1:
        scales = scales[:, 0, :]  # accept quantize()'s (G, 1, N) directly
    if scales.ndim != 2:
        raise ValueError(f"scales must be (G, N), got shape {scales.shape}")
    G = scales.shape[0]
    if K != K2:
        raise ValueError(f"x K={K} != qw K={K2}")
    if scales.shape[1] != N:
        raise ValueError(f"scales N={scales.shape[1]} != weight N={N}")
    if K % G != 0:
        raise ValueError(f"groups {G} must divide K={K}")
    gsize = K // G
    block_m = min(block_m, M)
    block_n = min(block_n, N)
    block_k = min(block_k, gsize)
    if gsize % block_k != 0:
        raise ValueError(f"group size {gsize} must be a multiple of block_k {block_k}")
    if M % block_m or N % block_n or K % block_k:
        raise ValueError(f"shape ({M},{K})x({K},{N}) not divisible by blocks "
                         f"({block_m},{block_k},{block_n})")
    out_dtype = out_dtype or x.dtype
    nk = K // block_k
    # 8x-replicate scale rows: Mosaic block shapes need >=8 sublanes, and a
    # (G, N) array cannot hand out (1, block_n) blocks
    scales8 = jnp.repeat(scales, 8, axis=0)

    return pl.pallas_call(
        functools.partial(_qmm_kernel, nk=nk),
        grid=(M // block_m, N // block_n, nk),
        in_specs=[
            pl.BlockSpec((block_m, block_k), lambda i, j, k: (i, k)),
            pl.BlockSpec((block_k, block_n), lambda i, j, k: (k, j)),
            pl.BlockSpec((8, block_n), lambda i, j, k: (k * block_k // gsize, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        interpret=_interpret(),
    )(x, qw, scales8)
