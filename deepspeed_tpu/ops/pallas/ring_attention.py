"""Ring attention: sequence parallelism for contexts beyond one chip.

The long-context half of the SP story (SURVEY §2.3 first-class requirement;
the v0.9.2 reference's long-sequence surface is block-sparse attention —
``deepspeed/ops/sparse_attention`` — and this framework also ships Ulysses
head-scatter in ``models/transformer._ulysses_specs``). Ulysses re-gathers
the full sequence per head, so VMEM/HBM still see O(T); ring attention keeps
every chip at O(T/n): each chip holds one sequence chunk of Q/K/V, KV chunks
rotate around the ``seq`` ring via ``ppermute`` (ICI neighbor traffic), and
each step's local flash-attention result merges into a running (out, lse)
pair — the online-softmax identity across chips instead of across blocks.

Causal scheduling, two variants behind one API (``schedule=``):

- ``unbalanced``: at ring step ``s`` chip ``i`` holds KV chunk ``i−s`` mod
  ``n``. Step 0 is the causal diagonal; step ``s≥1`` is a full (non-causal)
  block that only chips ``i >= s`` keep — wrapped chunks are future context,
  discarded by an lse=−inf merge, so ~half the non-diagonal block compute is
  wasted.
- ``zigzag`` (default): the global sequence splits into 2n chunks and chip
  ``i`` holds the PAIR (chunk i, chunk 2n−1−i) — one early, one late. At
  every non-diagonal step each chip does exactly one half-block of useful
  work (received-from-behind: full-Q x early-KV-half; received-from-ahead:
  late-Q-half x full-KV), recovering the ~2x causal efficiency. The
  contiguous→zig-zag chunk relayout (and its inverse on the output) runs as
  four ppermutes of half-chunks — O(T/n) neighbor traffic, amortized over
  the n ring steps.

Differentiable end-to-end: the per-step kernel is
``flash_attention_with_lse`` (custom VJP with the lse cotangent folded into
the dq/dkv kernels) and the merge/ppermute are plain JAX; each step is
``jax.checkpoint``-ed so backward recomputes block attention instead of
storing n per-step residuals.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .flash_attention import flash_attention_with_lse

_NEG_INF = -jnp.inf


def _merge(o1, lse1, o2, lse2):
    """Combine two normalized attention results over disjoint KV sets.
    -inf lse means 'attended nothing'; fully guarded against nan grads.
    Returns fp32 — the ring carry stays fp32 so only the final result
    rounds to the model dtype (n-1 intermediate roundings would otherwise
    accumulate in bf16)."""
    m = jnp.maximum(lse1, lse2)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    w1 = jnp.where(jnp.isfinite(lse1), jnp.exp(jnp.minimum(lse1 - m_safe, 0.0)), 0.0)
    w2 = jnp.where(jnp.isfinite(lse2), jnp.exp(jnp.minimum(lse2 - m_safe, 0.0)), 0.0)
    denom = w1 + w2
    denom_safe = jnp.where(denom == 0, 1.0, denom)
    out = (o1.astype(jnp.float32) * w1[..., None] + o2.astype(jnp.float32) * w2[..., None]) / \
        denom_safe[..., None]
    lse = jnp.where(denom == 0, _NEG_INF, m_safe + jnp.log(denom_safe))
    return out, lse


def ring_attention_local(q, k, v, axis_name="seq", causal=True, block_q=512, block_kv=512,
                         scale=None):
    """Per-chip body — call inside ``shard_map`` with ``axis_name`` bound.

    q: (B, H, Tc, D); k/v: (B, Hkv, Tc, D) — this chip's sequence chunk
    (global position = chip index * Tc + local). Returns (B, H, Tc, D)."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, H, Tc, D = q.shape

    def attend(kv, causal_flag):
        kk, vv = kv
        return flash_attention_with_lse(q, kk, vv, causal_flag, block_q, block_kv, scale)

    # step 0: the causal diagonal chunk (fp32 carry; one rounding at the end)
    out0, lse = jax.checkpoint(functools.partial(attend, causal_flag=causal))((k, v))
    out = out0.astype(jnp.float32)

    if n == 1:
        return out.astype(q.dtype)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(s, carry):
        out, lse, kv = carry
        kv = jax.tree_util.tree_map(lambda x: jax.lax.ppermute(x, axis_name, perm), kv)
        o_s, lse_s = jax.checkpoint(functools.partial(attend, causal_flag=False))(kv)
        if causal:
            # chip i now sees chunk (i - s) mod n; wrapped chunks are future
            keep = (idx >= s)[None, None, None]
            lse_s = jnp.where(keep, lse_s, _NEG_INF)
        out, lse = _merge(out, lse, o_s, lse_s)
        return out, lse, kv

    out, lse, _ = jax.lax.fori_loop(1, n, body, (out, lse, (k, v)))
    return out.astype(q.dtype)


# --------------------------------------------------------------------- zigzag
def _zigzag_mapping(n, inverse=False):
    """Half-chunk routing tables: ``mapping[dst_slot]`` is a list of
    ``(src_chip, src_slot, dst_chip)``. Forward: contiguous layout (chip s
    holds chunks 2s, 2s+1) -> zig-zag (chip i holds chunks i, 2n-1-i)."""
    mapping = {0: [], 1: []}
    for i in range(n):
        for dst_slot, chunk in ((0, i), (1, 2 * n - 1 - i)):
            src_chip, src_slot = chunk // 2, chunk % 2
            if inverse:
                # transpose: contiguous chip src_chip slot src_slot receives
                # chunk back from zig-zag chip i slot dst_slot
                mapping[src_slot].append((i, dst_slot, src_chip))
            else:
                mapping[dst_slot].append((src_chip, src_slot, i))
    return mapping


def _permute_halves(halves, mapping, axis_name):
    """Route local half-chunks by the mapping (<=2 ppermutes per dst slot;
    a chip that is no pair's destination receives zeros, so summing the
    slot-wise ppermutes reassembles every destination exactly once)."""
    out = []
    for dst_slot in (0, 1):
        acc = None
        for src_slot in (0, 1):
            pairs = [(sc, dc) for sc, ss, dc in mapping[dst_slot] if ss == src_slot]
            if not pairs:
                continue
            moved = jax.lax.ppermute(halves[src_slot], axis_name, pairs)
            acc = moved if acc is None else acc + moved
        out.append(acc)
    return tuple(out)


def _zigzag_relayout(x, axis_name, n, inverse=False):
    """(B, H, 2c, D) local chunk-pair -> re-routed chunk-pair."""
    c = x.shape[2] // 2
    halves = (x[:, :, :c], x[:, :, c:])
    h0, h1 = _permute_halves(halves, _zigzag_mapping(n, inverse), axis_name)
    return jnp.concatenate([h0, h1], axis=2)


def zigzag_ring_attention_local(q, k, v, axis_name="seq", block_q=512, block_kv=512,
                                scale=None):
    """Per-chip body over the ZIG-ZAG layout: local tensors hold (chunk i,
    chunk 2n-1-i) of the 2n-chunk causal sequence. Every element of the early
    chunk precedes every element of the late chunk, so the local diagonal is
    a plain causal flash call on the concatenation; non-diagonal steps are
    exactly one balanced half-block each (see module docstring)."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, H, T2, D = q.shape
    c = T2 // 2

    def attend(qq, kk, vv, causal_flag):
        return flash_attention_with_lse(qq, kk, vv, causal_flag, block_q, block_kv, scale)

    out0, lse = jax.checkpoint(functools.partial(attend, causal_flag=True))(q, k, v)
    out = out0.astype(jnp.float32)
    if n == 1:
        return out.astype(q.dtype)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def from_behind(kv):
        # kv came from chip j < i: its early chunk precedes BOTH local q
        # chunks; its late chunk follows both. Full Q x early-KV-half.
        kk, vv = kv
        o, l = jax.checkpoint(functools.partial(attend, causal_flag=False))(
            q, kk[:, :, :c], vv[:, :, :c])
        return o.astype(jnp.float32), l

    def from_ahead(kv):
        # kv came from chip j > i: both its chunks sit between local q's
        # early and late chunks. Late-Q-half x full KV; early half attends
        # nothing (lse=-inf so the merge ignores it).
        kk, vv = kv
        o, l = jax.checkpoint(functools.partial(attend, causal_flag=False))(
            q[:, :, c:], kk, vv)
        pad_o = jnp.zeros((B, H, c, D), jnp.float32)
        pad_l = jnp.full((B, H, c), _NEG_INF, l.dtype)
        return (jnp.concatenate([pad_o, o.astype(jnp.float32)], axis=2),
                jnp.concatenate([pad_l, l], axis=2))

    def body(s, carry):
        out, lse, kv = carry
        kv = jax.tree_util.tree_map(lambda x: jax.lax.ppermute(x, axis_name, perm), kv)
        o_s, lse_s = jax.lax.cond(idx >= s, from_behind, from_ahead, kv)
        out, lse = _merge(out, lse, o_s, lse_s)
        return out, lse, kv

    out, lse, _ = jax.lax.fori_loop(1, n, body, (out, lse, (k, v)))
    return out.astype(q.dtype)


def ring_attention(q, k, v, causal=True, block_q=512, block_kv=512, scale=None,
                   schedule="zigzag"):
    """Mesh-level entry: q (B, H, T, D), k/v (B, Hkv, T, D) sequence-sharded
    over the ``seq`` axis, batch over data axes, heads over ``tensor`` (when
    divisible). Runs the ring inside ``shard_map``; falls back to a plain
    flash call on a trivial mesh. ``schedule``: 'zigzag' (balanced causal,
    default) or 'unbalanced'; non-causal attention always uses the plain
    rotation (every block is useful there)."""
    from ...comm import comm as dist

    def local_fn(n_ring, local_t):
        use_zigzag = (schedule == "zigzag" and causal and n_ring > 1
                      and local_t % 2 == 0)

        def fn(q, k, v):
            if use_zigzag:
                q_z = _zigzag_relayout(q, dist.SEQ_AXIS, n_ring)
                k_z = _zigzag_relayout(k, dist.SEQ_AXIS, n_ring)
                v_z = _zigzag_relayout(v, dist.SEQ_AXIS, n_ring)
                out = zigzag_ring_attention_local(q_z, k_z, v_z, dist.SEQ_AXIS,
                                                  block_q, block_kv, scale)
                return _zigzag_relayout(out, dist.SEQ_AXIS, n_ring, inverse=True)
            return ring_attention_local(q, k, v, dist.SEQ_AXIS, causal, block_q, block_kv,
                                        scale)

        return fn

    if dist.in_manual_region():
        # already inside someone's shard_map: run the ring only if the seq
        # axis is actually bound there
        if dist.SEQ_AXIS in dist.get_manual_axes():
            n_ring = dist.get_mesh().shape[dist.SEQ_AXIS] if dist.has_mesh() else 1
            return local_fn(n_ring, q.shape[2])(q, k, v)
        return _dense_fallback(q, k, v, causal, block_q, block_kv, scale)
    if not dist.has_mesh() or dist.get_mesh().shape[dist.SEQ_AXIS] == 1:
        return _dense_fallback(q, k, v, causal, block_q, block_kv, scale)

    mesh = dist.get_mesh()
    B, H, T, D = q.shape
    Hkv = k.shape[1]
    dp_axes, _ = dist.attention_partition_axes(B, H)
    # heads ride the tensor axis so TP shards attention instead of
    # regathering it (the auto partitioner cannot split a pallas_call)
    tdeg = mesh.shape[dist.TENSOR_AXIS]
    head_axis = dist.TENSOR_AXIS if (tdeg > 1 and H % tdeg == 0) else None
    if head_axis and Hkv % tdeg != 0:  # GQA narrower than TP: expand KV heads
        k = jnp.repeat(k, H // Hkv, axis=1)
        v = jnp.repeat(v, H // Hkv, axis=1)
    spec = P(dp_axes or None, head_axis, dist.SEQ_AXIS, None)
    # Full-manual over every mesh axis: axes the spec does not name just see
    # replicated blocks. A partial-manual region (axis_names ⊂ mesh axes)
    # cannot use check_vma=False — None spec entries are then read as
    # replicated-over-ALL-mesh-axes and shard_map rejects the out_specs for
    # every auto axis — and check_vma=True needs vma-annotated out_shapes
    # all the way into the pallas_call, so full-manual is the simple shape.
    axes = set(mesh.axis_names)

    n_ring = mesh.shape[dist.SEQ_AXIS]
    with dist.manual_axes(axes):
        fn = local_fn(n_ring, T // n_ring)
        from . import shard_map_compat
        return shard_map_compat(fn, mesh, (spec, spec, spec), spec)(q, k, v)


def _dense_fallback(q, k, v, causal, block_q, block_kv, scale):
    from .flash_attention import sharded_flash_attention
    return sharded_flash_attention(q, k, v, causal, block_q, block_kv, scale)
