"""Quantization ops: group-wise symmetric/asymmetric int quantize/dequantize.

Counterpart of the reference's ``deepspeed/ops/quantizer`` (CUDA
``ds_quantizer``: ``csrc/quantization/pt_binding.cpp`` quantize/sr_quantize
with grouped scales). On TPU the offline direction (weights -> int8) is plain
XLA below; the *serving* direction — matmul against int8 weights without
ever materializing the bf16 dequantized matrix in HBM — is the Pallas kernel
in ``ops/pallas/quant_matmul.py``.

Convention: per-group scales along the contraction (first) axis of a
(K, N) weight; ``groups`` divides K. Symmetric: q = round(w / s),
s = max|w| / (2^(b-1) - 1) per (group, column).
"""

import jax.numpy as jnp


def _group_reshape(w, groups):
    K = w.shape[0]
    if K % groups != 0:
        raise ValueError(f"groups {groups} must divide contraction dim {K}")
    return w.reshape(groups, K // groups, *w.shape[1:])


def quantize(w, bits=8, groups=1, symmetric=True):
    """w: (K, ...) float -> (q int8, scale fp32, zero fp32 or None).

    ``scale``/``zero`` have shape (groups, 1, ...) broadcastable against the
    grouped weight."""
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    qmax = 2.0**(bits - 1) - 1
    wg = _group_reshape(jnp.asarray(w, jnp.float32), groups)
    if symmetric:
        scale = jnp.max(jnp.abs(wg), axis=1, keepdims=True) / qmax
        scale = jnp.where(scale == 0, 1.0, scale)
        q = jnp.clip(jnp.round(wg / scale), -qmax - 1, qmax)
        return q.reshape(w.shape).astype(jnp.int8), scale, None
    lo = jnp.min(wg, axis=1, keepdims=True)
    hi = jnp.max(wg, axis=1, keepdims=True)
    scale = (hi - lo) / (2.0**bits - 1)
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round((wg - lo) / scale) - 2.0**(bits - 1), -qmax - 1, qmax)
    zero = lo + scale * 2.0**(bits - 1)
    return q.reshape(w.shape).astype(jnp.int8), scale, zero


def dequantize(q, scale, zero=None, groups=None, dtype=jnp.bfloat16):
    """Inverse of :func:`quantize` (group count inferred from ``scale``)."""
    g = scale.shape[0] if groups is None else groups
    qg = _group_reshape(jnp.asarray(q, jnp.float32), g)
    w = qg * scale if zero is None else qg * scale + zero
    return w.reshape(q.shape).astype(dtype)


class Quantizer:
    """Stateful façade mirroring the reference's ``ds_quantizer`` call shape."""

    def __init__(self, bits=8, groups=1, symmetric=True):
        self.bits = bits
        self.groups = groups
        self.symmetric = symmetric

    def quantize(self, w):
        return quantize(w, self.bits, self.groups, self.symmetric)

    def dequantize(self, q, scale, zero=None, dtype=jnp.bfloat16):
        return dequantize(q, scale, zero, self.groups, dtype)
