"""Quantization ops: group-wise symmetric/asymmetric int quantize/dequantize.

Counterpart of the reference's ``deepspeed/ops/quantizer`` (CUDA
``ds_quantizer``: ``csrc/quantization/pt_binding.cpp`` quantize/sr_quantize
with grouped scales). On TPU the offline direction (weights -> int8) is plain
XLA below; the *serving* direction — matmul against int8 weights without
ever materializing the bf16 dequantized matrix in HBM — is the Pallas kernel
in ``ops/pallas/quant_matmul.py``.

Convention: per-group scales along the contraction (first) axis of a
(K, N) weight; ``groups`` divides K. Symmetric: q = round(w / s),
s = max|w| / (2^(b-1) - 1) per (group, column).

4-bit values from :func:`quantize` come back one int8 PER VALUE (the
convenient compute layout); :func:`pack_int4`/:func:`unpack_int4` fold two
of them into one byte so a stored 4-bit tensor actually halves bytes.

The serving KV-cache direction lives here too: :func:`quantize_kv_rows` /
:func:`dequantize_kv_rows` group-quantize per TOKEN ROW (one symmetric
scale shared by K and V across every head's values written at that cache
position — the group is the row), the layout the int8 paged KV tier stores
and the paged Pallas decode kernels dequantize in-register.
"""

import jax.numpy as jnp


def _group_reshape(w, groups):
    K = w.shape[0]
    if K % groups != 0:
        raise ValueError(f"groups {groups} must divide contraction dim {K}")
    return w.reshape(groups, K // groups, *w.shape[1:])


def quantize(w, bits=8, groups=1, symmetric=True):
    """w: (K, ...) float -> (q int8, scale fp32, zero fp32 or None).

    ``scale``/``zero`` have shape (groups, 1, ...) broadcastable against the
    grouped weight."""
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    qmax = 2.0**(bits - 1) - 1
    wg = _group_reshape(jnp.asarray(w, jnp.float32), groups)
    if symmetric:
        scale = jnp.max(jnp.abs(wg), axis=1, keepdims=True) / qmax
        scale = jnp.where(scale == 0, 1.0, scale)
        q = jnp.clip(jnp.round(wg / scale), -qmax - 1, qmax)
        return q.reshape(w.shape).astype(jnp.int8), scale, None
    lo = jnp.min(wg, axis=1, keepdims=True)
    hi = jnp.max(wg, axis=1, keepdims=True)
    scale = (hi - lo) / (2.0**bits - 1)
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round((wg - lo) / scale) - 2.0**(bits - 1), -qmax - 1, qmax)
    zero = lo + scale * 2.0**(bits - 1)
    return q.reshape(w.shape).astype(jnp.int8), scale, zero


def dequantize(q, scale, zero=None, groups=None, dtype=jnp.bfloat16):
    """Inverse of :func:`quantize` (group count inferred from ``scale``)."""
    g = scale.shape[0] if groups is None else groups
    qg = _group_reshape(jnp.asarray(q, jnp.float32), g)
    w = qg * scale if zero is None else qg * scale + zero
    return w.reshape(q.shape).astype(dtype)


def pack_int4(q):
    """Fold a 4-bit-valued int8 tensor (values in [-8, 7], e.g. from
    ``quantize(bits=4)``) into half the bytes: consecutive pairs along the
    FIRST (contraction) axis share one int8 — low nibble = even row, high
    nibble = odd row. The first dim must be even (group quantization
    already requires ``groups | K``, and any even K qualifies)."""
    q = jnp.asarray(q, jnp.int8)
    K = q.shape[0]
    if K % 2:
        raise ValueError(f"pack_int4 needs an even first dim, got {K}")
    lo = q[0::2]
    hi = q[1::2]
    # two's-complement nibbles: keep only the low 4 bits of each value
    return ((lo & 0x0F) | ((hi & 0x0F) << 4)).astype(jnp.int8)


def unpack_int4(p):
    """Inverse of :func:`pack_int4`: (K/2, ...) packed int8 -> (K, ...)
    int8 values in [-8, 7] (sign-extended from each nibble)."""
    p = jnp.asarray(p, jnp.int8)
    lo = (p & 0x0F).astype(jnp.int8)
    hi = ((p >> 4) & 0x0F).astype(jnp.int8)
    # sign-extend the 4-bit two's-complement nibbles
    lo = jnp.where(lo >= 8, lo - 16, lo)
    hi = jnp.where(hi >= 8, hi - 16, hi)
    K2 = p.shape[0]
    out = jnp.empty((2 * K2, ) + p.shape[1:], jnp.int8)
    out = out.at[0::2].set(lo)
    out = out.at[1::2].set(hi)
    return out


def quantize_kv_rows(k, v, scale_dtype=jnp.float16):
    """Joint per-token-row symmetric int8 quantization for the paged KV
    tier.

    ``k``/``v``: (B, heads, T, hd) fresh rows about to be written into the
    cache. ONE scale per (batch row, token), shared by K and V across every
    head: the quantization group is the full written row — the coarsest
    grouping whose error stays bounded by one int8 step of the row's joint
    absmax, and the narrowest scale storage (2 bytes/row total) that keeps
    the int8 tier at >= 1.9x the resident rows of a bf16 pool even at small
    head dims (separate per-tensor or per-head scales eat the savings
    exactly where slots/chip matter). Returns ``(kq, vq, scales
    (B, 1, T, 1))`` — the scale layout mirrors the KV cache row layout so
    the same indexed-write path stores all three."""
    kf = jnp.asarray(k, jnp.float32)
    vf = jnp.asarray(v, jnp.float32)
    amax = jnp.maximum(jnp.max(jnp.abs(kf), axis=(1, 3), keepdims=True),
                       jnp.max(jnp.abs(vf), axis=(1, 3), keepdims=True))  # (B,1,T,1)
    scale = jnp.maximum(amax / 127.0, 1e-8).astype(scale_dtype)
    s32 = scale.astype(jnp.float32)
    kq = jnp.clip(jnp.round(kf / s32), -127, 127).astype(jnp.int8)
    vq = jnp.clip(jnp.round(vf / s32), -127, 127).astype(jnp.int8)
    return kq, vq, scale


def dequantize_kv_rows(q, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv_rows` for the non-kernel (XLA
    fallback) attention path: ``q`` (B, heads, S, hd) int8, ``scale``
    (B, 1, S, 1) -> float rows. The Pallas paged kernels do this multiply
    in-register instead (bf16 KV never lands in HBM)."""
    return (q.astype(jnp.float32) * scale.astype(jnp.float32)).astype(dtype)


class Quantizer:
    """Stateful façade mirroring the reference's ``ds_quantizer`` call shape."""

    def __init__(self, bits=8, groups=1, symmetric=True):
        self.bits = bits
        self.groups = groups
        self.symmetric = symmetric

    def quantize(self, w):
        return quantize(w, self.bits, self.groups, self.symmetric)

    def dequantize(self, q, scale, zero=None, dtype=jnp.bfloat16):
        return dequantize(q, scale, zero, self.groups, dtype)
