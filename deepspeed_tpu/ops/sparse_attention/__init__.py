from .sparsity_config import (SparsityConfig, DenseSparsityConfig, FixedSparsityConfig,  # noqa: F401
                              VariableSparsityConfig, BigBirdSparsityConfig,
                              BSLongformerSparsityConfig, LocalSlidingWindowSparsityConfig)
from .block_sparse_attention import make_block_sparse_attention  # noqa: F401
from .sparse_self_attention import SparseSelfAttention  # noqa: F401
