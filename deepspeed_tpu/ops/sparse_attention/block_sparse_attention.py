"""Pallas block-sparse flash attention.

TPU-native replacement for the reference's Triton block-sparse matmuls
(``deepspeed/ops/sparse_attention/matmul.py`` sdd/dsd/dds +
``softmax.py``): instead of three sparse matmul kernels with a separate
sparse softmax, one flash-style kernel streams only the *active* KV blocks
of each query block row (online softmax, fp32 accumulators, bf16 MXU
operands), and the backward follows the same two-kernel (dq; dkv) split as
the dense flash kernel in ``ops/pallas/flash_attention.py``.

The layout is a compile-time constant: per (head, q-block) the active
kv-block indices are baked into small int32 index tables; each distinct
layout therefore compiles its own kernel (same trade the reference makes —
its Triton kernels JIT per layout too).

Compute cost scales with the number of active blocks, so a sliding-window
layout turns O(T^2) attention into O(T·w) — the long-context story this
subsystem exists for.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _interpret():
    return jax.default_backend() == "cpu"


def _index_tables(layout):
    """(H, nq, nk) 0/1 -> per-row and per-column active index tables.

    Returns (q_idx (H,nq,K), q_cnt (H,nq), kv_idx (H,nk,Kt), kv_cnt (H,nk));
    padding entries repeat index 0 but are never visited (count-bounded
    loops)."""
    H, nq, nk = layout.shape
    q_cnt = layout.sum(-1).astype(np.int32)
    kv_cnt = layout.sum(-2).astype(np.int32)
    K = max(1, int(q_cnt.max()))
    Kt = max(1, int(kv_cnt.max()))
    q_idx = np.zeros((H, nq, K), np.int32)
    kv_idx = np.zeros((H, nk, Kt), np.int32)
    for h in range(H):
        for i in range(nq):
            act = np.nonzero(layout[h, i])[0]
            q_idx[h, i, :len(act)] = act
        for j in range(nk):
            act = np.nonzero(layout[h, :, j])[0]
            kv_idx[h, j, :len(act)] = act
    return q_idx, q_cnt, kv_idx, kv_cnt


def _fwd_kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, block,
                causal, seq_len):
    d = q_ref.shape[-1]
    h = pl.program_id(1)
    qi = pl.program_id(2)
    q_start = qi * block
    q = q_ref[0, 0]

    iq = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
    ik = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
    ikq = ik - iq

    def body(j, carry):
        m, l, acc = carry
        kv_start = pl.multiple_of(idx_ref[h, qi, j] * block, block)
        k = k_ref[0, 0, pl.ds(kv_start, block), :]
        v = v_ref[0, 0, pl.ds(kv_start, block), :]
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = ik < seq_len - kv_start
        if causal:
            mask = mask & (ikq <= q_start - kv_start)
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # explicit zero under the mask: a row whose every visited entry is
        # masked (causal row with only future blocks) must yield p=0 -> l=0
        # -> zero output, not exp(0)=1 against the mask sentinel
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(p.astype(v.dtype), v, (((1, ), (0, )), ((), ())),
                                                preferred_element_type=jnp.float32)
        return m_new, l, acc

    init = (jnp.full((block, 1), -jnp.inf, jnp.float32), jnp.zeros((block, 1), jnp.float32),
            jnp.zeros((block, d), jnp.float32))
    m, l, acc = jax.lax.fori_loop(0, cnt_ref[h, qi], body, init)
    l_safe = jnp.where(l == 0, 1.0, l)
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0] = jnp.where(l == 0, -jnp.inf, m + jnp.log(l_safe))


def _bwd_dq_kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, block, causal, seq_len):
    d = q_ref.shape[-1]
    h = pl.program_id(1)
    qi = pl.program_id(2)
    q_start = qi * block
    q = q_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]

    iq = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
    ik = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
    ikq = ik - iq

    def body(j, dq):
        kv_start = pl.multiple_of(idx_ref[h, qi, j] * block, block)
        k = k_ref[0, 0, pl.ds(kv_start, block), :]
        v = v_ref[0, 0, pl.ds(kv_start, block), :]
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = ik < seq_len - kv_start
        if causal:
            mask = mask & (ikq <= q_start - kv_start)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        return dq + jax.lax.dot_general(ds, k, (((1, ), (0, )), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, cnt_ref[h, qi], body, jnp.zeros((block, d), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, block, causal, seq_len):
    d = k_ref.shape[-1]
    h = pl.program_id(1)
    ki = pl.program_id(2)
    kv_start = ki * block
    k = k_ref[0, 0, pl.ds(kv_start, block), :]
    v = v_ref[0, 0, pl.ds(kv_start, block), :]

    iq = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
    ik = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
    ikq = ik - iq

    def body(n, carry):
        dk, dv = carry
        q_start = pl.multiple_of(idx_ref[h, ki, n] * block, block)
        q = q_ref[0, 0, pl.ds(q_start, block), :]
        do = do_ref[0, 0, pl.ds(q_start, block), :]
        lse = lse_ref[0, 0, pl.ds(q_start, block), :]
        delta = delta_ref[0, 0, pl.ds(q_start, block), :]
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        mask = (ik < seq_len - kv_start) & (iq < seq_len - q_start)
        if causal:
            mask = mask & (ikq <= q_start - kv_start)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dv = dv + jax.lax.dot_general(p.astype(do.dtype), do, (((0, ), (0, )), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        dk = dk + jax.lax.dot_general(ds, q, (((0, ), (0, )), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    zero = jnp.zeros((block, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, cnt_ref[h, ki], body, (zero, zero))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def make_block_sparse_attention(layout, block, causal=True, scale=None):
    """Build an attention fn specialized to a static block ``layout``.

    ``layout``: numpy (H, nq_blocks, nkv_blocks) 0/1. Returns
    ``fn(q, k, v) -> out`` for q/k/v of shape (B, H, T, D) with
    T <= nq_blocks*block (the tail is padded internally). Differentiable
    (custom VJP, same two-kernel split as the dense flash kernel)."""
    layout = np.asarray(layout)
    if layout.ndim != 3:
        raise ValueError(f"layout must be (H, nq, nk), got {layout.shape}")
    q_idx_np, q_cnt_np, kv_idx_np, kv_cnt_np = _index_tables(layout)
    H, nq, nk = layout.shape

    q_idx = jnp.asarray(q_idx_np)
    q_cnt = jnp.asarray(q_cnt_np)  # (H, nq)
    kv_idx = jnp.asarray(kv_idx_np)
    kv_cnt = jnp.asarray(kv_cnt_np)

    def _pad(x, n_blocks):
        t = x.shape[2]
        pad = n_blocks * block - t
        if pad < 0:
            raise ValueError(f"sequence {t} exceeds layout capacity {n_blocks * block}")
        if pad:
            x = jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
        return x

    @jax.custom_vjp
    def attend(q, k, v):
        out, _ = attend_fwd(q, k, v)
        return out

    def _call_fwd(q, k, v):
        B, Hq, T, D = q.shape
        if Hq != H:
            raise ValueError(f"layout built for {H} heads, got {Hq}")
        sc = scale if scale is not None else 1.0 / (D**0.5)
        qp, kp, vp = _pad(q, nq), _pad(k, nk), _pad(v, nk)
        out, lse = pl.pallas_call(
            functools.partial(_fwd_kernel, scale=sc, block=block, causal=causal, seq_len=T),
            grid=(B, H, nq),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1, block, D), lambda b, h, i: (b, h, i, 0)),
                pl.BlockSpec((1, 1, nk * block, D), lambda b, h, i: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, nk * block, D), lambda b, h, i: (b, h, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, block, D), lambda b, h, i: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block, 1), lambda b, h, i: (b, h, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B, H, nq * block, D), q.dtype),
                jax.ShapeDtypeStruct((B, H, nq * block, 1), jnp.float32),
            ],
            interpret=_interpret(),
        )(q_idx, q_cnt, qp, kp, vp)
        return out, lse, (qp, kp, vp)

    def attend_fwd(q, k, v):
        T = q.shape[2]
        out_p, lse, (qp, kp, vp) = _call_fwd(q, k, v)
        return out_p[:, :, :T], (qp, kp, vp, out_p, lse, T)

    def attend_bwd(res, g):
        qp, kp, vp, out_p, lse, T = res
        B, _, Tq, D = qp.shape
        sc = scale if scale is not None else 1.0 / (D**0.5)
        dop = jnp.pad(g, ((0, 0), (0, 0), (0, Tq - T), (0, 0))) if Tq != T else g
        delta = jnp.einsum("bhtd,bhtd->bht", dop.astype(jnp.float32),
                           out_p.astype(jnp.float32))[..., None]
        lse_f = jnp.where(jnp.isfinite(lse), lse, 0.0)  # empty rows: p stays 0 via mask

        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel, scale=sc, block=block, causal=causal, seq_len=T),
            grid=(B, H, nq),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1, block, D), lambda b, h, i: (b, h, i, 0)),
                pl.BlockSpec((1, 1, nk * block, D), lambda b, h, i: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, nk * block, D), lambda b, h, i: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, block, D), lambda b, h, i: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block, 1), lambda b, h, i: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block, 1), lambda b, h, i: (b, h, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, block, D), lambda b, h, i: (b, h, i, 0)),
            out_shape=jax.ShapeDtypeStruct(qp.shape, qp.dtype),
            interpret=_interpret(),
        )(q_idx, q_cnt, qp, kp, vp, dop, lse_f, delta)

        dk, dv = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel, scale=sc, block=block, causal=causal, seq_len=T),
            grid=(B, H, nk),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 1, nq * block, D), lambda b, h, j: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, nk * block, D), lambda b, h, j: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, nk * block, D), lambda b, h, j: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, nq * block, D), lambda b, h, j: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, nq * block, 1), lambda b, h, j: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, nq * block, 1), lambda b, h, j: (b, h, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, block, D), lambda b, h, j: (b, h, j, 0)),
                pl.BlockSpec((1, 1, block, D), lambda b, h, j: (b, h, j, 0)),
            ],
            out_shape=[jax.ShapeDtypeStruct(kp.shape, kp.dtype),
                       jax.ShapeDtypeStruct(vp.shape, vp.dtype)],
            interpret=_interpret(),
        )(kv_idx, kv_cnt, qp, kp, vp, dop, lse_f, delta)
        return dq[:, :, :T], dk[:, :, :T], dv[:, :, :T]

    attend.defvjp(attend_fwd, attend_bwd)
    return attend
