"""SparseSelfAttention: layout-driven attention over (B, H, T, D) tensors.

Counterpart of reference ``ops/sparse_attention/sparse_self_attention.py:19``
(whose forward composes Triton sdd-matmul -> sparse softmax -> dsd-matmul);
here one fused Pallas kernel per layout. The layout/kernel pair is built
lazily per sequence length and cached — layouts are compile-time constants.
"""

from ...utils.logging import logger
from .block_sparse_attention import make_block_sparse_attention


class SparseSelfAttention:

    def __init__(self, sparsity_config, scale=None, max_seq_length=None):
        self.sparsity_config = sparsity_config
        self.scale = scale
        self.max_seq_length = max_seq_length
        self._cache = {}  # seq_len -> attend fn

    def _attend_fn(self, seq_len):
        fn = self._cache.get(seq_len)
        if fn is None:
            cfg = self.sparsity_config
            layout = cfg.make_layout(seq_len)
            causal = getattr(cfg, "attention", "bidirectional") == "unidirectional"
            density = float(layout.mean())
            logger.info(f"SparseSelfAttention: {type(cfg).__name__} layout for seq {seq_len}: "
                        f"{layout.shape[1]}x{layout.shape[2]} blocks of {cfg.block}, "
                        f"density {density:.1%}{' (causal)' if causal else ''}")
            fn = make_block_sparse_attention(layout, cfg.block, causal=causal, scale=self.scale)
            self._cache[seq_len] = fn
        return fn

    def __call__(self, query, key, value):
        """query/key/value: (B, H, T, D) with H == sparsity_config.num_heads
        and T a multiple of the config block size. Returns (B, H, T, D)."""
        if self.max_seq_length is not None and query.shape[2] > self.max_seq_length:
            raise ValueError(f"sequence {query.shape[2]} exceeds max_seq_length "
                             f"{self.max_seq_length}")
        return self._attend_fn(query.shape[2])(query, key, value)
