"""Block-sparse attention layout configurations.

API-parity counterpart of the reference's ``deepspeed/ops/sparse_attention/
sparsity_config.py`` (same class names and constructor parameters; the Triton
block-sparse matmuls behind it become a Pallas kernel here). Each config
produces a layout tensor of shape ``(num_heads, num_blocks, num_blocks)``
with 1 where a (query-block, key-block) tile participates in attention.

The patterns are the published ones the reference implements:
- Fixed (Sparse Transformers, Child et al. 2019): local windows + global
  summary blocks.
- BigBird (Zaheer et al. 2020): sliding window + random + global.
- BSLongformer (Beltagy et al. 2020): sliding window + designated global
  indices.
- Variable: per-window local sizes + random + global, generalizing Fixed.
- LocalSlidingWindow: sliding window only.

Layouts are plain numpy (static with respect to jit): the kernel consumes
them as compile-time constants, so each distinct layout compiles once.
"""

import numpy as np


class SparsityConfig:
    """Base: block size, head count, per-head layout sharing."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len):
        if seq_len % self.block != 0:
            raise ValueError(f"sequence length {seq_len} must be a multiple of block "
                             f"{self.block}")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks), dtype=np.int64)

    def propagate_first_head(self, layout):
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    # subclasses implement make_layout(seq_len)
    def make_layout(self, seq_len):
        raise NotImplementedError

    def _apply_attention_direction(self, layout, attention):
        if attention == "unidirectional":
            # zero strictly-upper-triangular blocks; the in-block diagonal
            # masking happens inside the kernel
            nb = layout.shape[1]
            layout *= np.tril(np.ones((nb, nb), dtype=layout.dtype))[None]
        return layout


class DenseSparsityConfig(SparsityConfig):
    """All blocks active (debug/reference point)."""

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[...] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Local windows of ``num_local_blocks``; the last ``num_global_blocks``
    of each window act as global tokens (column-global, plus row-global when
    ``horizontal_global_attention``). Different heads may use different
    representative blocks (``num_different_global_patterns``)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_local_blocks=4, num_global_blocks=1, attention="bidirectional",
                 horizontal_global_attention=False, num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(f"num_local_blocks {num_local_blocks} must be divisible by "
                             f"num_global_blocks {num_global_blocks}")
        if attention not in ("unidirectional", "bidirectional"):
            raise ValueError(f"invalid attention {attention!r}")
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal_global_attention requires bidirectional attention")
        max_patterns = num_local_blocks // num_global_blocks
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError("num_different_global_patterns > 1 requires "
                             "different_layout_per_head")
        if num_different_global_patterns > max_patterns:
            raise ValueError(f"num_different_global_patterns {num_different_global_patterns} "
                             f"exceeds {max_patterns}")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        for h in range(self.num_layout_heads):
            # local windows (block-diagonal bands of window size)
            for w0 in range(0, nb, self.num_local_blocks):
                w1 = min(w0 + self.num_local_blocks, nb)
                layout[h, w0:w1, w0:w1] = 1
            # global representatives: last num_global_blocks of each window,
            # rotated per head when multiple patterns are requested
            rot = (h % self.num_different_global_patterns) * self.num_global_blocks
            for w0 in range(0, nb, self.num_local_blocks):
                g0 = w0 + self.num_local_blocks - self.num_global_blocks - rot
                if g0 < w0 or g0 >= nb:
                    continue
                g1 = min(g0 + self.num_global_blocks, nb)
                first_row = 0 if self.attention == "bidirectional" else g0
                layout[h, first_row:, g0:g1] = 1  # everyone attends the reps
                if self.horizontal_global_attention:
                    layout[h, g0:g1, :] = 1  # reps attend everyone
        layout = self.propagate_first_head(layout)
        return self._apply_attention_direction(layout, self.attention)


class VariableSparsityConfig(SparsityConfig):
    """Generalized Fixed: random blocks, a list of local window sizes (last
    entry repeats), and explicit global block indices."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=0, local_window_blocks=[4], global_block_indices=[0],
                 global_block_end_indices=None, attention="bidirectional",
                 horizontal_global_attention=False):
        super().__init__(num_heads, block, different_layout_per_head)
        if attention not in ("unidirectional", "bidirectional"):
            raise ValueError(f"invalid attention {attention!r}")
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal_global_attention requires bidirectional attention")
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = list(local_window_blocks)
        self.global_block_indices = list(global_block_indices)
        if global_block_end_indices is not None:
            if len(global_block_end_indices) != len(global_block_indices):
                raise ValueError("global_block_end_indices must pair with global_block_indices")
            self.global_block_end_indices = list(global_block_end_indices)
        else:
            self.global_block_end_indices = None
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention

    def _global_ranges(self, nb):
        if self.global_block_end_indices is None:
            return [(i, i + 1) for i in self.global_block_indices if i < nb]
        return [(s, min(e, nb)) for s, e in zip(self.global_block_indices,
                                                self.global_block_end_indices) if s < nb]

    def make_layout(self, seq_len):
        rng = np.random.default_rng(0)  # deterministic: layouts are compile-time
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        for h in range(self.num_layout_heads):
            if self.num_random_blocks:
                for row in range(nb):
                    cols = rng.choice(nb, size=min(self.num_random_blocks, nb), replace=False)
                    layout[h, row, cols] = 1
            w0 = 0
            wi = 0
            while w0 < nb:
                size = self.local_window_blocks[min(wi, len(self.local_window_blocks) - 1)]
                w1 = min(w0 + size, nb)
                layout[h, w0:w1, w0:w1] = 1
                w0 = w1
                wi += 1
            for g0, g1 in self._global_ranges(nb):
                first_row = 0 if self.attention == "bidirectional" else g0
                layout[h, first_row:, g0:g1] = 1
                if self.horizontal_global_attention:
                    layout[h, g0:g1, :] = 1
        layout = self.propagate_first_head(layout)
        return self._apply_attention_direction(layout, self.attention)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird: random + sliding window + global (first/last blocks)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_random_blocks=1, num_sliding_window_blocks=3, num_global_blocks=1,
                 attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        if attention not in ("unidirectional", "bidirectional"):
            raise ValueError(f"invalid attention {attention!r}")
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention

    def make_layout(self, seq_len):
        rng = np.random.default_rng(0)
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        g = min(self.num_global_blocks, nb)
        for h in range(self.num_layout_heads):
            for row in range(nb):
                lo, hi = max(0, row - w), min(nb, row + w + 1)
                layout[h, row, lo:hi] = 1  # sliding window
                if self.attention == "bidirectional":
                    choices = np.arange(nb)
                else:
                    choices = np.arange(row + 1)
                k = min(self.num_random_blocks, len(choices))
                layout[h, row, rng.choice(choices, size=k, replace=False)] = 1
            layout[h, :, :g] = 1  # global columns (first blocks)
            layout[h, :g, :] = 1  # global rows
            if self.attention == "bidirectional":
                layout[h, :, nb - g:] = 1  # and last blocks
                layout[h, nb - g:, :] = 1
        layout = self.propagate_first_head(layout)
        return self._apply_attention_direction(layout, self.attention)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + designated global indices."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False,
                 num_sliding_window_blocks=3, global_block_indices=[0],
                 global_block_end_indices=None, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = list(global_block_indices)
        if global_block_end_indices is not None:
            if len(global_block_end_indices) != len(global_block_indices):
                raise ValueError("global_block_end_indices must pair with global_block_indices")
        self.global_block_end_indices = (list(global_block_end_indices)
                                         if global_block_end_indices is not None else None)
        self.attention = attention

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        if self.global_block_end_indices is None:
            ranges = [(i, i + 1) for i in self.global_block_indices if i < nb]
        else:
            ranges = [(s, min(e, nb)) for s, e in zip(self.global_block_indices,
                                                      self.global_block_end_indices) if s < nb]
        for h in range(self.num_layout_heads):
            for row in range(nb):
                lo, hi = max(0, row - w), min(nb, row + w + 1)
                layout[h, row, lo:hi] = 1
            for g0, g1 in ranges:
                layout[h, :, g0:g1] = 1
                layout[h, g0:g1, :] = 1
        layout = self.propagate_first_head(layout)
        return self._apply_attention_direction(layout, self.attention)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Sliding window only (cheap long-context autoregression)."""

    def __init__(self, num_heads, block=16, num_sliding_window_blocks=3,
                 attention="unidirectional"):
        super().__init__(num_heads, block)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for row in range(nb):
            if self.attention == "unidirectional":
                lo, hi = max(0, row - (self.num_sliding_window_blocks - 1)), row + 1
            else:
                lo, hi = max(0, row - w), min(nb, row + w + 1)
            layout[0, row, lo:hi] = 1
        layout = self.propagate_first_head(layout)
        return self._apply_attention_direction(layout, self.attention)
