"""Spatial (diffusion) ops: the UNet/VAE elementwise surface.

Counterpart of reference ``csrc/spatial/csrc/opt_bias_add.cu`` +
``pt_binding.cpp`` (the diffusers acceleration kernels: ``bias_add``,
``bias_add_add``, ``bias_add_bias_add`` over NCHW activations) and the
channels-last groupnorm the injected UNet path leans on. On TPU these are
pure fusion targets — XLA folds the adds into the surrounding conv/matmul
epilogues, so the value of this module is API parity plus the NHWC layout
contract (TPU convs want channels-last; the reference's NCHW kernels do
not): conversion utilities included.

The reference's ``generic_injection`` rewrites diffusers' attention modules;
here diffusion attention runs through the same Pallas flash/decode kernels
as the language models (``ops/pallas``) once tensors are in (B, heads, T,
head_dim) — ``spatial_attention`` below does the NHWC<->bhtd plumbing.
"""

import jax
import jax.numpy as jnp


def nchw_to_nhwc(x):
    return jnp.transpose(x, (0, 2, 3, 1))


def nhwc_to_nchw(x):
    return jnp.transpose(x, (0, 3, 1, 2))


def bias_add(activation, bias):
    """NHWC bias add (reference ``opt_bias_add``): activation (B, H, W, C)
    + bias (C,)."""
    return activation + bias.astype(activation.dtype)


def bias_add_add(activation, bias, other):
    """activation + bias + other (reference ``opt_bias_add_add``: the UNet
    residual epilogue)."""
    return activation + bias.astype(activation.dtype) + other


def bias_add_bias_add(activation, bias, other, other_bias):
    """(activation + bias) + (other + other_bias) — reference
    ``opt_bias_add_bias_add``, the dual-stream epilogue."""
    return (activation + bias.astype(activation.dtype)
            + other + other_bias.astype(activation.dtype))


def group_norm_nhwc(x, scale, bias, groups=32, eps=1e-5):
    """GroupNorm over NHWC (B, H, W, C) with fp32 statistics — the UNet/VAE
    normalization the reference runs via torch GroupNorm between its fused
    kernels."""
    B, H, W, C = x.shape
    if C % groups:
        raise ValueError(f"channels {C} not divisible by groups {groups}")
    xg = x.astype(jnp.float32).reshape(B, H, W, groups, C // groups)
    mean = xg.mean(axis=(1, 2, 4), keepdims=True)
    var = xg.var(axis=(1, 2, 4), keepdims=True)
    xn = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(B, H, W, C)
    return (xn * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)


def spatial_attention(q, k, v, heads, block_q=256, block_kv=256):
    """Self-attention over flattened spatial tokens (the diffusers
    ``Attention`` block): q/k/v (B, H*W, C) -> (B, H*W, C), computed through
    the Pallas flash kernel in bhtd layout (non-causal)."""
    from .pallas.flash_attention import flash_attention
    B, T, C = q.shape
    hd = C // heads
    to_bhtd = lambda t: jnp.transpose(t.reshape(B, T, heads, hd), (0, 2, 1, 3))
    out = flash_attention(to_bhtd(q), to_bhtd(k), to_bhtd(v), False,
                          min(block_q, T), min(block_kv, T), None)
    return jnp.transpose(out, (0, 2, 1, 3)).reshape(B, T, C)
