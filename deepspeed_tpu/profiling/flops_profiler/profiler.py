"""FLOPs profiler.

TPU-native analogue of the reference flops profiler
(``deepspeed/profiling/flops_profiler/profiler.py:23`` — module-hook counters
patched over torch functional calls). Under XLA none of that machinery is
needed: the compiler already knows the exact op costs of the compiled
program, exposed through ``compiled.cost_analysis()``; per-module analytic
breakdowns come from ``flax.linen.tabulate``. So this profiler has two
sources:

- **compiled**: ``profile_compiled(fn, *args)`` lowers + compiles and reads
  XLA's cost analysis (true executed FLOPs, including rematerialization —
  the number that explains step time).
- **analytic**: ``get_model_profile(model, input_shape)`` — reference-parity
  standalone API returning (flops, macs, params) for one forward pass, with
  an optional per-module table.

Engine integration: with ``flops_profiler.enabled``, the engine profiles its
compiled train step at ``profile_step`` and logs achieved TFLOP/s vs the
accelerator peak.
"""

import jax
import numpy as np

from ...utils.logging import logger, log_dist


def _cost_analysis(compiled):
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0] if ca else {}
    return dict(ca or {})


def profile_compiled(fn, *args, full_compile=False, **kwargs):
    """Cost analysis of ``fn`` on these args.

    Default path reads the analysis from the *lowering* (pre-optimization
    StableHLO) — tracing only, no XLA compile, so profiling a step the engine
    already compiled does not pay a second multi-minute compilation at 10B+
    scale. ``full_compile=True`` additionally compiles and reports the
    post-optimization numbers plus program memory. Returns
    ``{"flops", "bytes_accessed"[, "peak_memory"]}``."""
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    lowered = jitted.lower(*args, **kwargs)
    if not full_compile:
        try:
            ca = dict(lowered.cost_analysis() or {})
            if ca.get("flops"):
                return {
                    "flops": float(ca.get("flops", 0.0)),
                    "bytes_accessed": float(ca.get("bytes accessed", ca.get("bytes_accessed", 0.0))),
                }
        except Exception:
            pass  # fall through to the compiled path
    compiled = lowered.compile()
    ca = _cost_analysis(compiled)
    out = {
        "flops": float(ca.get("flops", 0.0)),
        "bytes_accessed": float(ca.get("bytes accessed", ca.get("bytes_accessed", 0.0))),
    }
    try:
        mem = compiled.memory_analysis()
        out["peak_memory"] = float(getattr(mem, "temp_size_in_bytes", 0) +
                                   getattr(mem, "argument_size_in_bytes", 0))
    except Exception:
        pass
    return out


class FlopsProfiler:
    """Profiles a DeepSpeedEngine's compiled train step (reference
    ``FlopsProfiler`` object API: start/stop/get_total_*/print)."""

    def __init__(self, model=None, ds_engine=None):
        self.model = model if model is not None else getattr(ds_engine, "module", None)
        self.engine = ds_engine
        self.started = False
        self._stats = {}
        self._steps = 0
        self._t0 = None

    def start_profile(self, ignore_list=None):
        import time
        self.started = True
        self._steps = 0
        self._t0 = time.perf_counter()
        if self.engine is not None and "train_batch" in self.engine._compiled:
            fn = self.engine._compiled["train_batch"]
            # AOT-compiled steps carry their cost analysis; fall back to 0s
            try:
                self._stats = profile_compiled(fn, self.engine.state, None)
            except Exception:
                self._stats = {}

    def record_step(self, compiled_stats=None):
        self._steps += 1
        if compiled_stats:
            self._stats = compiled_stats

    def stop_profile(self):
        import time
        if self._t0 is not None:
            self._stats["duration"] = time.perf_counter() - self._t0
        self.started = False

    def get_total_flops(self, as_string=False):
        f = self._stats.get("flops", 0.0) * max(self._steps, 1)
        return number_to_string(f, "FLOPs") if as_string else f

    def get_total_macs(self, as_string=False):
        m = self.get_total_flops() / 2
        return number_to_string(m, "MACs") if as_string else m

    def get_total_duration(self, as_string=False):
        d = self._stats.get("duration", 0.0)
        return f"{d:.2f} s" if as_string else d

    def get_total_params(self, as_string=False):
        if self.engine is not None:
            n = sum(np.prod(x.shape) for x in jax.tree_util.tree_leaves(self.engine.state.params))
        elif hasattr(self.model, "cfg") and hasattr(self.model.cfg, "num_params"):
            n = self.model.cfg.num_params()
        else:
            n = 0
        return number_to_string(n, "") if as_string else int(n)

    def print_model_profile(self, profile_step=1, module_depth=-1, top_modules=1, detailed=True,
                            output_file=None):
        lines = ["-" * 72, "DeepSpeed-TPU Flops Profiler (XLA cost analysis)", "-" * 72]
        lines.append(f"params:                 {self.get_total_params(as_string=True)}")
        lines.append(f"flops per step:         {number_to_string(self._stats.get('flops', 0), 'FLOPs')}")
        lines.append(f"bytes accessed/step:    {number_to_string(self._stats.get('bytes_accessed', 0), 'B')}")
        if "peak_memory" in self._stats:
            lines.append(f"program memory:         {number_to_string(self._stats['peak_memory'], 'B')}")
        if self._stats.get("duration") and self._steps:
            per_step = self._stats["duration"] / self._steps
            lines.append(f"measured ms/step:       {per_step * 1000:.1f}")
            lines.append(f"achieved TFLOP/s:       {self._stats.get('flops', 0) / per_step / 1e12:.2f}")
        if detailed and hasattr(self.model, "module"):
            try:
                lines.append(module_profile_tree(self.model, depth=module_depth,
                                                 top_modules=top_modules))
            except Exception as e:
                lines.append(f"(per-module table unavailable: {e})")
        report = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(report)
        else:
            logger.info("\n" + report)
        return report


def module_profile_tree(model, batch_size=1, seq_len=None, depth=-1, top_modules=3):
    """Reference-style per-module breakdown (``profiler.py:239``
    ``print_model_profile`` depth/top-k tree): analytic forward FLOPs, MACs
    and params per named module scope, aggregated per depth with the top-k
    heaviest modules at each level and their share of the model total.

    Where the reference counts through torch module hooks, here flax's module
    tracer supplies per-scope flops and variables — same tree, no hooks."""
    import jax.numpy as jnp
    from flax.linen import summary

    cfg = model.cfg
    T = seq_len or min(cfg.max_seq_len, 512)
    ids = jnp.zeros((batch_size, T), jnp.int32)
    table = summary._get_module_table(model.module, depth=None, show_repeated=False,
                                      compute_flops=True, compute_vjp_flops=False)(
        {"params": jax.random.key(0)}, ids)

    def row_params(row):
        if not row.counted_variables:
            return 0
        import jax as _jax
        return sum(int(np.prod(v.shape)) for col in row.counted_variables.values()
                   for v in _jax.tree_util.tree_leaves(col))

    raw = [(row.path, type(row.module_copy).__name__,
            float(row.flops) if row.flops not in (None, ) else 0.0, row_params(row))
           for row in table]
    # aggregate params over descendants (flax counts each variable once, at
    # its owning leaf scope)
    rows = [(p, cls, f, sum(pr2 for p2, _, _, pr2 in raw if p2[:len(p)] == p))
            for p, cls, f, _ in raw]
    total_flops = next((f for p, _, f, _ in rows if p == ()), 0.0) or 1.0
    total_params = next((pr for p, _, _, pr in rows if p == ()), 0)
    max_depth = max((len(p) for p, _, _, _ in rows), default=0)
    if depth is None or depth < 0:
        depth = min(max_depth, 3)

    lines = [f"per-module forward profile (bs={batch_size}, seq={T}; "
             f"total {number_to_string(total_flops, 'FLOPs')}, "
             f"{number_to_string(total_params, 'params')}):"]
    for d in range(1, depth + 1):
        level = [(p, cls, f, pr) for p, cls, f, pr in rows if len(p) == d]
        if not level:
            break
        level.sort(key=lambda r: -r[2])
        lines.append(f"depth {d} (top {min(top_modules, len(level))} of {len(level)} modules "
                     f"by fwd FLOPs):")
        for p, cls, f, pr in level[:top_modules]:
            name = "/".join(p)
            lines.append(f"  {name:<34s} {cls:<16s} "
                         f"{number_to_string(pr, 'params'):>14s} "
                         f"{number_to_string(f / 2, 'MACs'):>12s} {100 * f / total_flops:5.1f}%")
    return "\n".join(lines)


def get_model_profile(model, input_shape=None, args=None, print_profile=True, detailed=True,
                      module_depth=-1, top_modules=1, as_string=True, output_file=None,
                      ignore_modules=None, batch=None):
    """Standalone forward-pass profile (reference ``get_model_profile``):
    returns (flops, macs, params) for one forward on ``input_shape`` =
    (batch, seq) token ids, computed by compiling the forward with XLA and
    reading its cost analysis."""
    import jax.numpy as jnp

    if batch is None:
        if input_shape is None:
            raise ValueError("provide input_shape=(batch, seq) or a batch dict")
        rng = np.random.default_rng(0)
        batch = {"input_ids": rng.integers(0, model.cfg.vocab_size, input_shape).astype(np.int32)}
    params = jax.eval_shape(model.init_params, jax.random.key(0))
    params = jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), params)

    def fwd(p, ids):
        return model.apply(p, ids)

    stats = profile_compiled(fwd, params, batch["input_ids"])
    flops = stats["flops"]
    macs = flops / 2
    n_params = model.cfg.num_params() if hasattr(model.cfg, "num_params") else 0

    if print_profile:
        log_dist(f"get_model_profile: flops={number_to_string(flops, 'FLOPs')} "
                 f"macs={number_to_string(macs, 'MACs')} params={number_to_string(n_params, '')}", [0])
    if as_string:
        return (number_to_string(flops, "FLOPs"), number_to_string(macs, "MACs"),
                number_to_string(n_params, ""))
    return flops, macs, n_params


def number_to_string(num, unit):
    for scale, prefix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(num) >= scale:
            return f"{num / scale:.2f} {prefix}{unit}"
    return f"{num:.0f} {unit}"
