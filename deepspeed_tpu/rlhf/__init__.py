"""RLHF hybrid-engine subsystem: in-memory train↔generate weight handoff
through the continuous-batching scheduler.

TPU-native analogue of the reference ``DeepSpeedHybridEngine``
(``runtime/hybrid_engine.py:32``, the DeepSpeed-Chat actor pattern where
rollout generation alternates with PPO updates every step), rebuilt on the
modern serving stack instead of a raw cast-and-generate:

- :class:`WeightPublisher` snapshots the training engine's parameters into
  the inference compute layout as a versioned, generation-tagged
  :class:`Publication` (cast + reshard ONCE per publication, compiled once
  per layout) and installs it through the scheduler's
  ``pause -> flush -> swap_weights -> resume`` protocol — an in-memory swap
  with zero checkpoint round-trips and zero new XLA programs per cycle.
- :class:`RolloutCollector` runs prompt batches through
  ``DecodeScheduler.submit()``, so rollouts get everything serving has:
  chunked prefill, radix prefix-cache hits on the shared prompt template,
  speculative decoding, and per-request traces — and returns
  token/logprob/reward sequences into a :class:`RolloutBuffer`.
- ``runtime/hybrid_engine.DeepSpeedHybridEngine`` orchestrates the
  train -> generate -> train loop (N rollout rounds per publication, M
  PPO-shaped updates per rollout buffer, pluggable reward fn and update
  hook).

See ``benchmarks/RLHF.md`` for the loop shape, swap semantics, and the
staleness-vs-throughput tuning notes.
"""

from .publisher import Publication, WeightPublisher  # noqa: F401
from .rollout import RolloutBuffer, RolloutCollector, RolloutSample  # noqa: F401
