"""Weight publication: training masters -> inference compute layout, as a
versioned in-memory swap.

The reference hybrid engine flips between ZeRO-3 training modules and
kernel-injected inference containers that share weight storage
(``create_inference_module`` :298); DeepSpeed-Chat pays a gather/scatter
bookkeeping pass around every rollout phase. Here both modes are pure
functions over parameter pytrees, so a publication is ONE compiled
cast+reshard program: merge LoRA adapters (unless already fused), cast the
fp32 masters to the inference compute dtype, restack/unstack to the
inference module's layer layout, and land the result in the inference
sharding — all inside a single jit whose output is an OWNED tree (no leaf
aliases live training state, so the publication stays frozen while training
steps on).

Publications are generation-tagged: each fresh snapshot gets a monotonically
increasing ``version`` and records the training step it was cut at, and the
snapshot is cached against ``(global_steps, lora_fused)`` so back-to-back
rollouts between updates reuse the same tree (the identity-keyed
``_fast_tree_cache`` and the scheduler's step programs then see literally
the same object — nothing recompiles, nothing re-casts).

Installing a publication goes through the scheduler's swap protocol
(``pause -> flush -> swap_weights -> resume``): in-flight decode rows finish
under the weights that prefilled them, every retained prefix and radix
registration is invalidated (KV computed under stale weights must never be
served against new weights — enforced by the version stamps in
``inference/kv_cache.py``), and the new tree becomes the one every
subsequent dispatch reads. The whole cycle adds ZERO new XLA programs after
the first publication: the cast program is cached, and the step programs
take params as an argument.
"""

import time
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class Publication(NamedTuple):
    """One published weight generation."""
    version: int   # monotonic publication number (the KV version tag's peer)
    step: int      # training global step the snapshot was cut at
    params: Any    # device tree in the inference engine's compute layout


class WeightPublisher:
    """Snapshots a training :class:`DeepSpeedEngine`'s parameters into an
    inference engine's compute layout and installs them via the scheduler
    swap protocol. One publisher per (train engine, inference engine) pair;
    NOT thread-safe — drive it from the thread that pumps the scheduler."""

    def __init__(self, train_engine, infer_engine):
        self.train = train_engine
        self.infer = infer_engine
        self.version = 0          # last snapshot's tag; 0 = nothing published
        self.live = None          # Publication currently installed (or None)
        self._snap = None         # (cache_key, Publication) of the last snapshot
        self._compiled = {}       # (path, fused) -> compiled cast program
        self.telemetry = train_engine.telemetry

    # ------------------------------------------------------------------ snapshot
    def _lora(self):
        from ..runtime.lora import LoRAModel
        m = self.train.module
        return m if isinstance(m, LoRAModel) else None

    def _build_cast(self, fused, src):
        """The ONE cast+reshard program for this (source path, LoRA-fusion)
        combination: merge adapters -> cast to the inference compute dtype
        -> adapt the layer layout (stacked <-> unrolled) — out-shardings are
        the inference planner's, so XLA inserts whatever resharding
        collectives the layouts require. ``src`` is the already-gathered
        master tree (eval_shape only reads shapes, so the expensive
        param_stream host assembly is NOT repeated here)."""
        infer = self.infer
        dtype = infer.model_config.dtype
        lora = self._lora()

        def fn(p):
            if lora is not None:
                p = p["base"] if fused else lora.merge(p)
            p = jax.tree_util.tree_map(lambda x: jnp.asarray(x, dtype), p)
            return infer._adapt_layout(p)

        abstract = jax.eval_shape(fn, src)
        shardings = infer.planner.shardings(infer.planner.master_specs(abstract))
        return jax.jit(fn, out_shardings=shardings)

    def _masters(self, path):
        if path == "param_stream":
            # ZeRO-Infinity: masters live in host blocks; get_params_tree
            # assembles an OWNED fp32 host copy (PR 5 contract)
            return self.train.param_stream.get_params_tree(np.float32)
        return self.train.state.params

    def snapshot(self):
        """A :class:`Publication` of the CURRENT training weights. Cached
        against ``(global_steps, lora_fused)``: repeated rollouts between
        optimizer updates reuse the same tree (identity-stable, so nothing
        downstream re-keys); the next update cuts a fresh version."""
        train = self.train
        fused = bool(getattr(train, "_lora_fused", False))
        path = "param_stream" if train.param_stream is not None else "device"
        key = (int(train.global_steps), fused)
        if self._snap is not None and self._snap[0] == key:
            return self._snap[1]
        src = self._masters(path)  # gathered ONCE (param_stream assembly is a full host copy)
        ckey = (path, fused)
        if ckey not in self._compiled:
            self._compiled[ckey] = self._build_cast(fused, src)
        with train.mesh:
            params = self._compiled[ckey](src)
        self.version += 1
        pub = Publication(self.version, key[0], params)
        self._snap = (key, pub)
        return pub

    # ------------------------------------------------------------------ publish
    def publish(self, scheduler=None):
        """Snapshot + install: drive the scheduler's
        ``pause -> flush -> swap_weights -> resume`` protocol (or a plain
        assignment when no scheduler exists yet). A publication that is
        already live is a no-op — ``generate()``-per-rollout callers pay
        nothing between updates. Returns the live :class:`Publication`."""
        tel = self.telemetry
        t0 = time.perf_counter()
        pub = self.snapshot()
        sched = scheduler if scheduler is not None else self.infer._scheduler
        if (self.live is not None and pub is self.live
                and (sched is None or sched.published_version == pub.version)):
            return pub  # already live AND the scheduler's bookkeeping agrees
        # a scheduler built AFTER a pre-scheduler publish (legacy generate()
        # first) re-installs the live publication through the swap protocol
        # so published_version/weights_version stay in lockstep with it
        if sched is not None:
            sched.pause()
            try:
                sched.flush()
                sched.swap_weights(pub.params, version=pub.version)
            finally:
                sched.resume()
        else:
            self.infer.params = pub.params
        self.live = pub
        if tel.enabled:
            dur = time.perf_counter() - t0
            tel.histogram("rlhf/publish_ms", dur * 1e3)
            tel.counter("rlhf/publications")
            tel.record_span("rlhf/publish", tel.now() - dur, dur,
                            attrs={"version": pub.version, "step": pub.step})
            tel.gauge("rlhf/staleness_steps", self.staleness_steps())
        return pub

    def publish_adapter(self, adapter_id, store=None):
        """Per-tenant ADAPTER-DELTA publication (multi-LoRA serving): snapshot
        only the training :class:`~deepspeed_tpu.runtime.lora.LoRAModel`'s
        adapter leaves and register them into the serving fleet's paged
        adapter store as ``adapter_id``'s next version — the base weight
        tree is untouched, so no pause/flush/swap cycle runs and co-resident
        tenants keep decoding. Isolation rides the store's version tags: the
        re-registration mints a fresh uid, every scheduler's invalidation
        listener reclaims the OLD uid's KV/prefix registrations on its own
        pump thread, and in-flight requests finish on the page they pinned.
        Returns the new adapter version.

        This is how per-tenant policy variants ship: N RLHF loops fine-tune
        adapters over one frozen base, and each ``publish_adapter`` makes
        that tenant's latest policy servable side-by-side with every other
        tenant's — no merged-weight swap rotation, no recompiles (the pool
        shapes are fixed by the rank-bucket config)."""
        tel = self.telemetry
        t0 = time.perf_counter()
        lora = self._lora()
        if lora is None:
            raise ValueError("publish_adapter requires the training engine to "
                             "wrap a LoRAModel (adapter-only training)")
        path = "param_stream" if self.train.param_stream is not None else "device"
        masters = self._masters(path)
        tree = jax.device_get(masters["lora"])
        if store is None:
            store = self.infer.adapter_store()
        version = store.register(adapter_id, lora_tree=tree, alpha=lora.alpha,
                                 rank=lora.r)
        if tel.enabled:
            dur = time.perf_counter() - t0
            tel.histogram("rlhf/adapter_publish_ms", dur * 1e3)
            tel.counter("rlhf/adapter_publications")
            tel.record_span("rlhf/publish_adapter", tel.now() - dur, dur,
                            attrs={"adapter_id": adapter_id, "version": version,
                                   "step": int(self.train.global_steps)})
        return version

    def staleness_steps(self):
        """Optimizer steps taken since the live publication was cut — the
        off-policy gap rollouts currently decode under (0 right after a
        publish; grows by M across each update phase)."""
        if self.live is None:
            return 0
        return int(self.train.global_steps) - self.live.step
