"""Rollout collection through the continuous-batching scheduler.

The seed-era hybrid stub generated rollouts with the static-batch
``generate()`` program — one compiled shape per (batch, prompt-bucket),
no cross-request batching, no prefix reuse. The collector instead submits
every prompt through ``DecodeScheduler.submit()``, so rollouts ride the
full serving stack: iteration-level continuous batching, chunked prefill,
radix prefix-cache hits on the shared prompt template (RLHF prompt sets
share long system/task prefixes — exactly the radix cache's best case),
speculative decoding when configured, and per-request traces.

Each finished request becomes a :class:`RolloutSample` carrying the chosen
tokens, their log-probabilities under the weights that generated them (the
PPO "old logprobs", computed from the scheduler's collected per-step
logits and tagged with the publication version), and a scalar reward from
the pluggable reward fn. Samples accumulate in a :class:`RolloutBuffer`
that shapes PPO-style update batches.
"""

import time

import numpy as np


def _logprobs_of(logits, tokens):
    """Per-step log P(token) from a (T, V) float32 logits block — the
    numerically-stable log-softmax row-gather."""
    if len(tokens) == 0:
        return np.zeros(0, np.float32)
    l = logits[:len(tokens)].astype(np.float64)
    l = l - l.max(axis=-1, keepdims=True)
    lse = np.log(np.exp(l).sum(axis=-1))
    rows = np.arange(len(tokens))
    return (l[rows, np.asarray(tokens)] - lse).astype(np.float32)


class RolloutSample:
    """One prompt -> completion rollout, frozen at collection time."""

    __slots__ = ("prompt", "tokens", "logprobs", "reward", "version", "rid")

    def __init__(self, prompt, tokens, logprobs, reward, version, rid=None):
        self.prompt = np.asarray(prompt, np.int32)
        self.tokens = np.asarray(tokens, np.int32)
        self.logprobs = np.asarray(logprobs, np.float32)
        self.reward = float(reward)
        self.version = version  # weights publication the rollout decoded under
        self.rid = rid

    def __len__(self):
        return int(self.tokens.size)


class RolloutBuffer:
    """Accumulates :class:`RolloutSample`\\ s across collect rounds and
    shapes PPO-style update batches."""

    def __init__(self):
        self.samples = []

    def add(self, sample):
        self.samples.append(sample)

    def __len__(self):
        return len(self.samples)

    def clear(self):
        self.samples = []

    def total_tokens(self):
        return int(sum(len(s) for s in self.samples))

    def versions(self):
        """Distinct publication versions represented in the buffer (a
        single-version buffer is fully on-policy w.r.t. its publication)."""
        return sorted({s.version for s in self.samples})

    def ppo_batch(self, batch_size, pad_token_id=0, start=0, bucket=64,
                  max_len=None):
        """One PPO-shaped update batch of exactly ``batch_size`` rows
        (cycling through the buffer from ``start`` when it is smaller):

        - ``input_ids`` (B, T): prompt + completion, right-padded,
        - ``labels`` (B, T): pre-shifted next-token targets with ``-100``
          on padding (the stock LM loss ignores them — the default update
          must never spend gradient learning to emit the pad token),
        - ``loss_mask`` (B, T): 1.0 on completion tokens (the only
          positions a policy-gradient loss should touch),
        - ``old_logprobs`` (B, T): log P(token) under the generating
          publication, 0 off-completion,
        - ``rewards`` (B,), ``advantages`` (B,): sequence reward and its
          group-mean-baselined advantage (the minimal PPO shape — swap in
          a learned critic via a custom update hook).

        ``T`` rounds the batch's longest row up to a power-of-two bucket
        (floor ``bucket``, capped at ``max_len``) so rotating prompt sets
        and per-epoch row windows reuse ONE compiled train-step program
        per bucket instead of retracing on every distinct length — the
        same geometric-bucket trick the serving prefill path uses.
        ``bucket=0``/``None`` pads to the exact max row length.
        """
        if not self.samples:
            raise ValueError("ppo_batch on an empty RolloutBuffer")
        rows = [self.samples[(start + i) % len(self.samples)]
                for i in range(batch_size)]
        raw = max(len(r.prompt) + len(r.tokens) for r in rows)
        T = raw
        if bucket:
            T = int(bucket)
            while T < raw:
                T *= 2
        if max_len is not None:
            if raw > max_len:
                raise ValueError(f"rollout rows of {raw} tokens exceed max_len {max_len}")
            T = min(T, int(max_len))
        ids = np.full((batch_size, T), pad_token_id, np.int32)
        labels = np.full((batch_size, T), -100, np.int32)
        mask = np.zeros((batch_size, T), np.float32)
        oldlp = np.zeros((batch_size, T), np.float32)
        rewards = np.zeros(batch_size, np.float32)
        for i, r in enumerate(rows):
            p, g = len(r.prompt), len(r.tokens)
            ids[i, :p] = r.prompt
            ids[i, p:p + g] = r.tokens
            labels[i, :p + g - 1] = ids[i, 1:p + g]
            mask[i, p:p + g] = 1.0
            oldlp[i, p:p + g] = r.logprobs
            rewards[i] = r.reward
        return {"input_ids": ids, "labels": labels, "loss_mask": mask,
                "old_logprobs": oldlp, "rewards": rewards,
                "advantages": rewards - rewards.mean()}


class RolloutCollector:
    """Submits prompt batches through the shared scheduler and harvests
    token/logprob/reward sequences. ``reward_fn(prompt, tokens) -> float``
    is pluggable (default 0.0 — reward models hang off here)."""

    def __init__(self, engine, reward_fn=None):
        self.engine = engine
        self.reward_fn = reward_fn
        self.telemetry = engine.telemetry
        self.total_tokens = 0
        self.total_requests = 0

    def collect(self, prompts, max_new_tokens=64, eos_token_id=None,
                do_sample=False, temperature=1.0, top_k=0, top_p=1.0,
                seed=0, buffer=None, reward_fn=None, version=None):
        """One rollout round: every prompt through
        ``DecodeScheduler.submit(collect_logits=True)``, results into
        ``buffer`` (a fresh :class:`RolloutBuffer` when None). Old
        logprobs come from the per-step logits the scheduler already
        collects — bit-identical to what any serving client would see,
        because they ARE the serving path's logits."""
        sched = self.engine.scheduler()
        reward_fn = reward_fn if reward_fn is not None else self.reward_fn
        if version is None:
            version = (sched.published_version
                       if sched.published_version is not None
                       else sched.weights_version)
        buf = buffer if buffer is not None else RolloutBuffer()
        tel = self.telemetry
        # PR 8 request tracing covers rollouts too: each one gets its own
        # req/* span tree (prefix_probe -> prefill chunks -> decode ->
        # complete), flow-linked to the shared sched/step iterations
        tracing = tel.enabled and getattr(tel, "trace_requests", False)
        t0 = time.perf_counter()
        handles = []
        try:
            for i, prompt in enumerate(prompts):
                trace = None
                if tracing:
                    from ..telemetry import RequestTrace
                    trace = RequestTrace(tel, rollout=True, version=version)
                handles.append(
                    (prompt, sched.submit(prompt, max_new_tokens=max_new_tokens,
                                          eos_token_id=eos_token_id,
                                          do_sample=do_sample,
                                          temperature=temperature, top_k=top_k,
                                          top_p=top_p, seed=seed + i,
                                          collect_logits=True, trace=trace)))
        except Exception:
            for _, h in handles:  # don't orphan already-queued rollouts
                h.cancel()
            raise
        n_tokens = 0
        try:
            for prompt, h in handles:
                tokens = h.result()
                logits = h.result_logits()
                lp = _logprobs_of(logits, tokens)
                reward = float(reward_fn(prompt, tokens)) if reward_fn else 0.0
                buf.add(RolloutSample(prompt, tokens, lp, reward, version,
                                      rid=h._req.rid))
                n_tokens += len(tokens)
        except Exception:
            # a mid-harvest failure (reward_fn raised, one request errored)
            # must not leave the REST of the round occupying slots on the
            # shared scheduler: the propagating traceback pins this frame's
            # `handles`, so __del__-based cancellation would never fire
            for _, h in handles:
                if not h.done:
                    h.cancel()
            raise
        dur = max(time.perf_counter() - t0, 1e-9)
        self.total_tokens += n_tokens
        self.total_requests += len(handles)
        if tel.enabled:
            tel.gauge("rlhf/rollout_tok_s", n_tokens / dur)
            tel.counter("rlhf/rollout_tokens", n_tokens)
            tel.counter("rlhf/rollout_requests", len(handles))
        return buf
