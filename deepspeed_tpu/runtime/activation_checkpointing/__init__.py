from . import checkpointing  # noqa: F401
