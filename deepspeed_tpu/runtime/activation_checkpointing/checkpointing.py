"""Activation checkpointing API (reference
``runtime/activation_checkpointing/checkpointing.py``: ``checkpoint`` :708,
``configure`` :789, ``is_configured`` :871, ``CheckpointFunction`` :474).

Design translation: the reference reimplements torch autograd checkpointing
with partitioned + CPU-offloaded activations and RNG bookkeeping (~900 LoC).
Under XLA every piece collapses into ``jax.checkpoint``:

- recompute-in-backward  -> ``jax.checkpoint`` itself (policy-driven),
- partition_activations  -> saved residuals keep their sharding; XLA SPMD
  already stores each shard's slice only — nothing to partition by hand,
- cpu_checkpointing      -> ``jax.checkpoint`` + host offload of residuals is
  a placement policy (``save_and_offload_only_these_names``),
- contiguous_memory_optimization / synchronize / profile -> allocator and
  scheduler concerns XLA owns.

``checkpoint(function, *args)`` therefore IS ``jax.checkpoint`` with the
configured policy; models built from ``deepspeed_tpu.models`` normally use
the ``activation_checkpointing`` config section instead (engine applies the
remat policy to the layer stack), and this module serves code written
against the reference's functional API.
"""

import jax

from ...utils.logging import logger

_config = {
    "partition_activations": False,
    "contiguous_memory_optimization": False,
    "cpu_checkpointing": False,
    "number_checkpoints": None,
    "synchronize_checkpoint_boundary": False,
    "profile": False,
    "configured": False,
    "policy": None,
}


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None, checkpoint_in_cpu=None,
              synchronize=None, profile=None):
    """Record the reference knobs; the ones with no XLA meaning warn once.
    ``deepspeed_config``: dict (or object with ``raw_config``) whose
    ``activation_checkpointing`` section seeds the keyword defaults, exactly
    as the reference reads its json."""
    _config["configured"] = True
    if deepspeed_config is not None:
        raw = getattr(deepspeed_config, "raw_config", deepspeed_config)
        sec = dict(dict(raw).get("activation_checkpointing", {}))
        if partition_activations is None:
            partition_activations = sec.get("partition_activations")
        if contiguous_checkpointing is None:
            contiguous_checkpointing = sec.get("contiguous_memory_optimization")
        if num_checkpoints is None:
            num_checkpoints = sec.get("number_checkpoints")
        if checkpoint_in_cpu is None:
            checkpoint_in_cpu = sec.get("cpu_checkpointing")
        if synchronize is None:
            synchronize = sec.get("synchronize_checkpoint_boundary")
        if profile is None:
            profile = sec.get("profile")
    if partition_activations is not None:
        _config["partition_activations"] = partition_activations
    if num_checkpoints is not None:
        _config["number_checkpoints"] = num_checkpoints
    if checkpoint_in_cpu:
        _config["cpu_checkpointing"] = True
        # offload the residuals this codebase names via checkpoint_name (the
        # flash kernel outputs — the big per-layer activations worth hosting)
        _config["policy"] = jax.checkpoint_policies.save_and_offload_only_these_names(
            names_which_can_be_saved=[],
            names_which_can_be_offloaded=["flash_out", "flash_lse"],
            offload_src="device", offload_dst="pinned_host")
    for name, val in (("contiguous_checkpointing", contiguous_checkpointing),
                      ("synchronize", synchronize), ("profile", profile)):
        if val:
            logger.warning(f"activation checkpointing: {name} has no XLA equivalent "
                           f"(allocator/scheduler owned); accepted as a no-op")


def is_configured():
    return _config["configured"]


def reset():
    _config["configured"] = False
    _config["policy"] = None


def checkpoint(function, *args):
    """Recompute ``function``'s activations in backward (``jax.checkpoint``)."""
    return jax.checkpoint(function, policy=_config["policy"])(*args)


def model_parallel_cuda_manual_seed(seed):
    """Reference RNG bookkeeping shim: JAX threads explicit PRNG keys, so a
    global device seed has nothing to set; returns the key for callers that
    want one."""
    return jax.random.key(seed)


class CheckpointFunction:
    """Reference-shaped alias: ``CheckpointFunction.apply(fn, *args)``."""

    @staticmethod
    def apply(function, *args):
        return checkpoint(function, *args)
