"""Checkpoint save/load.

Analogue of reference ``deepspeed/runtime/checkpoint_engine/`` (pluggable
``CheckpointEngine`` ABC, torch + async Nebula backends) and of the
save/load paths in ``engine.py:2802/:2497``. Backend is Orbax: arrays are
written as *global logical tensors* regardless of mesh layout, which gives
the universal-checkpoint property (reference ``deepspeed/checkpoint/``
offline 3D reshape machinery) by construction — restoring onto a different
mesh/ZeRO stage is just restoring with different target shardings.

Layout per checkpoint dir (DeepSpeed-compatible shape):
    <save_dir>/<tag>/state/        orbax pytree (sharded arrays)
    <save_dir>/<tag>/client_sd.json
    <save_dir>/latest              text file holding the newest tag
"""

import json
import os
import threading

import jax
import numpy as np

from ...utils.logging import logger


class CheckpointEngine:
    """Pluggable backend ABC (reference ``checkpoint_engine.py:9``)."""

    def __init__(self, config_params=None):
        pass

    def create(self, tag):
        pass

    def save(self, state_dict, path):
        raise NotImplementedError

    def load(self, path, map_location=None):
        raise NotImplementedError

    def commit(self, tag):
        return True


class OrbaxCheckpointEngine(CheckpointEngine):

    def __init__(self, config_params=None, use_async=False):
        super().__init__(config_params)
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.use_async = use_async
        self._ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler()) if use_async \
            else ocp.Checkpointer(ocp.PyTreeCheckpointHandler())

    def save(self, state, path):
        self._ckptr.save(os.path.abspath(path), state, force=True)

    def load(self, path, abstract_target=None, partial=False):
        import orbax.checkpoint as ocp
        restore_args = None
        if abstract_target is not None:
            restore_args = ocp.checkpoint_utils.construct_restore_args(abstract_target)
            return self._ckptr.restore(os.path.abspath(path),
                                       args=ocp.args.PyTreeRestore(
                                           item=abstract_target,
                                           restore_args=restore_args,
                                           partial_restore=partial))
        return self._ckptr.restore(os.path.abspath(path))

    def commit(self, tag):
        if self.use_async:
            self._ckptr.wait_until_finished()
        return True


def _latest_path(save_dir):
    return os.path.join(save_dir, "latest")


def get_latest_tag(load_dir):
    p = _latest_path(load_dir)
    if os.path.isfile(p):
        with open(p) as f:
            return f.read().strip()
    return None


# one long-lived async engine (an AsyncCheckpointer owns a background
# thread pool; creating one per save would leak threads) + the in-flight
# finalizer thread, which writes 'latest' once the write is durable
_async_engine = None
_pending_commit = None
_pending_error = None
_atexit_registered = False


def _drain_pending_at_exit():
    try:
        wait_pending_saves()
    except Exception as e:
        logger.error(f"async checkpoint failed during interpreter exit: {e!r}")


def _get_async_engine():
    global _async_engine
    if _async_engine is None:
        _async_engine = OrbaxCheckpointEngine(use_async=True)
    return _async_engine


def wait_pending_saves():
    """Block until any in-flight async checkpoint is fully committed and its
    'latest' pointer written. Call before load, exit, or dependent work.
    Re-raises any failure from the background commit — a silently lost
    checkpoint must not be discovered at restore time."""
    global _pending_commit, _pending_error
    if _pending_commit is not None:
        _pending_commit.join()
        _pending_commit = None
    if _pending_error is not None:
        err, _pending_error = _pending_error, None
        raise RuntimeError("async checkpoint save failed in the background") from err


def save_checkpoint(save_dir, tag, state, client_sd, save_latest=True, use_async=False):
    global _pending_commit
    wait_pending_saves()  # serialize with a previous in-flight save
    ckpt_dir = os.path.join(os.path.abspath(save_dir), str(tag))
    os.makedirs(ckpt_dir, exist_ok=True)
    engine = _get_async_engine() if use_async else OrbaxCheckpointEngine()
    engine.save(state, os.path.join(ckpt_dir, "state"))
    if jax.process_index() == 0:
        with open(os.path.join(ckpt_dir, "client_sd.json"), "w") as f:
            json.dump(_jsonable(client_sd), f, indent=2)

    # 'latest' moves only once the write is durable (commit blocks on the
    # async writer), so a crash mid-save can never leave 'latest' pointing at
    # a partial checkpoint. In async mode that finalization overlaps training
    # on a daemon thread (the reference's Nebula tiered-commit pattern,
    # nebula_checkpoint_engine.py:20).
    def finalize():
        engine.commit(tag)
        if save_latest and jax.process_index() == 0:
            with open(_latest_path(save_dir), "w") as f:
                f.write(str(tag))

    def finalize_capturing():
        global _pending_error
        try:
            finalize()
        except BaseException as e:  # surfaced by the next wait_pending_saves()
            _pending_error = e
            logger.error(f"async checkpoint commit for tag {tag} failed: {e!r}")

    if use_async:
        global _atexit_registered
        if not _atexit_registered:
            # a normal interpreter exit must not kill an in-flight commit
            import atexit
            atexit.register(_drain_pending_at_exit)
            _atexit_registered = True
        _pending_commit = threading.Thread(target=finalize_capturing, daemon=True,
                                           name=f"ckpt-commit-{tag}")
        _pending_commit.start()
    else:
        finalize()


def load_checkpoint(load_dir, tag, state_shardings, mesh, template, load_optimizer_states=True,
                    load_module_only=False):
    wait_pending_saves()
    load_dir = os.path.abspath(load_dir)
    if tag is None:
        tag = get_latest_tag(load_dir)
        if tag is None:
            logger.warning(f"no 'latest' file found in {load_dir}; cannot auto-resume")
            return None, None
    ckpt_dir = os.path.join(load_dir, str(tag))
    state_path = os.path.join(ckpt_dir, "state")
    if not os.path.isdir(state_path):
        logger.warning(f"checkpoint {state_path} does not exist")
        return None, None

    # abstract target: shapes/dtypes from the live state, shardings from plan —
    # this is what makes the checkpoint mesh-layout-independent
    abstract = jax.tree_util.tree_map(
        lambda x, s: jax.ShapeDtypeStruct(np.shape(x), x.dtype, sharding=s), template, state_shardings)
    engine = OrbaxCheckpointEngine()
    # a target with no optimizer leaves (ZeRO-Offload engines, module-only
    # loads) restores a subset of what a device-optimizer engine saved; the
    # state NamedTuple is serialized by field name, so a dict of just the
    # wanted fields selects them
    partial = (not jax.tree_util.tree_leaves(template.opt_state) or load_module_only
               or not load_optimizer_states)
    if partial:
        fields = {f: getattr(abstract, f) for f in ("step", "params", "loss_scale", "skipped_steps")}
        restored = engine.load(state_path, abstract_target=fields, partial=True)
        state = template._replace(**restored)
    else:
        state = engine.load(state_path, abstract_target=abstract)

    client_sd = {}
    sd_path = os.path.join(ckpt_dir, "client_sd.json")
    if os.path.isfile(sd_path):
        with open(sd_path) as f:
            client_sd = json.load(f)
    if load_module_only or not load_optimizer_states:
        state = template._replace(params=state.params, step=state.step)
    return state, client_sd


def load_params_only(load_dir, tag=None, abstract_params=None):
    """Restore just the model params from a training checkpoint, as host
    arrays (inference-engine weight loading; reference
    ``inference/engine.py:419``). With ``abstract_params`` (a
    ``jax.eval_shape`` pytree) only the params subtree is read from disk —
    optimizer moments and accumulators are never materialized."""
    wait_pending_saves()
    load_dir = os.path.abspath(load_dir)
    if tag is None:
        tag = get_latest_tag(load_dir)
        if tag is None:
            raise FileNotFoundError(f"no 'latest' file in {load_dir}; pass an explicit tag")
    state_path = os.path.join(load_dir, str(tag), "state")
    if not os.path.isdir(state_path):
        raise FileNotFoundError(f"checkpoint {state_path} does not exist")
    engine = OrbaxCheckpointEngine()
    if abstract_params is not None:
        import orbax.checkpoint as ocp
        target = {"params": jax.tree_util.tree_map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype), abstract_params)}
        restored = engine._ckptr.restore(os.path.abspath(state_path),
                                         args=ocp.args.PyTreeRestore(item=target,
                                                                     partial_restore=True))
        params = restored["params"]
    else:
        state = engine.load(state_path)
        params = state["params"] if isinstance(state, dict) and "params" in state else state[1]
    return jax.tree_util.tree_map(np.asarray, params)


def _jsonable(obj):
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (np.integer, )):
        return int(obj)
    if isinstance(obj, (np.floating, )):
        return float(obj)
    if hasattr(obj, "item") and getattr(obj, "ndim", None) == 0:
        return obj.item()
    return obj
