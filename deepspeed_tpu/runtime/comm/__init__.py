from .compressed import onebit_all_reduce, quantized_all_reduce  # noqa: F401
