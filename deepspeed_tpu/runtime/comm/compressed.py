"""Compressed collectives: error-compensated 1-bit and int8 all-reduce.

TPU-native analogue of the reference's compressed-communication backends
(``runtime/comm/nccl.py:54`` / ``mpi.py:132`` ``compressed_allreduce``: 1-bit
sign compression with error feedback over cupy+NCCL gather/allgather, used by
the 1-bit Adam/LAMB optimizers). Design translation (SURVEY §2.2/§5): the
wire format is what the collective exchanges, so compression = quantize →
XLA collective on the narrow dtype → dequantize, inside ``shard_map`` over
the data axis. On ICI the bandwidth win rarely pays for the quantization
math (the engine's dense default); over DCN multislice it does — these
primitives are the building blocks the 1-bit optimizers plug into.

Both functions are *collective* ops: call inside ``shard_map`` (or any
manual-axes region) with ``axis_name`` bound.
"""

import jax
import jax.numpy as jnp


def onebit_all_reduce(x, error, axis_name):
    """Error-compensated 1-bit averaged all-reduce (reference
    ``compressed_allreduce``).

    Each worker sends only sign bits plus one fp32 scale: the compensated
    tensor ``c = x + error`` is compressed to ``scale * sign(c)`` with
    ``scale = mean(|c|)``; the average of the compressed tensors is the
    result, and ``c - compressed`` carries to the next call as error
    feedback. Returns ``(avg, new_error)``.
    """
    c = x.astype(jnp.float32) + error
    scale = jnp.mean(jnp.abs(c))
    # int8 sign plane: 1/4 the bytes of f32 on the wire; the scale is a scalar
    signs = jnp.where(c >= 0, jnp.int8(1), jnp.int8(-1))
    local_compressed = scale * signs.astype(jnp.float32)
    new_error = c - local_compressed
    # average of per-worker (scale_i * sign_i): psum the sign plane weighted
    # by its scalar scale — communicated as (int8 plane, f32 scalar) pair
    summed = jax.lax.psum(local_compressed, axis_name)
    n = jax.lax.psum(jnp.ones((), jnp.float32), axis_name)
    return summed / n, new_error


def quantized_all_reduce(x, axis_name, bits=8):
    """Symmetric int-quantized averaged all-reduce.

    A shared scale (global abs-max over the group) quantizes every worker's
    tensor to ``bits``-bit integers; the integer psum is exact, so unlike the
    1-bit path this needs no error feedback — precision loss is bounded by
    one quantization step. Returns the dequantized average.
    """
    xf = x.astype(jnp.float32)
    qmax = 2.0**(bits - 1) - 1
    scale = jax.lax.pmax(jnp.max(jnp.abs(xf)), axis_name) / qmax
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(xf / scale), -qmax - 1, qmax).astype(jnp.int32)
    total = jax.lax.psum(q, axis_name)
    n = jax.lax.psum(jnp.ones((), jnp.float32), axis_name)
    return (total.astype(jnp.float32) * scale / n).astype(x.dtype)
