"""Compressed collectives: error-compensated 1-bit and int8 all-reduce with
the narrow dtype ON THE WIRE.

TPU-native analogue of the reference's compressed-communication backends
(``runtime/comm/nccl.py:54`` / ``mpi.py:132`` ``compressed_allreduce``: 1-bit
sign compression with error feedback over cupy+NCCL gather/allgather, used by
the 1-bit Adam/LAMB optimizers). The algorithm is the reference's two-phase
gather scheme — a plain ``psum`` of ``scale * signs`` would put dense fp32
back on the wire, which is exactly what these exist to avoid:

  phase 1  each worker compresses its compensated tensor to (int8 sign
           plane, fp32 scalar scale), chunks it n ways, and ``all_to_all``s
           the chunks — worker i collects everyone's chunk i (int8 wire).
  local    worker i averages its chunk: sum_j scale_j * sign_j / n.
  phase 2  the averaged chunk is compressed AGAIN (server error feedback),
           and the (int8 chunk, scalar) pairs are ``all_gather``ed so every
           worker reconstructs the full result (int8 wire).

Wire bytes per worker ~ 2 * size * (n-1)/n * 1 B vs ~ 2 * size * (n-1)/n *
4 B for the dense fp32 ring all-reduce: a 4x reduction (8x vs the reference's
fp32 grads; 2x vs a bf16 wire), matching the reference's
compressed-chunk gather design. Both error feedbacks (worker + server) are
carried by the caller, as in ``OnebitAdam`` (``fp16/onebit/adam.py``).

Both functions are *collective* ops: call inside ``shard_map`` (or any
manual-axes region) with ``axis_name`` bound.
"""

import jax
import jax.numpy as jnp


def chunk_len(size, n):
    """Per-worker chunk length for a flat tensor of ``size`` over ``n``
    workers (the server-error leaf shape the optimizers carry)."""
    return -(-size // n)


def _to_chunks(flat, n, k):
    pad = n * k - flat.shape[0]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad, ), flat.dtype)])
    return flat.reshape(n, k)


def _compress8(v):
    """(int8 plane, scalar fp32 scale) symmetric compression of ``v``.

    Wire-format note (deliberate divergence from the reference's literal
    1-bit planes): NCCL bit-packs signs, so the reference's cheapest wire
    quantum is 1 bit; XLA collectives' narrowest dtype is s8, so OUR
    cheapest wire quantum is a byte either way — using all 8 bits costs
    zero extra wire bytes and cuts per-step compression noise ~100x (a bare
    sign plane loses the 1/sqrt(n) averaging after the server re-compress,
    which destabilizes 1-bit Adam's frozen-variance phase)."""
    s = jnp.max(jnp.abs(v)) / 127.0
    s = jnp.where(s == 0, 1.0, s)
    q = jnp.clip(jnp.round(v / s), -127, 127).astype(jnp.int8)
    return q, s


def onebit_all_reduce(x, error, server_error, axis_name):
    """Error-compensated compressed averaged all-reduce (reference
    ``compressed_allreduce``: two-phase chunk exchange with worker + server
    error feedback; int8 planes on the wire — see ``_compress8``).

    ``error``: worker error feedback, shape of ``x``. ``server_error``: server
    error feedback for this worker's owned chunk, shape ``(chunk_len(x.size,
    n),)``. Returns ``(avg, new_error, new_server_error)``. Only int8 planes
    and scalar fp32 scales cross the wire.
    """
    n = jax.lax.axis_size(axis_name)
    c = x.astype(jnp.float32) + error
    q, scale = _compress8(c)
    new_error = c - scale * q.astype(jnp.float32)
    if n == 1:
        sc = c.reshape(-1) + server_error
        q2, s2 = _compress8(sc)
        out = s2 * q2.astype(jnp.float32)
        return out.reshape(x.shape), new_error, sc - out

    k = chunk_len(c.size, n)
    # phase 1: int8 chunk exchange — worker i collects everyone's chunk i
    recv = jax.lax.all_to_all(_to_chunks(q.reshape(-1), n, k), axis_name,
                              split_axis=0, concat_axis=0, tiled=True)  # (n, k) int8
    scales = jax.lax.all_gather(scale, axis_name)  # (n,) fp32 scalars
    avg_chunk = jnp.einsum("n,nk->k", scales, recv.astype(jnp.float32)) / n

    # phase 2: compress the averaged chunk (server error feedback) + gather
    sc = avg_chunk + server_error
    q2, s2 = _compress8(sc)
    new_server_error = sc - s2 * q2.astype(jnp.float32)
    g_q = jax.lax.all_gather(q2, axis_name)  # (n, k) int8 wire
    g_scales = jax.lax.all_gather(s2, axis_name)  # (n,) fp32
    full = (g_scales[:, None] * g_q.astype(jnp.float32)).reshape(-1)
    return full[:c.size].reshape(x.shape), new_error, new_server_error


def quantized_all_reduce(x, axis_name, bits=8):
    """Symmetric int8-on-the-wire quantized averaged all-reduce.

    Two-phase like ``onebit_all_reduce`` but stateless: a group-shared scale
    (abs-max) quantizes each worker's tensor to ``bits`` levels packed in
    int8; chunk sums are exact in int32 locally; the averaged chunk is
    requantized per-owner for the int8 gather. Precision loss is bounded by
    two quantization steps (vs one for a dense wire) — the price of the 4x
    wire saving. Returns the dequantized average.
    """
    n = jax.lax.axis_size(axis_name)
    xf = x.astype(jnp.float32)
    qmax = 2.0**(bits - 1) - 1
    scale = jax.lax.pmax(jnp.max(jnp.abs(xf)), axis_name) / qmax
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(xf / scale), -qmax - 1, qmax).astype(jnp.int8)
    if n == 1:
        return (q.astype(jnp.float32) * scale).astype(x.dtype)

    k = chunk_len(xf.size, n)
    recv = jax.lax.all_to_all(_to_chunks(q.reshape(-1), n, k), axis_name,
                              split_axis=0, concat_axis=0, tiled=True)  # (n, k) int8
    # exact int32 sum of n int8 chunks (|sum| <= n * 128 << 2^31)
    avg_chunk = recv.astype(jnp.int32).sum(0).astype(jnp.float32) * scale / n

    s_scale = jnp.max(jnp.abs(avg_chunk)) / qmax
    s_scale = jnp.where(s_scale == 0, 1.0, s_scale)
    q2 = jnp.clip(jnp.round(avg_chunk / s_scale), -qmax - 1, qmax).astype(jnp.int8)
    g_q = jax.lax.all_gather(q2, axis_name)  # (n, k) int8 wire
    g_scales = jax.lax.all_gather(s_scale, axis_name)  # (n,) fp32
    full = (g_scales[:, None] * g_q.astype(jnp.float32)).reshape(-1)
    return full[:xf.size].reshape(x.shape).astype(x.dtype)
