"""Top-level config.

Analogue of reference ``deepspeed/runtime/config.py`` (``DeepSpeedConfig``
:674, ``_initialize_params`` :767, batch-size triple resolution :738-760).
Accepts the same JSON document (path or dict). TPU extension: a ``mesh``
section declaring parallel axis sizes (tensor/pipeline/sequence/expert); the
data axis is inferred from world size.
"""

import json
import os

from .config_utils import DeepSpeedConfigModel, ConfigField, dict_raise_error_on_duplicate_keys
from .zero.config import DeepSpeedZeroConfig, ZeroStageEnum
from ..utils.logging import logger


class FP16Config(DeepSpeedConfigModel):
    enabled = ConfigField(default=False)
    auto_cast = ConfigField(default=False)
    loss_scale = ConfigField(default=0)
    initial_scale_power = ConfigField(default=16)
    loss_scale_window = ConfigField(default=1000)
    hysteresis = ConfigField(default=2)
    min_loss_scale = ConfigField(default=1)
    fp16_master_weights_and_grads = ConfigField(default=False)
    fp16_opt_level = ConfigField(default=None)  # accepted, unused (apex-ism)


class BF16Config(DeepSpeedConfigModel):
    enabled = ConfigField(default=False)


class OptimizerConfig(DeepSpeedConfigModel):
    type = ConfigField(default=None)
    params = ConfigField(default=dict)
    legacy_fusion = ConfigField(default=False)


class SchedulerConfig(DeepSpeedConfigModel):
    type = ConfigField(default=None)
    params = ConfigField(default=dict)


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    """Reference ``runtime/activation_checkpointing/config.py`` keys."""
    partition_activations = ConfigField(default=False)
    contiguous_memory_optimization = ConfigField(default=False)
    cpu_checkpointing = ConfigField(default=False)
    number_checkpoints = ConfigField(default=None)
    synchronize_checkpoint_boundary = ConfigField(default=False)
    profile = ConfigField(default=False)
    # TPU extension: jax.checkpoint policy name (e.g. "dots_saveable",
    # "nothing_saveable", "dots_with_no_batch_dims_saveable")
    policy = ConfigField(default=None)


class MonitorBackendConfig(DeepSpeedConfigModel):
    enabled = ConfigField(default=False)
    output_path = ConfigField(default="")
    job_name = ConfigField(default="DeepSpeedJobName")
    # wandb-only
    team = ConfigField(default=None)
    group = ConfigField(default=None)
    project = ConfigField(default=None)


class CommsLoggerConfig(DeepSpeedConfigModel):
    enabled = ConfigField(default=False)
    verbose = ConfigField(default=False)
    prof_all = ConfigField(default=True)
    debug = ConfigField(default=False)
    prof_ops = ConfigField(default=list)


class FlopsProfilerConfig(DeepSpeedConfigModel):
    enabled = ConfigField(default=False)
    recompute_fwd_factor = ConfigField(default=0.0)
    profile_step = ConfigField(default=1)
    module_depth = ConfigField(default=-1)
    top_modules = ConfigField(default=1)
    detailed = ConfigField(default=True)
    output_file = ConfigField(default=None)


class TelemetryConfig(DeepSpeedConfigModel):
    """TPU extension: the unified telemetry sink (``deepspeed_tpu/telemetry``).

    Default-off; when enabled the engine writes a structured event stream
    (``telemetry.jsonl``) and a Perfetto-loadable ``trace.json`` under
    ``output_path``. See ``benchmarks/OBSERVABILITY.md``.
    """
    enabled = ConfigField(default=False)
    output_path = ConfigField(default="telemetry")
    # events buffered before an automatic flush (spans + gauges; counters
    # and histograms snapshot at each flush)
    flush_interval = ConfigField(default=100)
    # "chrome" writes trace.json in Chrome-trace format; "none" disables it
    trace_format = ConfigField(default="chrome")
    # histogram sliding window: percentiles always describe roughly the
    # last hist_window_s seconds from a bounded chunked reservoir of
    # hist_max_samples values (long-running serving never freezes on
    # startup-era samples)
    hist_window_s = ConfigField(default=300.0)
    hist_max_samples = ConfigField(default=4096)
    # per-request tracing (gateway/scheduler span trees + flow links);
    # rides the enabled sink — flip off to keep only aggregate telemetry
    request_tracing = ConfigField(default=True)
    # anomaly flight recorder (telemetry/flight_recorder.py): always-on
    # ring of recent full-resolution events, dumped around anomalies.
    # Keys: enabled (true) / capacity (8192) / post_window_s (0.25) /
    # min_interval_s (1.0)
    flight_recorder = ConfigField(default=dict)
    # SLO engine (telemetry/slo.py): objectives + multi-window burn-rate
    # alerting. Keys: objectives (list of specs) / fast_window_s /
    # slow_window_s / burn_threshold / eval_interval_s; the serving
    # gateway falls back to its default objective slate when none given
    slo = ConfigField(default=dict)
    # serving capacity accounting (telemetry/capacity.py): fence-and-time
    # every Nth scheduler sync for the live MFU / HBM-bandwidth / roofline
    # gauges (1 = every sync, tests only; the async dispatch pipeline is
    # never fenced between samples)
    capacity_sample_every = ConfigField(default=32)
    # on-demand XLA profiling (telemetry/profiler.py): capture one device
    # trace of this many seconds at the training engine's next report
    # interval (0 = off; serving uses POST /v1/debug/profile instead)
    profile_report_s = ConfigField(default=0.0)


class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation = ConfigField(default="Warn")
    load_universal = ConfigField(default=False)
    use_node_local_storage = ConfigField(default=False)
    parallel_write = ConfigField(default=dict)
    # TPU extension: async checkpointing via a background commit thread
    async_save = ConfigField(default=False)


class MeshConfig(DeepSpeedConfigModel):
    """TPU extension: parallel axis sizes for the device mesh.

    Axis order (outer→inner, DCN-slowest to ICI-fastest):
    ``('pipe', 'data', 'seq', 'tensor', 'expert-implied')``. The reference has
    no first-class mesh; TP was delegated to a user mpu (SURVEY §2.3).
    """
    tensor_parallel_size = ConfigField(default=1, aliases=("model_parallel_size",))
    pipeline_parallel_size = ConfigField(default=1)
    sequence_parallel_size = ConfigField(default=1)
    expert_parallel_size = ConfigField(default=1)
    data_parallel_size = ConfigField(default=None)  # inferred if None
    # device assignment order, advanced use
    axis_order = ConfigField(default=("pipe", "data", "seq", "tensor"))


class DeepSpeedConfigError(Exception):
    pass


class DeepSpeedConfig(DeepSpeedConfigModel):
    _allow_extra = True  # top level tolerates sections consumed elsewhere

    train_batch_size = ConfigField(default=None)
    train_micro_batch_size_per_gpu = ConfigField(default=None)
    gradient_accumulation_steps = ConfigField(default=None)
    steps_per_print = ConfigField(default=10)
    dump_state = ConfigField(default=False)
    disable_allgather = ConfigField(default=False)
    communication_data_type = ConfigField(default=None)
    prescale_gradients = ConfigField(default=False)
    gradient_predivide_factor = ConfigField(default=1.0)
    sparse_gradients = ConfigField(default=False)
    gradient_clipping = ConfigField(default=0.0)
    fp32_allreduce = ConfigField(default=False)
    seed = ConfigField(default=1234)

    optimizer = ConfigField(default=OptimizerConfig)
    scheduler = ConfigField(default=SchedulerConfig)
    fp16 = ConfigField(default=FP16Config)
    bf16 = ConfigField(default=BF16Config, aliases=("bfloat16",))
    amp = ConfigField(default=dict)
    zero_optimization = ConfigField(default=DeepSpeedZeroConfig)
    activation_checkpointing = ConfigField(default=ActivationCheckpointingConfig)
    # HF-style boolean alias; folded into activation_checkpointing in __init__
    gradient_checkpointing = ConfigField(default=None)

    tensorboard = ConfigField(default=MonitorBackendConfig)
    csv_monitor = ConfigField(default=MonitorBackendConfig)
    wandb = ConfigField(default=MonitorBackendConfig)
    comms_logger = ConfigField(default=CommsLoggerConfig)
    telemetry = ConfigField(default=TelemetryConfig)
    flops_profiler = ConfigField(default=FlopsProfilerConfig)

    wall_clock_breakdown = ConfigField(default=False)
    memory_breakdown = ConfigField(default=False)
    dataloader_drop_last = ConfigField(default=False)
    data_types = ConfigField(default=dict)
    checkpoint = ConfigField(default=CheckpointConfig)
    # RLHF hybrid engine (reference runtime/hybrid_engine.py; keys:
    # enabled, max_out_tokens, kernel_inject)
    hybrid_engine = ConfigField(default=dict)
    elasticity = ConfigField(default=dict)
    autotuning = ConfigField(default=dict)
    compression_training = ConfigField(default=dict)
    data_efficiency = ConfigField(default=dict)
    curriculum_learning = ConfigField(default=dict)
    progressive_layer_drop = ConfigField(default=dict)
    sparse_attention = ConfigField(default=dict)
    aio = ConfigField(default=dict)
    mesh = ConfigField(default=MeshConfig)
    # pipeline section (used when model is a PipelineModule)
    pipeline = ConfigField(default=dict)
    zero_allow_untested_optimizer = ConfigField(default=True)
    zero_force_ds_cpu_optimizer = ConfigField(default=False)

    def __init__(self, config, mpu=None, world_size=None):
        if isinstance(config, (str, os.PathLike)):
            if not os.path.exists(config):
                raise DeepSpeedConfigError(f"Config file {config} not found")
            with open(config, "r") as f:
                config_dict = json.load(f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
        elif isinstance(config, dict):
            config_dict = config
        elif config is None:
            config_dict = {}
        else:
            raise DeepSpeedConfigError(f"Expected a config path or dict, got {type(config)}")

        super().__init__(config_dict)
        self.raw_config = config_dict
        self._warn_inert_sections(config_dict)

        if world_size is None:
            try:
                from .. import comm as dist
                world_size = dist.get_world_size() if dist.is_initialized() else 1
            except Exception:
                world_size = 1
        self.world_size = world_size
        self.mpu = mpu
        if mpu is not None and self.mesh.data_parallel_size is None:
            try:
                # mpu reports the combined DP group (DeepSpeed convention,
                # includes expert ranks); our data axis excludes expert
                mpu_dp = mpu.get_data_parallel_world_size()
                if mpu_dp % self.mesh.expert_parallel_size == 0:
                    self.mesh.data_parallel_size = mpu_dp // self.mesh.expert_parallel_size
            except Exception:
                pass
        if self.gradient_checkpointing is not None:
            if self.gradient_checkpointing and self.activation_checkpointing.policy is None:
                self.activation_checkpointing.policy = "nothing_saveable"
        if dict(config_dict.get("nebula", {}) or {}).get("enabled"):
            # nebula shim (reference nebula/config.py): the service's async
            # tiered persistence maps onto the native Orbax async engine —
            # but an EXPLICIT checkpoint.async_save in the config wins
            from ..nebula import DeepSpeedNebulaConfig
            self.nebula = DeepSpeedNebulaConfig(config_dict)
            if "async_save" not in dict(config_dict.get("checkpoint", {}) or {}):
                self.checkpoint.async_save = True
        else:
            self.nebula = None
        if dict(config_dict.get("elasticity", {})).get("enabled"):
            # elastic batch resolution (reference engine.py:462 guard +
            # elasticity.py:233): the pre-computed elastic batch overrides any
            # explicit batch keys so resizes keep the effective batch fixed
            from ..elasticity import compute_elastic_config
            final_batch, _, micro = compute_elastic_config(
                config_dict, world_size=self.world_size, return_microbatch=True)
            self.train_batch_size = final_batch
            if micro is not None:
                self.train_micro_batch_size_per_gpu = micro
                self.gradient_accumulation_steps = None
        self._resolve_data_parallel_size()
        self._configure_train_batch_size()
        self._do_sanity_check()

    # Config sections parsed for DeepSpeed-JSON compatibility but not (yet)
    # backed by an implementation. Silent acceptance would be a correctness
    # trap for users porting configs, so their presence warns loudly. Remove
    # entries as the corresponding subsystem lands.
    # ("sparse_attention" stays here deliberately: the block-sparse subsystem
    # ships as an ops-level API — ops/sparse_attention — but this config
    # *section* does not rewire a model's attention by itself.)
    INERT_SECTIONS = frozenset({
        "amp", "sparse_attention", "sparse_gradients", "communication_data_type",
        "fp32_allreduce", "disable_allgather", "memory_breakdown", "dump_state",
        "data_types", "zero_force_ds_cpu_optimizer",
    })

    def _warn_inert_sections(self, config_dict):
        for key in sorted(set(config_dict) & self.INERT_SECTIONS):
            val = config_dict[key]
            if val in (False, None) or val == {} or val == []:
                continue  # explicitly disabled / empty: nothing being ignored
            if isinstance(val, dict) and val.get("enabled", True) is False:
                continue  # {"enabled": false, ...}: disabled section
            logger.warning(
                f"config section '{key}' is accepted for DeepSpeed-JSON compatibility but "
                f"has NO effect in this build — remove it or expect different behavior")

    # -- batch size arithmetic (reference config.py:738-760) ---------------
    def _resolve_data_parallel_size(self):
        """The ZeRO data-parallel group spans expert×data; data is what's
        left of the world after tp/pp/sp/ep are laid out."""
        m = self.mesh
        non_dp = m.tensor_parallel_size * m.pipeline_parallel_size * m.sequence_parallel_size
        if self.world_size % non_dp != 0:
            raise DeepSpeedConfigError(
                f"world size {self.world_size} not divisible by tp*pp*sp = {non_dp}")
        combined_dp = self.world_size // non_dp  # expert * data
        if combined_dp % m.expert_parallel_size != 0:
            raise DeepSpeedConfigError(
                f"dp group size {combined_dp} not divisible by expert_parallel_size "
                f"{m.expert_parallel_size}")
        inferred_data = combined_dp // m.expert_parallel_size
        if m.data_parallel_size is None:
            m.data_parallel_size = inferred_data
        elif m.data_parallel_size != inferred_data and self.world_size > 1:
            raise DeepSpeedConfigError(
                f"data_parallel_size {m.data_parallel_size} inconsistent with world size "
                f"{self.world_size} / (tp*pp*sp*ep) = {inferred_data}")

    def _configure_train_batch_size(self):
        train_batch = self.train_batch_size
        micro_batch = self.train_micro_batch_size_per_gpu
        grad_acc = self.gradient_accumulation_steps
        # batch replicas span the full ZeRO dp group: expert × data
        dp = self.mesh.data_parallel_size * self.mesh.expert_parallel_size

        if train_batch is not None and micro_batch is not None and grad_acc is not None:
            pass
        elif train_batch is not None and micro_batch is not None:
            grad_acc = train_batch // micro_batch
            grad_acc //= dp
            grad_acc = max(1, grad_acc)
        elif train_batch is not None and grad_acc is not None:
            micro_batch = train_batch // dp
            micro_batch //= grad_acc
            micro_batch = max(1, micro_batch)
        elif micro_batch is not None and grad_acc is not None:
            train_batch = micro_batch * grad_acc * dp
        elif train_batch is not None:
            grad_acc = 1
            micro_batch = train_batch // dp
        elif micro_batch is not None:
            train_batch = micro_batch * dp
            grad_acc = 1
        else:
            raise DeepSpeedConfigError(
                "Either train_batch_size or train_micro_batch_size_per_gpu needs to be provided")

        self.train_batch_size = train_batch
        self.train_micro_batch_size_per_gpu = micro_batch
        self.gradient_accumulation_steps = grad_acc

        if train_batch != micro_batch * grad_acc * dp:
            raise DeepSpeedConfigError(
                f"Check batch related parameters. train_batch_size is not equal to "
                f"micro_batch_per_gpu * gradient_acc_step * world_size "
                f"{train_batch} != {micro_batch} * {grad_acc} * {dp}")

    def _do_sanity_check(self):
        if self.fp16.enabled and self.bf16.enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")
        if self.zero_optimization.stage > 0 and self.optimizer.type is None:
            logger.debug("ZeRO enabled with client/implicit optimizer")
        if self.gradient_accumulation_steps < 1:
            raise DeepSpeedConfigError("gradient_accumulation_steps must be >= 1")

    # -- convenience properties mirroring engine accessors ------------------
    @property
    def zero_enabled(self):
        return self.zero_optimization.stage > 0

    @property
    def zero_stage(self):
        return self.zero_optimization.stage

    @property
    def compute_dtype(self):
        import jax.numpy as jnp
        if self.bf16.enabled:
            return jnp.bfloat16
        if self.fp16.enabled:
            return jnp.float16
        return jnp.float32

    @property
    def loss_scale(self):
        return self.fp16.loss_scale if self.fp16.enabled else 0

    @property
    def dynamic_loss_scale(self):
        return self.fp16.enabled and self.fp16.loss_scale == 0

    def print_config(self, name="DeepSpeedConfig"):
        logger.info("{}:".format(name))
        logger.info(json.dumps(self.to_dict(), indent=2, default=str, sort_keys=True))
