"""Typed config models.

Analogue of the reference ``deepspeed/runtime/config_utils.py``
(``DeepSpeedConfigModel``): every subsystem config is a declarative class with
typed, defaulted fields, deprecated-key aliasing, and strict unknown-key
detection. Implemented on dataclass-like plain classes (no pydantic in the
image) to keep import cost near zero.
"""

import copy
import json
from ..utils.logging import logger


class ConfigField:
    """Declarative field: default + optional alias (deprecated name) + validator."""

    def __init__(self, default=None, aliases=(), validator=None, help=""):
        self.default = default
        self.aliases = tuple(aliases)
        self.validator = validator
        self.help = help


class DeepSpeedConfigModel:
    """Base class: subclasses declare ``ConfigField`` class attributes.

    ``Model(param_dict)`` consumes keys named after the attributes (or their
    aliases); unknown keys raise unless ``_allow_extra`` is set; nested models
    are declared by assigning the model *class* as a field default factory via
    ``ConfigField(default=SubModel)``.
    """

    _allow_extra = False

    def __init__(self, param_dict=None):
        param_dict = copy.copy(param_dict) if param_dict else {}
        cls = type(self)
        fields = {}
        for klass in reversed(cls.__mro__):
            for name, val in vars(klass).items():
                if isinstance(val, ConfigField):
                    fields[name] = val
        consumed = set()
        for name, field in fields.items():
            value = _MISSING
            if name in param_dict:
                value = param_dict[name]
                consumed.add(name)
            else:
                for alias in field.aliases:
                    if alias in param_dict:
                        value = param_dict[alias]
                        consumed.add(alias)
                        logger.warning(f"Config parameter {alias} is deprecated, use {name} instead")
                        break
            default = field.default
            if not isinstance(default, type) and callable(default) and value is _MISSING:
                # factory default (lambda producing a fresh mutable value)
                value = default()
            if isinstance(default, type) and not issubclass(default, DeepSpeedConfigModel):
                # factory default (dict/list/…): instantiate when absent
                if value is _MISSING:
                    value = default()
            if isinstance(default, type) and issubclass(default, DeepSpeedConfigModel):
                # nested model
                sub_dict = value if value is not _MISSING else {}
                if isinstance(sub_dict, DeepSpeedConfigModel):
                    value = sub_dict
                elif isinstance(sub_dict, bool):
                    # patterns like "bf16": true are not valid for nested models
                    raise ValueError(f"Expected dict for config key '{name}', got {sub_dict!r}")
                else:
                    value = default(sub_dict or {})
            elif value is _MISSING:
                value = copy.deepcopy(default)
            if field.validator is not None and value is not None:
                value = field.validator(value)
            setattr(self, name, value)
        extra = set(param_dict) - consumed
        if extra and not self._allow_extra:
            raise ValueError(f"Unknown config keys for {cls.__name__}: {sorted(extra)}")
        self._extra = {k: param_dict[k] for k in extra}

    def to_dict(self):
        out = {}
        for name in vars(self):
            if name.startswith("_"):
                continue
            val = getattr(self, name)
            if isinstance(val, DeepSpeedConfigModel):
                val = val.to_dict()
            out[name] = val
        out.update(getattr(self, "_extra", {}))
        return out

    def __repr__(self):
        return f"{type(self).__name__}({json.dumps(self.to_dict(), default=str)})"


class _Missing:

    def __repr__(self):
        return "<MISSING>"


_MISSING = _Missing()


def get_scalar_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_list_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def get_dict_param(param_dict, param_name, param_default_value):
    return param_dict.get(param_name, param_default_value)


def dict_raise_error_on_duplicate_keys(ordered_pairs):
    """Reject duplicate keys when parsing JSON (reference behavior)."""
    d = dict((k, v) for k, v in ordered_pairs)
    if len(d) != len(ordered_pairs):
        counter = {}
        for k, v in ordered_pairs:
            counter[k] = counter.get(k, 0) + 1
        keys = [k for k, v in counter.items() if v > 1]
        raise ValueError("Duplicate keys in DeepSpeed config: {}".format(keys))
    return d


class ScientificNotationEncoder(json.JSONEncoder):
    """Print large/small floats in scientific notation in config dumps."""

    def iterencode(self, o, _one_shot=False, level=0):
        indent = self.indent if self.indent is not None else 4
        prefix_close = " " * level * indent
        level += 1
        prefix = " " * level * indent
        if isinstance(o, bool):
            return "true" if o else "false"
        elif isinstance(o, float) and (o > 1e3 or o < 1e-3):
            return f"{o:e}"
        elif isinstance(o, dict):
            x = [f'\n{prefix}"{k}": {self.iterencode(v, level=level)}' for k, v in o.items()]
            return "{" + ", ".join(x) + f"\n{prefix_close}" + "}"
        elif isinstance(o, list):
            return f"[{ f', '.join(map(self.iterencode, o)) }]"
        return "\n, ".join(super().iterencode(o, _one_shot))
