"""Config key names + defaults.

Condensed analogue of the reference ``deepspeed/runtime/constants.py`` (417
LoC of key constants). Key *names* match the reference so user configs are
drop-in; values the TPU build does not support raise clearly at parse time.
"""

#############################################
# Batch size and accumulation
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None

GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
LEGACY_FUSION_DEFAULT = False

SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

# Supported optimizer names (reference engine.py ADAM_OPTIMIZER etc.)
ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
FUSED_ADAM_OPTIMIZER = "fusedadam"
CPU_ADAM_OPTIMIZER = "cpuadam"
ADAGRAD_OPTIMIZER = "adagrad"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
SGD_OPTIMIZER = "sgd"
LION_OPTIMIZER = "lion"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM_OPTIMIZER, CPU_ADAM_OPTIMIZER, ADAGRAD_OPTIMIZER, LAMB_OPTIMIZER,
    ONEBIT_ADAM_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER, SGD_OPTIMIZER, LION_OPTIMIZER
]

#############################################
# Precision
#############################################
FP32_ALLREDUCE = "fp32_allreduce"
FP32_ALLREDUCE_DEFAULT = False

PREC_SCALE = "prescale_gradients"
PREC_SCALE_DEFAULT = False

GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_AUTO_CAST = "auto_cast"
FP16_AUTO_CAST_DEFAULT = False
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 16
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1
FP16_MASTER_WEIGHTS_AND_GRADS = "fp16_master_weights_and_grads"
FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT = False

BFLOAT16 = "bf16"
BFLOAT16_OLD = "bfloat16"  # deprecated alias kept by the reference
BFLOAT16_ENABLED = "enabled"
BFLOAT16_ENABLED_DEFAULT = False

AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

COMMUNICATION_DATA_TYPE = "communication_data_type"
COMMUNICATION_DATA_TYPE_DEFAULT = None

#############################################
# ZeRO
#############################################
ZERO_OPTIMIZATION = "zero_optimization"

#############################################
# Logging / monitoring
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

TENSORBOARD = "tensorboard"
CSV_MONITOR = "csv_monitor"
WANDB = "wandb"
MONITOR_ENABLED = "enabled"

#############################################
# Checkpoint / data
#############################################
CHECKPOINT = "checkpoint"
LOAD_UNIVERSAL_CHECKPOINT = "load_universal"
LOAD_UNIVERSAL_CHECKPOINT_DEFAULT = False
USE_NODE_LOCAL_STORAGE_CHECKPOINT = "use_node_local_storage"
USE_NODE_LOCAL_STORAGE_CHECKPOINT_DEFAULT = False

DATA_TYPES = "data_types"
GRAD_ACCUM_DTYPE = "grad_accum_dtype"
GRAD_ACCUM_DTYPE_DEFAULT = None

DATALOADER_DROP_LAST = "dataloader_drop_last"
DATALOADER_DROP_LAST_DEFAULT = False

#############################################
# Activation checkpointing
#############################################
ACTIVATION_CHECKPOINTING = "activation_checkpointing"

#############################################
# Sparse attention
#############################################
SPARSE_ATTENTION = "sparse_attention"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"
SPARSE_MODE = "mode"
SPARSE_MODE_DEFAULT = SPARSE_FIXED_MODE

#############################################
# Gradient/elasticity misc
#############################################
PLD = "progressive_layer_drop"
PLD_ENABLED = "enabled"
PLD_ENABLED_DEFAULT = False
PLD_THETA = "theta"
PLD_THETA_DEFAULT = 1.0
PLD_GAMMA = "gamma"
PLD_GAMMA_DEFAULT = 0.001

CURRICULUM_LEARNING_LEGACY = "curriculum_learning"
DATA_EFFICIENCY = "data_efficiency"

ELASTICITY = "elasticity"

#############################################
# Parallelism axes (TPU mesh; extension over the reference which delegates
# TP to a user mpu and has no SP)
#############################################
MESH = "mesh"
TENSOR_PARALLEL_SIZE = "tensor_parallel_size"
PIPELINE_PARALLEL_SIZE = "pipeline_parallel_size"
SEQUENCE_PARALLEL_SIZE = "sequence_parallel_size"
EXPERT_PARALLEL_SIZE = "expert_parallel_size"

#############################################
# Routing keys held by top-level config but consumed by subsystems
#############################################
COMPRESSION_TRAINING = "compression_training"
FLOPS_PROFILER = "flops_profiler"
AUTOTUNING = "autotuning"
MONITOR_CONFIG = "monitor_config"
COMMS_LOGGER = "comms_logger"
