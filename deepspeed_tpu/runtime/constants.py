"""Config key names.

Condensed analogue of the reference ``deepspeed/runtime/constants.py``. Key
*names* match the reference so user configs are drop-in. Defaults live in ONE
place — the ``ConfigField`` declarations in ``config.py`` — not here.
"""

#############################################
# Batch size and accumulation
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

#############################################
# Optimizer / scheduler sections
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"
SCHEDULER = "scheduler"
SCHEDULER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

# Supported optimizer names (reference engine.py ADAM_OPTIMIZER etc.)
ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
FUSED_ADAM_OPTIMIZER = "fusedadam"
CPU_ADAM_OPTIMIZER = "cpuadam"
ADAGRAD_OPTIMIZER = "adagrad"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
SGD_OPTIMIZER = "sgd"
LION_OPTIMIZER = "lion"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM_OPTIMIZER, CPU_ADAM_OPTIMIZER, ADAGRAD_OPTIMIZER, LAMB_OPTIMIZER,
    ONEBIT_ADAM_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER, SGD_OPTIMIZER, LION_OPTIMIZER
]

#############################################
# Precision / gradients
#############################################
FP32_ALLREDUCE = "fp32_allreduce"
PREC_SCALE = "prescale_gradients"
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
SPARSE_GRADIENTS = "sparse_gradients"
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_LOSS_SCALE = "loss_scale"
FP16_AUTO_CAST = "auto_cast"
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_HYSTERESIS = "hysteresis"
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MASTER_WEIGHTS_AND_GRADS = "fp16_master_weights_and_grads"
BFLOAT16 = "bf16"
BFLOAT16_OLD = "bfloat16"  # deprecated alias kept by the reference
BFLOAT16_ENABLED = "enabled"
AMP = "amp"
AMP_ENABLED = "enabled"
GRADIENT_CLIPPING = "gradient_clipping"
COMMUNICATION_DATA_TYPE = "communication_data_type"
GRAD_ACCUM_DTYPE = "grad_accum_dtype"

#############################################
# Sections
#############################################
ZERO_OPTIMIZATION = "zero_optimization"
STEPS_PER_PRINT = "steps_per_print"
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
DUMP_STATE = "dump_state"
MEMORY_BREAKDOWN = "memory_breakdown"
TENSORBOARD = "tensorboard"
CSV_MONITOR = "csv_monitor"
WANDB = "wandb"
MONITOR_ENABLED = "enabled"
CHECKPOINT = "checkpoint"
LOAD_UNIVERSAL_CHECKPOINT = "load_universal"
USE_NODE_LOCAL_STORAGE_CHECKPOINT = "use_node_local_storage"
DATA_TYPES = "data_types"
DATALOADER_DROP_LAST = "dataloader_drop_last"
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
SPARSE_ATTENTION = "sparse_attention"
SPARSE_DENSE_MODE = "dense"
SPARSE_FIXED_MODE = "fixed"
SPARSE_VARIABLE_MODE = "variable"
SPARSE_BIGBIRD_MODE = "bigbird"
SPARSE_BSLONGFORMER_MODE = "bslongformer"
SPARSE_MODE = "mode"
PLD = "progressive_layer_drop"
PLD_ENABLED = "enabled"
PLD_THETA = "theta"
PLD_GAMMA = "gamma"
CURRICULUM_LEARNING_LEGACY = "curriculum_learning"
DATA_EFFICIENCY = "data_efficiency"
ELASTICITY = "elasticity"
COMPRESSION_TRAINING = "compression_training"
FLOPS_PROFILER = "flops_profiler"
AUTOTUNING = "autotuning"
COMMS_LOGGER = "comms_logger"

#############################################
# Parallelism axes (TPU mesh; extension over the reference which delegates
# TP to a user mpu and has no SP)
#############################################
MESH = "mesh"
TENSOR_PARALLEL_SIZE = "tensor_parallel_size"
PIPELINE_PARALLEL_SIZE = "pipeline_parallel_size"
SEQUENCE_PARALLEL_SIZE = "sequence_parallel_size"
EXPERT_PARALLEL_SIZE = "expert_parallel_size"
