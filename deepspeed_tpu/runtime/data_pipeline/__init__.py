from .curriculum_scheduler import CurriculumScheduler  # noqa: F401
from .data_sampler import DeepSpeedDataSampler  # noqa: F401
