from .curriculum_scheduler import CurriculumScheduler  # noqa: F401
from .data_sampler import DeepSpeedDataSampler, DifficultyDataSampler  # noqa: F401
from .indexed_dataset import (MMapIndexedDataset, MMapIndexedDatasetBuilder,  # noqa: F401
                              close_mmap_dataset_builder, create_mmap_dataset_builder)
