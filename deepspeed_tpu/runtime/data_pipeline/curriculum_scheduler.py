"""Curriculum difficulty scheduler.

Analogue of reference ``runtime/data_pipeline/curriculum_scheduler.py:11``
(``CurriculumScheduler``): maps the global step to a difficulty level
(typically a sequence length). Supported ``schedule_type``s, same config keys
as the reference:

- ``fixed_linear``: min -> max linearly over ``total_curriculum_step``,
  rounded down to a multiple of ``difficulty_step``.
- ``fixed_root``: min + (max-min) * (t/T)^(1/root_degree), same rounding.
- ``fixed_discrete``: step function over ``difficulty`` / ``max_step`` lists.
- ``custom``: a user callable ``step -> difficulty`` set via
  ``set_custom_get_difficulty``.
"""


class CurriculumScheduler:

    def __init__(self, config):
        config = dict(config or {})
        self.schedule_type = config.get("schedule_type", "fixed_linear")
        self.min_difficulty = int(config.get("min_difficulty", 1))
        self.max_difficulty = int(config.get("max_difficulty", self.min_difficulty))
        sched = dict(config.get("schedule_config", {}))
        self._custom_fn = None
        if self.schedule_type in ("fixed_linear", "fixed_root"):
            if "total_curriculum_step" not in sched:
                raise ValueError(f"{self.schedule_type} schedule requires "
                                 "schedule_config.total_curriculum_step")
            self.total_step = int(sched["total_curriculum_step"])
            self.difficulty_step = int(sched.get("difficulty_step", 1))
            self.root_degree = int(sched.get("root_degree", 1 if self.schedule_type == "fixed_linear" else 2))
        elif self.schedule_type == "fixed_discrete":
            if "difficulty" not in sched or "max_step" not in sched:
                raise ValueError("fixed_discrete schedule requires schedule_config.difficulty "
                                 "and schedule_config.max_step lists")
            self.levels = [int(d) for d in sched["difficulty"]]
            self.boundaries = [int(s) for s in sched["max_step"]]
            if len(self.boundaries) != len(self.levels) - 1:
                raise ValueError("fixed_discrete: len(max_step) must be len(difficulty) - 1")
        elif self.schedule_type == "custom":
            pass
        else:
            raise ValueError(f"unknown curriculum schedule_type {self.schedule_type!r}")
        self.current_difficulty = self.get_difficulty(0)

    def set_custom_get_difficulty(self, fn):
        self._custom_fn = fn
        return self

    def get_difficulty(self, global_steps):
        if self.schedule_type == "custom":
            if self._custom_fn is None:
                raise ValueError("custom schedule: call set_custom_get_difficulty first")
            return self._custom_fn(global_steps)
        if self.schedule_type == "fixed_discrete":
            level = self.levels[-1]
            for d, bound in zip(self.levels, self.boundaries):
                if global_steps < bound:
                    level = d
                    break
            return min(level, self.max_difficulty)
        frac = min(1.0, max(0.0, global_steps / max(self.total_step, 1)))
        if self.schedule_type == "fixed_root":
            frac = frac**(1.0 / self.root_degree)
        raw = self.min_difficulty + (self.max_difficulty - self.min_difficulty) * frac
        stepped = int(raw) // self.difficulty_step * self.difficulty_step
        return max(self.min_difficulty, min(self.max_difficulty, stepped))

    def update_difficulty(self, global_steps):
        self.current_difficulty = self.get_difficulty(global_steps)
        return self.current_difficulty
