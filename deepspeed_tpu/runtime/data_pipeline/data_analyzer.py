"""Offline dataset analysis for curriculum learning.

Counterpart of reference ``runtime/data_pipeline/data_analyzer.py``
(``DataAnalyzer``: map workers compute per-sample metric values, reduce
builds sorted index files the curriculum ``DeepSpeedDataSampler`` consumes).
The torch-distributed map/reduce collapses to process-parallel chunks on one
host (TPU hosts are fat; dataset metrics are CPU work), and the output is
one ``.npy`` value file + one difficulty-sorted index file per metric —
exactly what ``data_sampler.DeepSpeedDataSampler(difficulties=...)`` takes.
"""

import os

import numpy as np

from ...utils.logging import logger


class DataAnalyzer:
    """``run_map_reduce(dataset)`` -> {metric: difficulties array} + files.

    ``metric_fns``: {name: fn(sample) -> scalar difficulty}. ``save_path``:
    optional directory for ``<metric>_values.npy`` /
    ``<metric>_index_to_sample.npy`` sidecars (reference file naming).
    """

    def __init__(self, metric_fns, save_path=None, num_workers=1, worker_id=0):
        self.metric_fns = dict(metric_fns)
        self.save_path = save_path
        self.num_workers = max(1, num_workers)
        self.worker_id = worker_id

    def _my_range(self, n):
        per = (n + self.num_workers - 1) // self.num_workers
        lo = self.worker_id * per
        return lo, min(n, lo + per)

    def run_map(self, dataset):
        """This worker's chunk: {metric: (indices, values)}."""
        n = len(dataset)
        lo, hi = self._my_range(n)
        out = {}
        for name, fn in self.metric_fns.items():
            vals = np.asarray([fn(dataset[i]) for i in range(lo, hi)], np.float64)
            out[name] = (np.arange(lo, hi), vals)
        return out

    def run_reduce(self, map_results):
        """Merge worker chunks, write sidecar files, return full value arrays."""
        merged = {}
        for name in self.metric_fns:
            idx = np.concatenate([r[name][0] for r in map_results])
            vals = np.concatenate([r[name][1] for r in map_results])
            order = np.argsort(idx, kind="stable")
            values = vals[order]
            merged[name] = values
            if self.save_path:
                os.makedirs(self.save_path, exist_ok=True)
                np.save(os.path.join(self.save_path, f"{name}_values.npy"), values)
                # difficulty-ascending sample order (reference index_to_sample)
                np.save(os.path.join(self.save_path, f"{name}_index_to_sample.npy"),
                        np.argsort(values, kind="stable"))
                logger.info(f"DataAnalyzer: wrote {name} index for {len(values)} samples "
                            f"under {self.save_path}")
        return merged

    def run_map_reduce(self, dataset):
        workers = [DataAnalyzer(self.metric_fns, None, self.num_workers, w)
                   for w in range(self.num_workers)]
        results = [w.run_map(dataset) for w in workers]
        self_result = self.run_reduce(results)
        return self_result

    @staticmethod
    def load(save_path, metric):
        """Read back a metric's difficulty values (for the data sampler)."""
        return np.load(os.path.join(save_path, f"{metric}_values.npy"))
