"""Offline dataset analysis for curriculum learning.

Counterpart of reference ``runtime/data_pipeline/data_analyzer.py``
(``DataAnalyzer``: map workers compute per-sample metric values, reduce
builds sorted index files the curriculum ``DeepSpeedDataSampler`` consumes).
The torch-distributed map/reduce collapses to process-parallel chunks on one
host (TPU hosts are fat; dataset metrics are CPU work). Outputs per metric:
``.npy`` value/sort sidecars (consumed by the light-weight
``DifficultyDataSampler``) plus the ``<metric>_index_to_sample`` /
``<metric>_index_to_metric`` mmap datasets the curriculum
``DeepSpeedDataSampler`` clusters over.
"""

import os

import numpy as np

from ...utils.logging import logger


class DataAnalyzer:
    """``run_map_reduce(dataset)`` -> {metric: difficulties array} + files.

    ``metric_fns``: {name: fn(sample) -> scalar difficulty}. ``save_path``:
    optional directory for ``<metric>_values.npy`` /
    ``<metric>_index_to_sample.npy`` sidecars (reference file naming).
    """

    def __init__(self, metric_fns, save_path=None, num_workers=1, worker_id=0):
        self.metric_fns = dict(metric_fns)
        self.save_path = save_path
        self.num_workers = max(1, num_workers)
        self.worker_id = worker_id

    def _my_range(self, n):
        per = (n + self.num_workers - 1) // self.num_workers
        lo = self.worker_id * per
        return lo, min(n, lo + per)

    def run_map(self, dataset):
        """This worker's chunk: {metric: (indices, values)}."""
        n = len(dataset)
        lo, hi = self._my_range(n)
        out = {}
        for name, fn in self.metric_fns.items():
            vals = np.asarray([fn(dataset[i]) for i in range(lo, hi)], np.float64)
            out[name] = (np.arange(lo, hi), vals)
        return out

    def run_reduce(self, map_results):
        """Merge worker chunks, write sidecar + mmap index files, return full
        value arrays. The mmap outputs are exactly what the curriculum
        ``DeepSpeedDataSampler`` consumes (reference ``data_analyzer.py:357``):
        ``<metric>_index_to_sample`` — one row of sample ids per unique metric
        value, ascending — and ``<metric>_index_to_metric`` — the values."""
        from .indexed_dataset import (close_mmap_dataset_builder,
                                      create_mmap_dataset_builder, find_fit_int_dtype)
        merged = {}
        for name in self.metric_fns:
            idx = np.concatenate([r[name][0] for r in map_results])
            vals = np.concatenate([r[name][1] for r in map_results])
            order = np.argsort(idx, kind="stable")
            values = vals[order]
            merged[name] = values
            if self.save_path:
                os.makedirs(self.save_path, exist_ok=True)
                np.save(os.path.join(self.save_path, f"{name}_values.npy"), values)
                np.save(os.path.join(self.save_path, f"{name}_index_to_sample.npy"),
                        np.argsort(values, kind="stable"))
                sample_dtype = find_fit_int_dtype(0, len(values))
                s_path = os.path.join(self.save_path, f"{name}_index_to_sample")
                m_path = os.path.join(self.save_path, f"{name}_index_to_metric")
                sb = create_mmap_dataset_builder(s_path, sample_dtype)
                mb = create_mmap_dataset_builder(m_path, np.int64 if
                                                 np.issubdtype(values.dtype, np.integer)
                                                 else np.float64)
                # one argsort + boundary split: O(N log N) regardless of how
                # many unique values a (possibly continuous) metric has
                order = np.argsort(values, kind="stable")
                sorted_vals = values[order]
                uniq, starts = np.unique(sorted_vals, return_index=True)
                for v, group in zip(uniq, np.split(order, starts[1:])):
                    sb.add_item(group.astype(sample_dtype))
                    mb.add_item(np.asarray([v]))
                close_mmap_dataset_builder(sb, s_path)
                close_mmap_dataset_builder(mb, m_path)
                logger.info(f"DataAnalyzer: wrote {name} value + mmap index files for "
                            f"{len(values)} samples under {self.save_path}")
        return merged

    def run_map_reduce(self, dataset):
        workers = [DataAnalyzer(self.metric_fns, None, self.num_workers, w)
                   for w in range(self.num_workers)]
        results = [w.run_map(dataset) for w in workers]
        self_result = self.run_reduce(results)
        return self_result

    @staticmethod
    def load(save_path, metric):
        """Read back a metric's difficulty values (for the data sampler)."""
        return np.load(os.path.join(save_path, f"{metric}_values.npy"))
