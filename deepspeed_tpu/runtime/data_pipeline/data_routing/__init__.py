from .scheduler import RandomLTDScheduler  # noqa: F401
