"""Random-LTD (layerwise token dropping) schedule.

Counterpart of reference ``runtime/data_pipeline/data_routing/scheduler.py``
(``RandomLTDScheduler`` :38; paper: "Random-LTD: Random and Layerwise Token
Dropping"): the number of tokens the selected layers *keep* grows from
``min_value`` to ``max_value`` (the full sequence) over
``schedule_config.require_steps`` steps in increments of ``seq_per_step``.
Same config keys as the reference's ``random_ltd`` section; the token
gather/scatter itself lives in the model (``models/transformer.py``
``ltd_apply``), selected per compile because shapes are static under jit.
"""


class RandomLTDScheduler:

    def __init__(self, config):
        cfg = dict(config or {})
        sched = dict(cfg.get("random_ltd_schedule", {}))
        self.min_value = int(sched.get("min_value", 128))
        self.max_value = int(sched.get("max_value", 2048))
        self.schedule_type = sched.get("schedule_type", "fixed_linear")
        if self.schedule_type != "fixed_linear":
            raise ValueError(f"random_ltd schedule_type {self.schedule_type!r} unsupported "
                             "(reference ships fixed_linear)")
        sc = dict(sched.get("schedule_config", {}))
        self.require_steps = int(sc.get("require_steps", 1))
        self.seq_per_step = int(sc.get("seq_per_step", 16))
        self.total_layer_num = int(cfg.get("total_layer_num", 0))
        self.random_ltd_layer_num = int(cfg.get("random_ltd_layer_num", 0))
        self.random_ltd_layer_id = list(cfg.get("random_ltd_layer_id", []))
        if self.random_ltd_layer_num and len(self.random_ltd_layer_id) != self.random_ltd_layer_num:
            raise ValueError("random_ltd_layer_id length must equal random_ltd_layer_num")
        self.current_seq = self.min_value
        self.state = {"consumed_layer_tokens": 0}

    def get_value(self, global_steps):
        """fixed_linear in ``seq_per_step`` increments, clamped to the range."""
        frac = min(1.0, max(0.0, global_steps / max(1, self.require_steps)))
        raw = self.min_value + frac * (self.max_value - self.min_value)
        stepped = self.min_value + int((raw - self.min_value) // self.seq_per_step) * self.seq_per_step
        return min(self.max_value, stepped)

    def update_seq(self, global_steps):
        self.current_seq = self.get_value(global_steps)
        self.state["consumed_layer_tokens"] += self.current_seq * max(1, self.random_ltd_layer_num)
        return self.current_seq

    def get_current_seq(self):
        return self.current_seq

    def set_current_seq(self, seq_length):
        self.current_seq = int(seq_length)

    def reset_to_init(self):
        self.current_seq = self.min_value
        self.state["consumed_layer_tokens"] = 0

    def state_dict(self):
        return {"current_seq": self.current_seq, **self.state}

    def load_state_dict(self, sd):
        self.current_seq = int(sd["current_seq"])
        self.state["consumed_layer_tokens"] = int(sd.get("consumed_layer_tokens", 0))
