"""Curriculum-aware data sampler.

Analogue of reference ``runtime/data_pipeline/data_sampler.py:36``
(``DeepSpeedDataSampler``): draws sample indices whose difficulty is within
the current curriculum threshold. The reference reads difficulties from an
offline data-analyzer index; here they are supplied directly (a sequence
aligned with the dataset) or computed by a callable per sample — the
analyzer's mmap machinery collapses to a numpy argsort on TPU hosts.

Usable as ``DeepSpeedDataLoader(..., data_sampler=...)`` — iterating yields
an epoch's worth of indices filtered/clipped by difficulty; call
``set_custom_map`` / ``state_dict`` / ``load_state_dict`` for parity.
"""

import numpy as np


class DeepSpeedDataSampler:

    def __init__(self, difficulties, curriculum_scheduler=None, total_samples=None, seed=0,
                 shuffle=True, drop_last=True):
        self.difficulties = np.asarray(difficulties)
        self.total_samples = total_samples or len(self.difficulties)
        self.scheduler = curriculum_scheduler
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.global_steps = 0
        # ascending difficulty order; the active prefix grows with the schedule
        self._order_by_difficulty = np.argsort(self.difficulties, kind="stable")

    def set_epoch(self, epoch):
        self.epoch = epoch

    def advance(self, global_steps):
        self.global_steps = global_steps
        if self.scheduler is not None:
            self.scheduler.update_difficulty(global_steps)

    def _active_indices(self):
        if self.scheduler is None:
            return np.arange(self.total_samples)
        limit = self.scheduler.current_difficulty
        sorted_diff = self.difficulties[self._order_by_difficulty]
        n_active = int(np.searchsorted(sorted_diff, limit, side="right"))
        n_active = max(n_active, 1)  # never an empty pool
        return self._order_by_difficulty[:n_active]

    def __iter__(self):
        active = self._active_indices()
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self.epoch))
            active = rng.permutation(active)
        return iter(active.tolist())

    def __len__(self):
        return len(self._active_indices())

    def state_dict(self):
        return {"epoch": self.epoch, "global_steps": self.global_steps,
                "current_difficulty": None if self.scheduler is None
                else self.scheduler.current_difficulty}

    def load_state_dict(self, sd):
        self.epoch = sd.get("epoch", 0)
        self.advance(sd.get("global_steps", 0))
