"""Curriculum data samplers.

Counterpart of reference ``data_pipeline/data_sampling/data_sampler.py:36``
(``DeepSpeedDataSampler``): difficulty-clustered sampling over an on-disk
``MMapIndexedDataset`` index built by the data analyzer. Per global batch
the per-metric curriculum schedules advance; when any difficulty moves, the
newly-admitted samples form a new shuffled cluster (persisted as an
mmap dataset under ``data_cluster_path``); batches draw from all live
clusters weighted by size, reshuffling a cluster when its cursor wraps.
Single-controller translation: the rank-0 + broadcast choreography of the
reference collapses — one process computes the batch and every consumer
slices its ``data_parallel_rank`` share.

``DifficultyDataSampler`` is the light-weight variant (difficulty array in
memory, one threshold) for quick curriculum setups without an on-disk index.
"""

import os

import numpy as np

from ...utils.logging import logger
from .curriculum_scheduler import CurriculumScheduler
from .indexed_dataset import (MMapIndexedDataset, close_mmap_dataset_builder,
                              create_mmap_dataset_builder, find_fit_int_dtype)

# config keys (reference data_pipeline/constants.py)
DATA_SAMPLING = "data_sampling"
CURRICULUM_LEARNING = "curriculum_learning"
CURRICULUM_METRICS = "curriculum_metrics"
VALUE_BASED = "value"
PERCENTILE_BASED = "percentile"
SINGLE_CLUSTER = "single_cluster"
CLUSTER_PREFIX = "cluster"


class DeepSpeedDataSampler:
    """Reference-parity curriculum sampler over analyzer-built indexes.

    ``data_efficiency_config``: the ``data_efficiency`` config section, keys
    as in the reference (``data_sampling.curriculum_learning.
    curriculum_metrics.<metric>``: ``index_to_sample_path``,
    ``index_to_metric_path``, ``difficulty_type`` value|percentile,
    ``clustering_type``, schedule fields). Iterating yields this
    data-parallel rank's micro-batch index lists.
    """

    def __init__(self, data_efficiency_config, one_epoch_total_samples, micro_batch_size,
                 data_parallel_rank=0, data_parallel_size=1, data_parallel_group=None,
                 gradient_accumulation_steps=1, global_rank=0, drop_last=True):
        self.config = data_efficiency_config
        self.one_epoch_total_samples = int(one_epoch_total_samples)
        self.index_dtype = find_fit_int_dtype(0, one_epoch_total_samples)
        sampling = dict(self.config.get(DATA_SAMPLING, {}))
        self.total_samples = self.one_epoch_total_samples * int(sampling.get("num_epochs", 1000))
        self.micro_batch_size = int(micro_batch_size)
        self.data_parallel_rank = int(data_parallel_rank)
        self.micro_batch_times_data_parallel_size = self.micro_batch_size * int(data_parallel_size)
        self.global_batch_size = (self.micro_batch_times_data_parallel_size
                                  * int(gradient_accumulation_steps))
        self.drop_last = drop_last
        self.np_rng = np.random.default_rng(int(self.config.get("seed", 1234)))
        self.batch = []
        self.consumed_samples = 0

        cl = dict(sampling.get(CURRICULUM_LEARNING, {}))
        self.curriculum_enabled = bool(cl.get("enabled", False))
        self.curriculum_step = 0
        self.current_difficulties = {}
        self.curriculum_schedulers = {}
        self.difficulty_type = {}
        self.clustering_type = {}
        self.index_to_sample = {}
        self.index_to_metric = {}
        self.data_clusters = []  # list[(name, MMapIndexedDataset)]
        self.data_cluster_sizes = []
        self.data_cluster_paths = []
        self.data_cluster_current_position = []
        self.data_1epoch_size = None
        if self.curriculum_enabled:
            self.cluster_path = cl["data_cluster_path"]
            os.makedirs(self.cluster_path, exist_ok=True)
            for metric, mcfg in dict(cl.get(CURRICULUM_METRICS, {})).items():
                mcfg = dict(mcfg)
                self.curriculum_schedulers[metric] = CurriculumScheduler(mcfg)
                self.difficulty_type[metric] = mcfg.get("difficulty_type", VALUE_BASED)
                self.clustering_type[metric] = mcfg.get("clustering_type", SINGLE_CLUSTER)
                if self.clustering_type[metric] != SINGLE_CLUSTER:
                    self.index_to_sample[metric] = MMapIndexedDataset(mcfg["index_to_sample_path"])
                    if self.difficulty_type[metric] == VALUE_BASED:
                        self.index_to_metric[metric] = MMapIndexedDataset(mcfg["index_to_metric_path"])

        assert self.total_samples > 0 and self.micro_batch_size > 0
        assert self.data_parallel_rank < int(data_parallel_size)

    def __len__(self):
        return self.total_samples

    def set_custom_curriculum_learning_schedule(self, schedule_func_dict):
        for metric, fn in schedule_func_dict.items():
            if metric in self.curriculum_schedulers:
                self.curriculum_schedulers[metric].set_custom_get_difficulty(fn)

    # -- cluster construction ---------------------------------------------
    def _samples_by_value(self, metric, value_start, value_end):
        rows = []
        for row in range(len(self.index_to_sample[metric])):
            v = self.index_to_metric[metric][row]
            if value_start < v <= value_end:
                rows.append(np.array(self.index_to_sample[metric][row]))
        return np.concatenate(rows) if rows else None

    def _samples_by_percentile(self, metric, pct_start, pct_end):
        idx = self.index_to_sample[metric]
        if self.data_1epoch_size is None:
            self.data_1epoch_size = sum(len(idx[r]) for r in range(len(idx)))
        max_pct = self.curriculum_schedulers[metric].max_difficulty
        per_pct = self.data_1epoch_size // max_pct
        start_count = per_pct * pct_start
        end_count = self.data_1epoch_size if pct_end == max_pct else per_pct * pct_end
        rows, count = [], 0
        for r in range(len(idx)):
            row = idx[r]
            if count + len(row) > start_count:
                lo = max(0, start_count - count)
                hi = len(row) if count + len(row) <= end_count else end_count - count
                rows.append(np.array(row[lo:hi]))
            count += len(row)
            if count >= end_count:
                break
        return np.concatenate(rows) if rows else None

    def _admitted(self, metric, prev, cur):
        if self.difficulty_type[metric] == VALUE_BASED:
            return self._samples_by_value(metric, prev, cur)
        return self._samples_by_percentile(metric, prev, cur)

    def get_new_cluster(self, previous_difficulties):
        name = CLUSTER_PREFIX + "".join(f"_{m}{self.current_difficulties[m]}"
                                        for m in self.curriculum_schedulers)
        path = os.path.join(self.cluster_path, name)
        multi = sum(1 for m in self.clustering_type
                    if self.clustering_type[m] != SINGLE_CLUSTER) > 1
        if multi:
            # intersection of every metric's admitted set, minus what earlier
            # clusters already cover (reference multi-metric branch). A metric
            # admitting nothing means an EMPTY intersection — dropping its
            # constraint would train on samples that violate it.
            new = None
            for m in self.curriculum_schedulers:
                if self.clustering_type[m] == SINGLE_CLUSTER:
                    sel = np.arange(self.one_epoch_total_samples, dtype=self.index_dtype)
                else:
                    lo = (float("-inf") if self.difficulty_type[m] == VALUE_BASED else 0)
                    sel = self._admitted(m, lo, self.current_difficulties[m])
                    if sel is None:
                        sel = np.empty(0, self.index_dtype)
                new = sel if new is None else np.intersect1d(new, sel, assume_unique=True)
            for _, cluster in self.data_clusters:
                new = np.setdiff1d(new, cluster[0], assume_unique=True)
        else:
            new = np.arange(self.one_epoch_total_samples, dtype=self.index_dtype) \
                if not self.data_clusters else None
            for m in self.curriculum_schedulers:
                if self.clustering_type[m] != SINGLE_CLUSTER:
                    new = self._admitted(m, previous_difficulties[m], self.current_difficulties[m])
        if new is not None and len(new):
            new = np.asarray(new, self.index_dtype)
            self.np_rng.shuffle(new)
            builder = create_mmap_dataset_builder(path, self.index_dtype)
            builder.add_item(new)
            close_mmap_dataset_builder(builder, path)
            ds = MMapIndexedDataset(path)
            self.data_clusters.append((name, ds))
            self.data_cluster_sizes.append(len(ds[0]))
            self.data_cluster_paths.append(name)
            self.data_cluster_current_position.append(0)
            logger.info(f"data sampler: new cluster {name} with {len(new)} samples")

    def _reshuffle_cluster(self, cidx):
        name = self.data_cluster_paths[cidx]
        path = os.path.join(self.cluster_path, name)
        data = np.copy(self.data_clusters[cidx][1][0])
        self.np_rng.shuffle(data)
        builder = create_mmap_dataset_builder(path, self.index_dtype)
        builder.add_item(data)
        close_mmap_dataset_builder(builder, path)
        self.data_clusters[cidx] = (name, MMapIndexedDataset(path))

    def _sample_from_clusters(self):
        weights = np.asarray(self.data_cluster_sizes, np.float64)
        weights = weights / weights.sum()
        picks = self.np_rng.choice(len(self.data_clusters), self.global_batch_size,
                                   replace=True, p=weights)
        return np.bincount(picks, minlength=len(self.data_clusters))

    def _take_from_cluster(self, cidx, n):
        pos = self.data_cluster_current_position[cidx]
        data = self.data_clusters[cidx][1][0]
        out = list(np.copy(data[pos:pos + n]))
        self.data_cluster_current_position[cidx] = pos + n
        if len(out) < n:
            # wrap-around fill: a cluster smaller than its sampled share must
            # still return n items (clusters are drawn with replacement, so
            # repeats are fine) — a single top-up would come up short and the
            # resulting short global batch would spin under drop_last. One
            # reshuffle + modular cycling, not a disk rewrite per wrap.
            self._reshuffle_cluster(cidx)
            data = self.data_clusters[cidx][1][0]
            remaining = n - len(out)
            reps = np.resize(np.copy(data), remaining)  # cycles when short
            out += list(reps)
            self.data_cluster_current_position[cidx] = remaining % max(len(data), 1)
        return out

    # -- batch generation ---------------------------------------------------
    def get_next_global_batch(self):
        if self.curriculum_enabled:
            self.curriculum_step += 1
            new_cluster = False
            previous = {}
            for m, sched in self.curriculum_schedulers.items():
                nxt = sched.update_difficulty(self.curriculum_step)
                if m not in self.current_difficulties or nxt != self.current_difficulties[m]:
                    new_cluster = True
                previous[m] = self.current_difficulties.get(
                    m, float("-inf") if self.difficulty_type[m] == VALUE_BASED else 0)
                self.current_difficulties[m] = nxt
            if new_cluster:
                self.get_new_cluster(previous)
            if not self.data_clusters:
                raise ValueError(
                    f"curriculum schedule admits no samples at difficulties "
                    f"{self.current_difficulties} (step {self.curriculum_step}); lower "
                    f"min_difficulty or check the metric index covers this range")
            batch = []
            for cidx, n in enumerate(self._sample_from_clusters()):
                batch += self._take_from_cluster(cidx, int(n))
            self.np_rng.shuffle(batch)
        else:
            batch = list(self.np_rng.integers(0, self.one_epoch_total_samples,
                                              self.global_batch_size))
        self.batch = [int(b) for b in batch]

    def __iter__(self):
        while self.consumed_samples <= self.total_samples:
            if not self.batch:
                self.get_next_global_batch()
            current = self.batch[:self.micro_batch_times_data_parallel_size]
            self.batch = self.batch[self.micro_batch_times_data_parallel_size:]
            if len(current) == self.micro_batch_times_data_parallel_size or \
                    (current and not self.drop_last):
                consumed = len(current)
                if consumed < self.micro_batch_times_data_parallel_size:
                    # drop_last=False tail: pad by cycling the partial batch
                    # so every DP rank still sees a full micro_batch_size —
                    # rank-divergent batch shapes would desync SPMD consumers
                    reps = -(-self.micro_batch_times_data_parallel_size // consumed)
                    current = (current * reps)[:self.micro_batch_times_data_parallel_size]
                start = self.data_parallel_rank * self.micro_batch_size
                yield current[start:start + self.micro_batch_size]
                self.consumed_samples += consumed

    # -- checkpoint ---------------------------------------------------------
    def state_dict(self):
        return {
            "batch": list(self.batch),
            "consumed_samples": self.consumed_samples,
            "curriculum_step": self.curriculum_step,
            "current_difficulties": dict(self.current_difficulties),
            "data_cluster_paths": list(self.data_cluster_paths),
            "data_cluster_current_position": list(self.data_cluster_current_position),
            "np_rng_state": self.np_rng.bit_generator.state,
        }

    def load_state_dict(self, sd):
        self.batch = list(sd["batch"])
        self.consumed_samples = sd["consumed_samples"]
        self.curriculum_step = sd["curriculum_step"]
        self.current_difficulties = dict(sd["current_difficulties"])
        self.data_cluster_paths = [os.path.basename(p) for p in sd["data_cluster_paths"]]
        self.data_cluster_current_position = list(sd["data_cluster_current_position"])
        self.np_rng.bit_generator.state = sd["np_rng_state"]
        if self.curriculum_enabled:
            self.data_clusters, self.data_cluster_sizes = [], []
            for name in self.data_cluster_paths:
                ds = MMapIndexedDataset(os.path.join(self.cluster_path, name))
                self.data_clusters.append((name, ds))
                self.data_cluster_sizes.append(len(ds[0]))


class DifficultyDataSampler:
    """Light-weight curriculum sampler: in-memory difficulty array + one
    threshold schedule (no on-disk index). Kept from the round-2 surface for
    quick setups; the reference-parity machinery is ``DeepSpeedDataSampler``."""

    def __init__(self, difficulties, curriculum_scheduler=None, total_samples=None, seed=0,
                 shuffle=True, drop_last=True):
        self.difficulties = np.asarray(difficulties)
        self.total_samples = total_samples or len(self.difficulties)
        self.scheduler = curriculum_scheduler
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.global_steps = 0
        # ascending difficulty order; the active prefix grows with the schedule
        self._order_by_difficulty = np.argsort(self.difficulties, kind="stable")

    def set_epoch(self, epoch):
        self.epoch = epoch

    def advance(self, global_steps):
        self.global_steps = global_steps
        if self.scheduler is not None:
            self.scheduler.update_difficulty(global_steps)

    def _active_indices(self):
        if self.scheduler is None:
            return np.arange(self.total_samples)
        limit = self.scheduler.current_difficulty
        sorted_diff = self.difficulties[self._order_by_difficulty]
        n_active = int(np.searchsorted(sorted_diff, limit, side="right"))
        n_active = max(n_active, 1)  # never an empty pool
        return self._order_by_difficulty[:n_active]

    def __iter__(self):
        active = self._active_indices()
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self.epoch))
            active = rng.permutation(active)
        return iter(active.tolist())

    def __len__(self):
        return len(self._active_indices())

    def state_dict(self):
        return {"epoch": self.epoch, "global_steps": self.global_steps,
                "current_difficulty": None if self.scheduler is None
                else self.scheduler.current_difficulty}

    def load_state_dict(self, sd):
        self.epoch = sd.get("epoch", 0)
        self.advance(sd.get("global_steps", 0))
