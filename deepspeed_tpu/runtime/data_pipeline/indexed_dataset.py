"""Memory-mapped indexed dataset (Megatron ``.bin``/``.idx`` format).

Counterpart of reference ``data_pipeline/data_sampling/indexed_dataset.py``
(``MMapIndexedDataset`` :369, ``MMapIndexedDatasetBuilder`` :575): random
access into a flat binary corpus through an mmap'd index, the on-disk format
Megatron-LM preprocessing emits — so existing preprocessed corpora serve
this framework's curriculum/data-efficiency pipeline unchanged. Pure numpy
(no torch): items are numpy array views straight off the mmap.

On-disk layout (little endian):
  <path>.bin   concatenated item payloads
  <path>.idx   magic 'MMIDIDX\\x00\\x00' | u64 version=1 | u8 dtype code |
               u64 n_items | u64 n_docs | i32 sizes[n_items] |
               i64 pointers[n_items] | i64 doc_idx[n_docs]
"""

import os
import struct

import numpy as np

_HDR_MAGIC = b"MMIDIDX\x00\x00"

dtypes = {
    1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
    6: np.float64, 7: np.double, 8: np.uint16, 9: np.uint32, 10: np.uint64,
}
_CODES = {np.dtype(v): k for k, v in dtypes.items()}


def code(dtype):
    return _CODES[np.dtype(dtype)]


def data_file_path(prefix):
    return prefix + ".bin"


def index_file_path(prefix):
    return prefix + ".idx"


def find_fit_int_dtype(low, high):
    """Smallest integer dtype covering [low, high] (reference utils)."""
    for dt in (np.uint8, np.int8, np.uint16, np.int16, np.uint32, np.int32,
               np.uint64, np.int64):
        info = np.iinfo(dt)
        if info.min <= low and high <= info.max:
            return dt
    return np.int64


class MMapIndexedDataset:
    """Read side: ``ds[i]`` -> 1-D numpy view of item i; slices return lists.

    ``skip_warmup`` accepted for reference parity (the page-cache warmup read
    is pointless under numpy memmap on modern kernels — always skipped).
    """

    def __init__(self, path, skip_warmup=True):
        self._path = path
        with open(index_file_path(path), "rb") as f:
            magic = f.read(len(_HDR_MAGIC))
            if magic != _HDR_MAGIC:
                raise ValueError(f"{index_file_path(path)}: not an MMIDIDX index "
                                 f"(bad magic {magic!r})")
            version, = struct.unpack("<Q", f.read(8))
            if version != 1:
                raise ValueError(f"unsupported index version {version}")
            dtype_code, = struct.unpack("<B", f.read(1))
            self._dtype = dtypes[dtype_code]
            self._len, = struct.unpack("<Q", f.read(8))
            self._doc_count, = struct.unpack("<Q", f.read(8))
            offset = f.tell()
        idx_buf = np.memmap(index_file_path(path), mode="r", order="C")
        self._sizes = np.frombuffer(idx_buf, np.int32, count=self._len, offset=offset)
        self._pointers = np.frombuffer(idx_buf, np.int64, count=self._len,
                                       offset=offset + self._sizes.nbytes)
        self._doc_idx = np.frombuffer(idx_buf, np.int64, count=self._doc_count,
                                      offset=offset + self._sizes.nbytes + self._pointers.nbytes)
        self._bin = np.memmap(data_file_path(path), mode="r", order="C")

    def __len__(self):
        return self._len

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return [self[i] for i in range(*idx.indices(self._len))]
        if idx < 0:
            idx += self._len
        ptr, size = int(self._pointers[idx]), int(self._sizes[idx])
        return np.frombuffer(self._bin, self._dtype, count=size, offset=ptr)

    def get(self, idx, offset=0, length=None):
        """Partial item read (reference ``get``)."""
        ptr, size = int(self._pointers[idx]), int(self._sizes[idx])
        if length is None:
            length = size - offset
        ptr += offset * np.dtype(self._dtype).itemsize
        return np.frombuffer(self._bin, self._dtype, count=length, offset=ptr)

    @property
    def sizes(self):
        return self._sizes

    @property
    def doc_idx(self):
        return self._doc_idx

    @property
    def dtype(self):
        return self._dtype

    @staticmethod
    def exists(path):
        return os.path.exists(index_file_path(path)) and os.path.exists(data_file_path(path))


class MMapIndexedDatasetBuilder:
    """Write side (reference :575): stream items into ``.bin``, then
    ``finalize`` writes the index."""

    def __init__(self, out_file, dtype=np.int64):
        self._file = open(out_file, "wb")
        self._dtype = np.dtype(dtype)
        self._sizes = []
        self._doc_idx = [0]

    def add_item(self, array):
        arr = np.ascontiguousarray(np.asarray(array).reshape(-1), self._dtype)
        self._file.write(arr.tobytes(order="C"))
        self._sizes.append(arr.size)

    add_item_numpy = add_item

    def end_document(self):
        self._doc_idx.append(len(self._sizes))

    def merge_file_(self, another_file):
        """Append another dataset with the same dtype (reference parity)."""
        other = MMapIndexedDataset(another_file)
        if np.dtype(other.dtype) != self._dtype:
            raise ValueError(f"dtype mismatch: {other.dtype} vs {self._dtype}")
        base = len(self._sizes)
        for i in range(len(other)):
            self.add_item(other[i])
        for d in other.doc_idx[1:]:
            self._doc_idx.append(base + int(d))

    def finalize(self, index_file):
        self._file.close()
        sizes = np.asarray(self._sizes, np.int32)
        pointers = np.zeros(len(sizes), np.int64)
        if len(sizes):
            np.cumsum(sizes[:-1].astype(np.int64) * self._dtype.itemsize, out=pointers[1:])
        with open(index_file, "wb") as f:
            f.write(_HDR_MAGIC)
            f.write(struct.pack("<Q", 1))
            f.write(struct.pack("<B", code(self._dtype)))
            f.write(struct.pack("<Q", len(sizes)))
            f.write(struct.pack("<Q", len(self._doc_idx)))
            f.write(sizes.tobytes(order="C"))
            f.write(pointers.tobytes(order="C"))
            f.write(np.asarray(self._doc_idx, np.int64).tobytes(order="C"))


def create_mmap_dataset_builder(path, dtype):
    return MMapIndexedDatasetBuilder(data_file_path(path), dtype=dtype)


def close_mmap_dataset_builder(builder, path):
    builder.end_document()
    builder.finalize(index_file_path(path))
