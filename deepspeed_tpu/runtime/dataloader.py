"""Data loading.

Analogue of reference ``deepspeed/runtime/dataloader.py``
(``DeepSpeedDataLoader``, ``RepeatingLoader``). Produces numpy microbatches
for the engine; accepts map-style datasets (``__len__``/``__getitem__``,
including torch Datasets), iterables of samples, or iterables that already
yield batches. Distributed sampling note: the engine places the *global*
batch onto the mesh itself, so on a single host the loader yields global
batches; multi-host feeding uses per-process shards assembled by
``jax.make_array_from_process_local_data``.
"""

import numpy as np


def default_collate(samples):
    """Stack a list of samples (dicts / tuples / arrays) into numpy batches."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate([s[i] for s in samples]) for i in range(len(first)))
    return np.stack([np.asarray(s) for s in samples])


class DeepSpeedDataLoader:

    def __init__(self,
                 dataset,
                 batch_size,
                 collate_fn=None,
                 drop_last=True,
                 seed=0,
                 shuffle=True,
                 data_sampler=None,
                 num_shards=1,
                 shard_index=0):
        """``num_shards``/``shard_index``: DistributedSampler-style split of
        the sample stream across feeding processes — every process must use
        the same seed so the global shuffle agrees, then each takes its own
        interleaved slice (no duplicated samples across hosts)."""
        if batch_size < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        if not (0 <= shard_index < num_shards):
            raise ValueError(f"shard_index {shard_index} out of range for {num_shards} shards")
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or default_collate
        self.drop_last = drop_last
        self.shuffle = shuffle
        self.data_sampler = data_sampler
        self.num_shards = num_shards
        self.shard_index = shard_index
        self.epoch = 0
        self._rng = np.random.default_rng(seed)
        self.len = None
        if data_sampler is not None and hasattr(data_sampler, "total_samples"):
            # batch-index samplers own membership AND epoch count; length
            # derives from the sampler, not the dataset (a DeepSpeedDataSampler
            # spans num_epochs worth of micro-batches)
            # per-RANK batches: the sampler hands each rank one micro-batch
            # per micro_batch_size*data_parallel_size consumed samples
            mbdp = getattr(data_sampler, "micro_batch_times_data_parallel_size",
                           getattr(data_sampler, "micro_batch_size", batch_size))
            self.len = int(data_sampler.total_samples) // max(1, int(mbdp))
        elif hasattr(dataset, "__len__") and hasattr(dataset, "__getitem__"):
            n = len(dataset) // num_shards
            self.len = n // batch_size if drop_last else (n + batch_size - 1) // batch_size

    def __len__(self):
        if self.len is None:
            raise TypeError("underlying dataset has no length")
        return self.len

    def _iter_map_style(self):
        n = len(self.dataset)
        if self.data_sampler is not None:
            it = iter(self.data_sampler)
            try:
                first = next(it)
            except StopIteration:
                return
            if np.ndim(first) >= 1:
                # batch-index sampler (e.g. DeepSpeedDataSampler): each item
                # IS this rank's micro-batch index list — honor it as-is
                # (the curriculum decides membership, order AND sharding)
                import itertools
                for idx_list in itertools.chain([first], it):
                    yield self.collate_fn([self.dataset[int(i)] for i in idx_list])
                return
            # per-sample sampler (e.g. DifficultyDataSampler): it yields a
            # scalar order; batch + shard it like a plain shuffle
            order = np.asarray([int(first)] + [int(i) for i in it])
        else:
            order = np.arange(n)
            if self.shuffle:
                self._rng.shuffle(order)
        n = len(order)  # shard equalization must use the SAMPLED length
        if self.num_shards > 1:
            # equal shard sizes keep multi-host collectives in lockstep: drop
            # the tail so every process sees the same number of batches
            # (DistributedSampler-style; a ragged tail would desync epochs)
            usable = n - n % self.num_shards
            order = order[:usable][self.shard_index::self.num_shards]
        for start in range(0, len(order), self.batch_size):
            idx = order[start:start + self.batch_size]
            if len(idx) < self.batch_size and self.drop_last:
                break
            yield self.collate_fn([self.dataset[int(i)] for i in idx])

    def _iter_iterable(self):
        if self.num_shards == 1:
            yield from self._iter_iterable_shard(iter(self.dataset))
            return
        # consume the stream in rounds of num_shards and keep only complete
        # rounds, so every shard sees exactly the same sample count (a ragged
        # tail would desync multi-host collectives — same rule as map-style)
        def my_samples():
            it = iter(self.dataset)
            while True:
                round_ = []
                for _ in range(self.num_shards):
                    try:
                        round_.append(next(it))
                    except StopIteration:
                        return  # incomplete final round: dropped on all shards
                yield round_[self.shard_index]

        yield from self._iter_iterable_shard(my_samples())

    def _iter_iterable_shard(self, samples):
        buf = []
        for sample in samples:
            buf.append(sample)
            if len(buf) == self.batch_size:
                yield self.collate_fn(buf)
                buf = []
        if buf and not self.drop_last:
            yield self.collate_fn(buf)

    def __iter__(self):
        self.epoch += 1
        if hasattr(self.dataset, "__len__") and hasattr(self.dataset, "__getitem__"):
            return self._iter_map_style()
        return self._iter_iterable()


class RepeatingLoader:
    """Wrap an iterator to restart on StopIteration (reference
    ``dataloader.py`` RepeatingLoader)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)
