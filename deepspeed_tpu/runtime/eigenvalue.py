"""Hessian eigenvalue estimation (power iteration).

TPU-native analogue of reference ``deepspeed/runtime/eigenvalue.py``
(``Eigenvalue``, used by MoQ to schedule quantization by curvature). The
reference power-iterates on accumulated gradients of a torch block; here the
Hessian-vector product is exact via ``jax.jvp`` over ``jax.grad`` (functional
autodiff — no double-backward hooks), and the iteration runs per top-level
parameter subtree.
"""

import jax
import jax.numpy as jnp

from ..utils.logging import logger


class Eigenvalue:

    def __init__(self, verbose=False, max_iter=100, tol=1e-2, stability=1e-6,
                 gas_boundary_resolution=1, layer_name="", layer_num=0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    def _normalize(self, tree):
        norm = jnp.sqrt(sum(jnp.sum(jnp.square(v)) for v in jax.tree_util.tree_leaves(tree)))
        scale = 1.0 / (norm + self.stability)
        return jax.tree_util.tree_map(lambda v: v * scale, tree), norm

    def compute_eigenvalue(self, loss_fn, params, batch, rng=None, key=0):
        """Top |eigenvalue| of the loss Hessian w.r.t. each top-level subtree
        of ``params``. Returns {subtree_name: float}."""

        def hvp(f, primal, tangent):
            return jax.jvp(jax.grad(f), (primal, ), (tangent, ))[1]

        results = {}
        names = list(params.keys()) if isinstance(params, dict) else [None]
        for name in names:
            sub = params[name] if name is not None else params

            def sub_loss(sub_params):
                full = dict(params, **{name: sub_params}) if name is not None else sub_params
                return loss_fn(full, batch, rng)

            v = jax.tree_util.tree_map(
                lambda x: jax.random.normal(jax.random.fold_in(jax.random.key(key), hash(name) % (2**31)),
                                            x.shape, jnp.float32), sub)
            v, _ = self._normalize(v)
            eig = 0.0
            for it in range(self.max_iter):
                hv = hvp(sub_loss, sub, v)
                v, norm = self._normalize(hv)
                prev, eig = eig, float(norm)
                if eig and abs(eig - prev) / (abs(eig) + self.stability) < self.tol:
                    break
            results[name if name is not None else "all"] = eig
            if self.verbose:
                logger.info(f"eigenvalue[{name}] ~= {eig:.4e} ({it + 1} iters)")
        return results
