"""Training engine.

TPU-native analogue of reference ``deepspeed/runtime/engine.py``
(``DeepSpeedEngine`` :181, ``forward`` :1624, ``backward`` :1765, ``step``
:1961, ``save_checkpoint`` :2802, ``load_checkpoint`` :2497). Design
translation (SURVEY §7): instead of wrapping an eager module with hooks, the
engine compiles ONE fused train step — forward, backward, gradient
accumulation (``lax.scan``), ZeRO resharding, clipping, optimizer update,
loss-scale management — into a single pjit program over the device mesh.
A ``forward()/backward()/step()`` 3-call facade is kept for API parity.

Model contract (the eager-module contract cannot survive tracing): ``model``
is a pure loss function ``loss_fn(params, batch, rng) -> loss`` (or
``(loss, aux_dict)``), or an object exposing ``.loss`` with that signature
(all models in ``deepspeed_tpu.models`` do), or a Flax module whose
``apply`` returns the loss.
"""

import json
import os
import time
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..accelerator import get_accelerator
from ..comm import comm as dist
from ..utils.logging import logger, log_dist
from ..utils.timer import (SynchronizedWallClockTimer, ThroughputTimer, NoopTimer, FORWARD_GLOBAL_TIMER,
                           BACKWARD_GLOBAL_TIMER, STEP_GLOBAL_TIMER, _device_sync)
from .config import DeepSpeedConfig
from .constants import (ADAM_OPTIMIZER, ADAMW_OPTIMIZER, FUSED_ADAM_OPTIMIZER, CPU_ADAM_OPTIMIZER,
                        ADAGRAD_OPTIMIZER, LAMB_OPTIMIZER, SGD_OPTIMIZER, LION_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
                        ONEBIT_LAMB_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER)
from .fp16.loss_scaler import create_loss_scaler
from .lr_schedules import get_lr_schedule, _LRSchedule
from .zero.config import ZeroStageEnum
from .zero.sharding import ShardingPlanner, TensorParallelRules


class TrainState(NamedTuple):
    """All mutable training state, as one sharded pytree."""
    step: Any  # i32 scalar
    params: Any  # fp32 master params (ZeRO-sharded per stage)
    opt_state: Any  # optimizer moments (ZeRO-sharded at stage >= 1)
    grad_acc: Any  # gradient accumulator — empty {} until the 3-call facade
    # is used (the fused train_batch path scans its own accumulator, so no
    # param-sized HBM buffer is carried there)
    micro_step: Any  # i32 scalar: micro-batches seen since last step()
    loss_scale: Any  # LossScaleState
    skipped_steps: Any  # i32 scalar


def _resolve_loss_fn(model):
    if hasattr(model, "loss") and callable(model.loss):
        return model.loss
    if hasattr(model, "apply"):  # Flax module

        def flax_loss(params, batch, rng):
            out = model.apply({"params": params}, batch, rngs={"dropout": rng} if rng is not None else None)
            if not (hasattr(out, "ndim") and out.ndim == 0):
                raise ValueError("Flax module passed as `model` must return a scalar loss from apply(); "
                                 "wrap it in a loss function or pass loss_fn(params, batch, rng) directly")
            return out

        return flax_loss
    if callable(model):
        return model
    raise ValueError(f"Cannot resolve a loss function from model of type {type(model)}")


class DeepSpeedEngine:

    def __init__(self,
                 model,
                 config=None,
                 config_class=None,
                 optimizer=None,
                 model_parameters=None,
                 training_data=None,
                 lr_scheduler=None,
                 mpu=None,
                 dist_init_required=None,
                 collate_fn=None,
                 dont_change_device=False,
                 tp_rules=None,
                 expert_pattern=None,
                 rng_seed=None):
        self.module = model
        self.loss_fn = _resolve_loss_fn(model)
        self.client_optimizer = optimizer
        self.client_lr_scheduler = lr_scheduler
        self.training_data = training_data
        self.collate_fn = collate_fn
        self.mpu = mpu
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self.skipped_steps = 0
        self.loaded_checkpoint_tag = None

        self._config = config_class if config_class is not None else DeepSpeedConfig(
            config, mpu, world_size=dist.get_world_size())

        # ---- mesh --------------------------------------------------------
        m = self._config.mesh
        if dist.has_mesh():
            self.mesh = dist.get_mesh()
        else:
            self.mesh = dist.initialize_mesh(pipe=m.pipeline_parallel_size,
                                             expert=m.expert_parallel_size,
                                             seq=m.sequence_parallel_size,
                                             tensor=m.tensor_parallel_size)

        # ---- precision ---------------------------------------------------
        self.compute_dtype = self._config.compute_dtype
        self.loss_scaler = create_loss_scaler(self._config.fp16 if self._config.fp16.enabled else None)
        self.dynamic_loss_scale = self._config.dynamic_loss_scale

        # ---- activation checkpointing (reference runtime/
        # activation_checkpointing/checkpointing.py:708; here a remat policy
        # applied to the model before compilation) ---------------------------
        ac = self._config.activation_checkpointing
        if ac.policy is not None or ac.partition_activations or ac.cpu_checkpointing:
            policy = ac.policy or "nothing_saveable"
            if hasattr(model, "set_remat_policy"):
                if getattr(getattr(model, "cfg", None), "remat_policy", None) != policy:
                    model.set_remat_policy(policy)
                    log_dist(f"activation checkpointing: remat policy '{policy}' applied", [0])
            else:
                logger.warning(
                    "activation_checkpointing configured but the model exposes no "
                    "set_remat_policy(policy) hook — section has NO effect; apply "
                    "jax.checkpoint in the model yourself")
            if ac.partition_activations:
                log_dist("activation_checkpointing.partition_activations: subsumed by the "
                         "sharding propagation of saved residuals (XLA keeps remat residuals "
                         "in their sharded layout; no gather/scatter pass is needed)", [0])

        # ---- sharding plan (ZeRO stages as placement rules) --------------
        if tp_rules is None and hasattr(model, "tp_rules"):
            tp_rules = model.tp_rules()
        if expert_pattern is None and hasattr(model, "expert_pattern"):
            expert_pattern = model.expert_pattern()
        pipe_pattern = model.pipeline_pattern() if hasattr(model, "pipeline_pattern") else None
        if self.mesh.shape[dist.PIPE_AXIS] > 1:
            if not (hasattr(model, "pipeline_loss") and pipe_pattern):
                raise ValueError(
                    "pipeline_parallel_size > 1 requires a model exposing pipeline_loss() and "
                    "pipeline_pattern() (all deepspeed_tpu.models with scan_layers=True do)")
            # MoE aux loss flows through the pipeline's aux channel
            # (spmd_pipeline with_aux; valid-tick masked, psum over pipe)
        self.planner = ShardingPlanner(self.mesh,
                                       self._config.zero_optimization,
                                       tp_rules=tp_rules,
                                       expert_pattern=expert_pattern,
                                       pipe_pattern=pipe_pattern)

        # ---- ZeRO-Offload (optimizer state in host DRAM) -----------------
        off = self._config.zero_optimization.offload_optimizer
        self.offload_optimizer = off.device in ("cpu", "nvme")
        if off.device == "nvme" and not off.nvme_path:
            raise ValueError("offload_optimizer.device='nvme' requires nvme_path")
        if self.offload_optimizer and self.mesh.shape[dist.PIPE_AXIS] > 1:
            raise NotImplementedError("offload_optimizer does not yet compose with "
                                      "pipeline_parallel_size > 1")
        self.host_opt = None

        # ---- ZeRO-Infinity parameter offload (streamed step) -------------
        offp = self._config.zero_optimization.offload_param
        self.offload_param = offp.device in ("cpu", "nvme")
        self.param_stream = None
        if self.offload_param:
            if self._config.zero_optimization.stage != 3:
                raise ValueError("offload_param requires zero stage 3 (reference "
                                 "zero/stage3.py:463 configures param swapping under "
                                 "stage 3 only)")
            if self.mesh.shape[dist.PIPE_AXIS] > 1:
                raise NotImplementedError("offload_param does not compose with "
                                          "pipeline_parallel_size > 1")
            if not hasattr(model, "stream_plan"):
                raise ValueError("offload_param requires a model exposing the parameter "
                                 "streaming protocol (stream_plan/stream_embed/stream_layer/"
                                 "stream_tail_loss — deepspeed_tpu.models transformers do)")
            if self.offload_optimizer:
                log_dist("offload_param subsumes offload_optimizer: the streamed step keeps "
                         "fp32 master + moments host-resident by construction", [0])
                self.offload_optimizer = False

        # ---- params ------------------------------------------------------
        if model_parameters is None and hasattr(model, "init_params"):
            model_parameters = None  # initialized sharded below
        self._seed = self._config.seed if rng_seed is None else rng_seed
        self._base_rng = jax.random.key(self._seed)

        if self.offload_param:
            # params never materialize on device: the runner owns host blocks
            # and the streamed step (no fused pjit state)
            from .zero.param_offload import ParamStreamRunner
            self.lr_schedule_fn, self.lr_scheduler = self._configure_lr_scheduler(lr_scheduler)
            self._onebit = None
            self.tx = None
            self.param_stream = ParamStreamRunner(
                model, self._config, self.mesh, self.planner, self.compute_dtype,
                self.lr_schedule_fn, rng_seed=self._seed)
            self.state_shardings = None
            self.state = TrainState(step=jnp.zeros((), jnp.int32), params={}, opt_state={},
                                    grad_acc={}, micro_step=jnp.zeros((), jnp.int32),
                                    loss_scale=self.loss_scaler.init_state(),
                                    skipped_steps=jnp.zeros((), jnp.int32))
        else:
            params = self._init_params(model, model_parameters)

            # ---- optimizer -----------------------------------------------
            self.lr_schedule_fn, self.lr_scheduler = self._configure_lr_scheduler(lr_scheduler)
            self._onebit = None  # set when a 1-bit/0-1 optimizer is configured
            self.tx = self._configure_optimizer(optimizer)

            # ---- state + shardings ---------------------------------------
            self.state_shardings = None
            if self.offload_optimizer:
                params = self._init_host_optimizer(params)
            self.state = self._init_state(params)
            del params

        # ---- curriculum learning + progressive layer drop ----------------
        # (legacy `curriculum_learning` section, reference engine.py:1663
        # seqlen truncation; `progressive_layer_drop`, engine.py:1658)
        cl_cfg = dict(self._config.raw_config.get("curriculum_learning", {}))
        self.curriculum_scheduler = None
        if cl_cfg.get("enabled"):
            from .data_pipeline.curriculum_scheduler import CurriculumScheduler
            self.curriculum_scheduler = CurriculumScheduler(cl_cfg)
            self.curriculum_type = cl_cfg.get("curriculum_type", "seqlen")
        pld_cfg = dict(self._config.raw_config.get("progressive_layer_drop", {}))
        self.progressive_layer_drop = None
        if pld_cfg.get("enabled"):
            from .progressive_layer_drop import ProgressiveLayerDrop
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=pld_cfg.get("theta", 0.5), gamma=pld_cfg.get("gamma", 0.001))
        # random-LTD (reference data_efficiency.data_routing.random_ltd,
        # data_routing/scheduler.py:38): keep-length schedule; the model does
        # the per-layer token gather/scatter with a static keep per compile
        routing_cfg = dict(dict(self._config.raw_config.get("data_efficiency", {}))
                           .get("data_routing", {}))
        ltd_cfg = dict(routing_cfg.get("random_ltd", {}))
        self.random_ltd_scheduler = None
        if routing_cfg.get("enabled") and ltd_cfg.get("enabled"):
            from .data_pipeline.data_routing import RandomLTDScheduler
            if not getattr(model, "supports_random_ltd", False):
                raise ValueError("random_ltd enabled but the model does not support it "
                                 "(no set_random_ltd; deepspeed_tpu.models transformers do)")
            if self.mesh.shape[dist.PIPE_AXIS] > 1:
                raise NotImplementedError("random_ltd does not compose with "
                                          "pipeline_parallel_size > 1 (pipeline_loss does not "
                                          "consume the keep length)")
            self.random_ltd_scheduler = RandomLTDScheduler(ltd_cfg)
            if not self.random_ltd_scheduler.random_ltd_layer_id:
                # default: every layer (reference requires the list; all-layers
                # is the only choice that also matches scanned models)
                n_layers = getattr(getattr(model, "cfg", None), "num_layers", 0)
                self.random_ltd_scheduler.random_ltd_layer_id = list(range(n_layers))
            if getattr(getattr(model, "cfg", None), "scan_layers", False):
                n_layers = model.cfg.num_layers
                if len(self.random_ltd_scheduler.random_ltd_layer_id) != n_layers:
                    logger.warning("random_ltd: scan_layers models apply token dropping to "
                                   "EVERY layer; the configured random_ltd_layer_id subset "
                                   "is ignored (use scan_layers=False for per-layer control)")
            self._ltd_current = None
        # data_efficiency.data_sampling: consumed by deepspeed_io (reference
        # builds the curriculum sampler into its dataloader,
        # data_pipeline/data_sampler.py:36); flag it so deepspeed_io wires a
        # DeepSpeedDataSampler when the user hands us the training_data
        self._data_sampling_cfg = dict(dict(self._config.raw_config
                                            .get("data_efficiency", {}))
                                       .get("data_sampling", {}))
        self._data_sampler = None
        self._pending_sampler_state = None  # checkpoint state loaded pre-sampler

        # ---- timers / monitor / telemetry / io ---------------------------
        self.wall_clock_breakdown = self._config.wall_clock_breakdown
        from ..monitor.monitor import MonitorMaster
        self.monitor = MonitorMaster(self._config)
        from ..telemetry import TelemetrySink, set_sink
        # the sink is the single reporting call site: gauges fan out to the
        # monitor backends; file output (JSONL + trace.json) only when the
        # 'telemetry' config section is enabled (default-off)
        self.telemetry = TelemetrySink(self._config.telemetry, monitor=self.monitor)
        if self.telemetry.enabled:
            set_sink(self.telemetry)
        self._trace_spans = self.wall_clock_breakdown or self.telemetry.enabled
        self.timers = SynchronizedWallClockTimer() if self._trace_spans else NoopTimer()
        self.tput_timer = ThroughputTimer(batch_size=self.train_batch_size(),
                                          steps_per_output=self._config.steps_per_print)
        self._step_flops = None  # XLA cost-analysis FLOPs of one optimizer step
        self._last_step_dur = None  # seconds, measured around the last step
        self._grad_sync_bytes_cached = None
        # SLO engine (telemetry.slo section): evaluated at the reporting
        # interval so MFU/overlap-efficiency floors can burn-rate alert on
        # the training side too (the serving gateway builds its own)
        self._slo = None
        if self.telemetry.enabled and self.telemetry.slo_config.get("objectives"):
            from ..telemetry import SLOEngine
            self._slo = SLOEngine(self.telemetry, self.telemetry.slo_config)
        # on-demand XLA profiling (telemetry/profiler.py): captures
        # requested via request_profile() start at the next REPORT boundary
        # (never mid-dispatch); telemetry.profile_report_s > 0 auto-arms one
        # capture of that duration at the first report interval
        self.profiler = None
        if self.telemetry.enabled:
            from ..telemetry.profiler import XlaProfiler
            self.profiler = XlaProfiler(self.telemetry.output_path)
            auto_s = float(getattr(self._config.telemetry,
                                   "profile_report_s", 0.0) or 0.0)
            if auto_s > 0.0:
                self.profiler.request(auto_s)
        self._fwd_since_step = 0  # facade micro-steps since the last step()
        self._facade_t0 = None

        self.training_dataloader = self.deepspeed_io(training_data) if training_data is not None else None

        # ---- compiled steps ----------------------------------------------
        self._compiled = {}
        self._pending_batches = []
        self._last_metrics = None
        self._eigenvalue = None  # built lazily from the 'eigenvalue' section

        log_dist(
            f"DeepSpeedEngine ready: world={dist.get_world_size()} mesh={dict(self.mesh.shape)} "
            f"zero_stage={self.zero_optimization_stage()} dtype={jnp.dtype(self.compute_dtype).name} "
            f"micro_bs={self.train_micro_batch_size_per_gpu()} gas={self.gradient_accumulation_steps()}", [0])

    # ------------------------------------------------------------------ config accessors
    # (parity with reference engine.py:456-819 get_* properties)
    def train_batch_size(self):
        return self._config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self._config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self._config.gradient_accumulation_steps

    def zero_optimization_stage(self):
        return self._config.zero_optimization.stage

    def zero_optimization(self):
        return self._config.zero_enabled

    def gradient_clipping(self):
        return self._config.gradient_clipping

    def steps_per_print(self):
        return self._config.steps_per_print

    def bfloat16_enabled(self):
        return self._config.bf16.enabled

    def fp16_enabled(self):
        return self._config.fp16.enabled

    def dp_world_size(self):
        return dist.get_world_size(dist.DP_AXES)

    @property
    def config(self):
        return self._config

    @property
    def params(self):
        return self.state.params

    def get_lr(self):
        return [float(self.lr_schedule_fn(jnp.asarray(self.global_steps, jnp.float32)))]

    def loss_scale(self):
        return float(self.state.loss_scale.cur_scale)

    # ------------------------------------------------------------------ init helpers
    def _init_params(self, model, model_parameters):
        """Materialize fp32 master params directly into their ZeRO sharding.

        The TPU equivalent of ``zero.Init`` (``partition_parameters.py:601``):
        parameters are *born sharded* — jit-evaluating the initializer with
        sharded out_shardings means no device ever holds the full model
        (critical for 70B-class models).
        """
        if model_parameters is not None:
            specs = self.planner.master_specs(model_parameters)
            shardings = self.planner.shardings(specs)
            cast = jax.jit(lambda p: jax.tree_util.tree_map(lambda x: jnp.asarray(x, jnp.float32), p),
                           out_shardings=shardings)
            return cast(model_parameters)
        if hasattr(model, "init_params"):
            abstract = jax.eval_shape(model.init_params, self._base_rng)
            specs = self.planner.master_specs(abstract)
            shardings = self.planner.shardings(specs)
            init = jax.jit(lambda rng: jax.tree_util.tree_map(lambda x: jnp.asarray(x, jnp.float32),
                                                              model.init_params(rng)),
                           out_shardings=shardings)
            with self.mesh:
                return init(self._base_rng)
        raise ValueError("Provide model_parameters or a model with init_params(rng)")

    def _init_host_optimizer(self, params_f32):
        """ZeRO-Offload: move fp32 master + moments to host DRAM (or NVMe —
        ZeRO-Infinity), PARTITIONED per host over the DP axes, and return the
        compute-dtype device params that replace them in TrainState. HBM
        afterwards holds only ~2 bytes/param instead of 16, host DRAM holds
        12 bytes/param ÷ dp_world (and with NVMe, only a rotating block
        window)."""
        from .zero.offload import HostOffloadOptimizer
        off = self._config.zero_optimization.offload_optimizer
        if off.device == "nvme":
            from .swap_tensor import NVMeOffloadOptimizer, get_aio_config
            self.host_opt = NVMeOffloadOptimizer(
                self._config.optimizer, self.lr_schedule_fn, nvme_path=off.nvme_path,
                aio_config=get_aio_config(self._config.raw_config),
                pipeline_read=bool(off.pipeline_read),
                pipeline_write=bool(off.pipeline_write))
            self.host_opt.compute_dtype = self.compute_dtype
        else:
            self.host_opt = HostOffloadOptimizer(self._config.optimizer, self.lr_schedule_fn)
        # lay the master out in the offload sharding (scattered over DP even
        # at stage 0) so each host pulls exactly its partition
        off_shardings = self.planner.shardings(self.planner.offload_specs(params_f32))
        reshard = jax.jit(lambda p: p, donate_argnums=(0, ), out_shardings=off_shardings)
        with self.mesh:
            params_off = reshard(params_f32)
        self.host_opt.init_from_device(params_off)
        shardings = self.planner.shardings(self.planner.master_specs(params_off))
        cast = jax.jit(lambda p: jax.tree_util.tree_map(lambda x: jnp.asarray(x, self.compute_dtype), p),
                       donate_argnums=(0, ), out_shardings=shardings)
        with self.mesh:
            compute_params = cast(params_off)
        tier = "NVMe" if off.device == "nvme" else "host DRAM"
        log_dist(f"ZeRO-Offload: {self.host_opt.num_params():,} params' optimizer state on {tier} "
                 f"(this host's partition, native cpu_adam), "
                 f"{jnp.dtype(self.compute_dtype).name} compute copy in HBM", [0])
        return compute_params

    def _init_state(self, params):
        master_specs = self.planner.master_specs(params)
        master_shardings = self.planner.shardings(master_specs)
        scalar = NamedSharding(self.mesh, P())

        if self.offload_optimizer:
            opt_state, opt_shardings = {}, {}
        elif self._onebit:
            # per-worker state (error feedback differs across DP ranks): every
            # leaf carries a leading dp dim, sharded over the data axis
            dp = self.mesh.shape[dist.DATA_AXIS]
            base = jax.eval_shape(self.tx.init, params)
            opt_state = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct((dp, ) + x.shape, x.dtype), base)
            opt_shardings = jax.tree_util.tree_map(
                lambda _: NamedSharding(self.mesh, P(dist.DATA_AXIS)), opt_state)
        else:
            opt_state = jax.eval_shape(self.tx.init, params)
            opt_shardings = self.planner.opt_state_shardings(opt_state, params)

        self.state_shardings = TrainState(
            step=scalar,
            params=master_shardings,
            opt_state=opt_shardings,
            grad_acc={},
            micro_step=scalar,
            loss_scale=jax.tree_util.tree_map(lambda _: scalar, self.loss_scaler.init_state()),
            skipped_steps=scalar,
        )

        def init_opt(p):
            if self.offload_optimizer:
                return {}
            if self._onebit:
                dp = self.mesh.shape[dist.DATA_AXIS]
                return jax.tree_util.tree_map(
                    lambda x: jnp.broadcast_to(x[None], (dp, ) + x.shape), self.tx.init(p))
            return self.tx.init(p)

        init_fn = jax.jit(
            lambda p: TrainState(
                step=jnp.zeros((), jnp.int32),
                params=p,
                opt_state=init_opt(p),
                grad_acc={},
                micro_step=jnp.zeros((), jnp.int32),
                loss_scale=self.loss_scaler.init_state(),
                skipped_steps=jnp.zeros((), jnp.int32),
            ),
            out_shardings=self.state_shardings,
        )
        with self.mesh:
            return init_fn(params)

    def _ensure_grad_acc(self):
        """Materialize the facade gradient-accumulation buffer on first use.

        The fused ``train_batch`` path never needs it, so a param-sized HBM
        buffer (~280 GB across the mesh at 70B fp32) is only paid when the
        forward/backward/step facade is actually exercised."""
        if jax.tree_util.tree_leaves(self.state.grad_acc):
            return
        grad_shardings = self.planner.shardings(self.planner.grad_specs(self.state.params))
        self.state_shardings = self.state_shardings._replace(grad_acc=grad_shardings)
        alloc = jax.jit(lambda s: s._replace(grad_acc=jax.tree_util.tree_map(jnp.zeros_like, s.params)),
                        donate_argnums=(0, ), out_shardings=self.state_shardings)
        with self.mesh:
            self.state = alloc(self.state)
        self._compiled.clear()  # compiled fns embed the old state shardings

    def _drop_grad_acc(self):
        """Return state to the canonical (no accumulator) structure."""
        if not jax.tree_util.tree_leaves(self.state.grad_acc):
            return
        self.state_shardings = self.state_shardings._replace(grad_acc={})
        self.state = self.state._replace(grad_acc={})
        self._compiled.clear()

    def _configure_lr_scheduler(self, client_lr_scheduler):
        """Returns (pure step->lr fn folded into the compiled step, stateful
        facade object or None). Reference engine.py:836."""
        sched_cfg = self._config.scheduler
        if client_lr_scheduler is not None:
            if isinstance(client_lr_scheduler, _LRSchedule):
                return client_lr_scheduler.__call__, client_lr_scheduler
            if callable(client_lr_scheduler):
                return client_lr_scheduler, None
            raise ValueError("lr_scheduler must be a deepspeed_tpu schedule or a step->lr callable")
        if sched_cfg.type is not None:
            sched = get_lr_schedule(sched_cfg.type, sched_cfg.params)
            return sched.__call__, sched
        base_lr = self._config.optimizer.params.get("lr", 1e-3)
        return (lambda step: jnp.asarray(base_lr, jnp.float32)), None

    def _configure_optimizer(self, client_optimizer):
        """Build the optax gradient transformation (reference
        ``_configure_basic_optimizer`` engine.py:1197). The LR schedule is
        passed as an optax schedule so it lives inside the compiled step.
        LoRA models with ``only_optimize_lora`` get the transformation
        masked to adapter leaves — optimizer state is allocated for adapters
        only (the DeepSpeed-Chat actor memory profile)."""
        from .lora import LoRAModel
        tx = self._configure_optimizer_inner(client_optimizer)
        if isinstance(self.module, LoRAModel) and self.module.only_optimize_lora:
            tx = optax.masked(tx, self.module.optimizer_mask)
            log_dist("LoRA: optimizer masked to adapter leaves "
                     f"(r={self.module.r}, alpha={self.module.alpha})", [0])
        return tx

    def _configure_optimizer_inner(self, client_optimizer):
        if client_optimizer is not None:
            if isinstance(client_optimizer, optax.GradientTransformation):
                return client_optimizer
            raise ValueError("client optimizer must be an optax.GradientTransformation")

        cfg = self._config.optimizer
        name = (cfg.type or ADAMW_OPTIMIZER).lower()
        p = dict(cfg.params)
        lr = self.lr_schedule_fn
        betas = p.get("betas", (0.9, 0.999))
        eps = p.get("eps", 1e-8)
        wd = p.get("weight_decay", 0.0)

        if name in (ADAM_OPTIMIZER, FUSED_ADAM_OPTIMIZER, CPU_ADAM_OPTIMIZER):
            # reference Adam defaults to adam_w_mode=True (ops/adam/fused_adam.py)
            if p.get("adam_w_mode", True):
                return optax.adamw(lr, b1=betas[0], b2=betas[1], eps=eps, weight_decay=wd)
            return optax.chain(optax.scale_by_adam(b1=betas[0], b2=betas[1], eps=eps),
                               optax.add_decayed_weights(wd) if wd else optax.identity(),
                               optax.scale_by_learning_rate(lr))
        if name == ADAMW_OPTIMIZER:
            return optax.adamw(lr, b1=betas[0], b2=betas[1], eps=eps, weight_decay=wd)
        if name == ADAGRAD_OPTIMIZER:
            return optax.chain(optax.scale_by_rss(initial_accumulator_value=p.get("initial_accumulator_value", 0.0),
                                                  eps=eps),
                               optax.scale_by_learning_rate(lr))
        if name == LAMB_OPTIMIZER:
            return optax.chain(
                optax.scale_by_adam(b1=betas[0], b2=betas[1], eps=eps),
                optax.add_decayed_weights(wd) if wd else optax.identity(),
                optax.scale_by_trust_ratio(min_norm=p.get("min_coeff", 0.01)),
                optax.scale_by_learning_rate(lr),
            )
        if name == SGD_OPTIMIZER:
            return optax.sgd(lr, momentum=p.get("momentum", 0.0), nesterov=p.get("nesterov", False))
        if name == LION_OPTIMIZER:
            return optax.lion(lr, b1=betas[0], b2=betas[1], weight_decay=wd)
        if name in (ONEBIT_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER, ZERO_ONE_ADAM_OPTIMIZER):
            # Error-compensated compressed-communication optimizers (reference
            # fp16/onebit/adam.py:13 via _configure_basic_optimizer
            # engine.py:1197). The train step switches to a shard_map over the
            # data axis where gradients stay per-shard and the optimizer's
            # 1-bit momentum exchange is the only cross-DP wire traffic
            # (_build_onebit_train_fn). Momentum/variance/error-feedback are
            # per-worker full-size, so ZeRO sharding of optimizer state does
            # not apply.
            from ..ops.adam import onebit_adam, onebit_lamb, zero_one_adam
            if self._config.zero_optimization.stage > 0:
                raise ValueError(f"{cfg.type} is incompatible with ZeRO stage "
                                 f"{self._config.zero_optimization.stage}: its momentum/error-"
                                 f"feedback state is per-worker full-size (reference 1-bit Adam "
                                 f"likewise requires stage 0); set zero stage 0")
            if self.offload_optimizer:
                raise ValueError(f"{cfg.type} does not compose with offload_optimizer")
            for ax in (dist.PIPE_AXIS, dist.EXPERT_AXIS, dist.SEQ_AXIS, dist.TENSOR_AXIS):
                if self.mesh.shape[ax] > 1:
                    raise ValueError(f"{cfg.type} supports pure data-parallel meshes only "
                                     f"(mesh axis {ax!r}={self.mesh.shape[ax]})")
            if self._config.gradient_clipping:
                logger.warning(f"{cfg.type}: gradient clipping uses the proxy norm "
                               f"sqrt(mean_dp ||g_shard||^2) — an upper bound on the true "
                               f"averaged-gradient norm (the dense norm would need the dense "
                               f"allreduce the optimizer exists to avoid)")
            self._onebit = name
            common = dict(b1=betas[0], b2=betas[1], eps=eps, weight_decay=wd)
            if name == ONEBIT_ADAM_OPTIMIZER:
                return onebit_adam(lr, dist.DATA_AXIS,
                                   freeze_step=p.get("freeze_step", 100), **common)
            if name == ONEBIT_LAMB_OPTIMIZER:
                return onebit_lamb(lr, dist.DATA_AXIS,
                                   freeze_step=p.get("freeze_step", 100),
                                   min_trust=p.get("min_coeff", 0.01),
                                   max_trust=p.get("max_coeff", 10.0), **common)
            return zero_one_adam(lr, dist.DATA_AXIS,
                                 var_freeze_step=p.get("var_freeze_step", 100),
                                 var_update_scaler=p.get("var_update_scaler", 16), **common)
        raise ValueError(f"Unknown optimizer type {cfg.type}")

    # ------------------------------------------------------------------ step math
    def _micro_loss_and_grads(self, params, batch, rng, scale):
        """One microbatch: cast master->compute, forward, backward, unscale later."""

        def scaled_loss(p):
            p_c = jax.tree_util.tree_map(lambda x: jnp.asarray(x, self.compute_dtype), p)
            # compute-param placement: stage-3 params stay scattered (XLA
            # all-gathers just-in-time per layer); params under
            # stage3_param_persistence_threshold are pinned replicated here
            p_c = jax.lax.with_sharding_constraint(p_c, self.planner.param_shardings(p_c))
            out = self.loss_fn(p_c, batch, rng)
            loss, aux = (out if isinstance(out, tuple) else (out, None))
            return loss.astype(jnp.float32) * scale, (loss, aux)

        grads, (loss, aux) = jax.grad(scaled_loss, has_aux=True)(params)
        return loss, grads

    def _grad_denom(self, scale):
        """Loss-scale x gas (x predivide) unscaling denominator."""
        denom = scale * self._config.gradient_accumulation_steps
        if self._config.prescale_gradients:
            denom = denom * self._config.gradient_predivide_factor
        return denom

    def _clip_coef(self, gnorm):
        """Gradient-clipping coefficient, or None when clipping is off."""
        clip = self._config.gradient_clipping
        if clip and clip > 0:
            return jnp.minimum(1.0, clip / (gnorm + 1e-6))
        return None

    def _apply_grads(self, state, grads, loss_mean):
        """Unscale, clip, update, handle overflow — shared by both paths."""
        scale = state.loss_scale.cur_scale
        denom = self._grad_denom(scale)
        grads = jax.tree_util.tree_map(lambda g: (g / denom).astype(jnp.float32), grads)
        # stage>=2: pin gradients to their scattered sharding
        grads = jax.lax.with_sharding_constraint(
            grads, self.planner.shardings(self.planner.grad_specs(state.params)))

        gnorm = optax.global_norm(grads)
        overflow = ~jnp.isfinite(gnorm)
        coef = self._clip_coef(gnorm)
        if coef is not None:
            grads = jax.tree_util.tree_map(lambda g: g * coef, grads)

        updates, new_opt = self.tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)

        # overflow: skip the update entirely (reference loss-scaler semantics)
        def sel(new, old):
            return jax.tree_util.tree_map(lambda n, o: jnp.where(overflow, o, n), new, old)

        new_params = sel(new_params, state.params)
        new_opt = sel(new_opt, state.opt_state)
        new_scale = self.loss_scaler.update(state.loss_scale, overflow)

        new_state = state._replace(
            step=state.step + jnp.where(overflow, 0, 1),
            params=new_params,
            opt_state=new_opt,
            grad_acc=jax.tree_util.tree_map(jnp.zeros_like, state.grad_acc),
            micro_step=jnp.zeros((), jnp.int32),
            loss_scale=new_scale,
            skipped_steps=state.skipped_steps + overflow.astype(jnp.int32),
        )
        lr = self.lr_schedule_fn(state.step.astype(jnp.float32))
        metrics = {
            "loss": loss_mean,
            "grad_norm": gnorm,
            "lr": lr,
            "overflow": overflow,
            "loss_scale": scale,
        }
        return new_state, metrics

    def _build_pp_train_fn(self):
        """Pipeline-parallel fused step: the whole microbatch stream runs
        through the SPMD pipeline (reference ``PipelineEngine.train_batch``,
        pipe/engine.py:285) inside one pjit; jax.grad through the
        ppermute/scan pipeline is the backward schedule."""
        gas = self._config.gradient_accumulation_steps

        pipe_cfg = dict(self._config.pipeline or {})
        schedule = str(pipe_cfg.pop("schedule", "auto"))
        if pipe_cfg:
            # the reference PipelineModule section has more keys; only
            # 'schedule' is consumed here — silence would be a porting trap
            logger.warning(f"pipeline section keys {sorted(pipe_cfg)} are not consumed "
                           f"(only 'schedule' is); they have NO effect in this build")
        if schedule not in ("auto", "fill_drain", "1f1b"):
            raise ValueError(f"pipeline.schedule must be 'auto', 'fill_drain' or '1f1b', "
                             f"got {schedule!r}")
        if schedule == "auto":
            # 1F1B is the default where it composes (O(stages) activation
            # liveness, reference TrainSchedule); fall back where it can't:
            # fp16 loss scaling, tensor/seq under the auto partitioner
            # inside the pipe-manual region, MoE aux, unscanned layers.
            mc = getattr(self.module, "cfg", None)
            eligible = (hasattr(self.module, "pipeline_value_and_grad")
                        and not self._config.fp16.enabled
                        and self.mesh.shape[dist.TENSOR_AXIS] == 1
                        and self.mesh.shape[dist.SEQ_AXIS] == 1
                        and getattr(mc, "num_experts", 0) == 0
                        and getattr(mc, "scan_layers", False))
            schedule = "1f1b" if eligible else "fill_drain"
            auto_picked = True
            log_dist(f"pipeline.schedule=auto -> {schedule}", [0])
        else:
            auto_picked = False
        if schedule == "1f1b" and self._config.fp16.enabled:
            # the interleaved backward seeds per-microbatch cotangents BEFORE
            # the engine's loss scale is applied; fp16's dynamic scaling
            # cannot protect it (bf16/fp32 need no scaling)
            raise NotImplementedError("pipeline.schedule='1f1b' does not support fp16 "
                                      "loss scaling; use bf16 (TPU-native) or fill_drain")
        if schedule == "1f1b" and not hasattr(self.module, "pipeline_value_and_grad"):
            raise ValueError("pipeline.schedule='1f1b' requires a model exposing "
                             "pipeline_value_and_grad (deepspeed_tpu.models transformers do)")
        if schedule == "1f1b" and (self.mesh.shape[dist.TENSOR_AXIS] > 1
                                   or self.mesh.shape[dist.SEQ_AXIS] > 1):
            # the manual fwd+bwd interleave currently trips XLA's SPMD
            # partitioner when tensor/seq axes stay under the auto
            # partitioner inside the pipe-manual region
            raise NotImplementedError("pipeline.schedule='1f1b' composes with pipe x data "
                                      "meshes; use the default fill-drain schedule with "
                                      "tensor/sequence parallelism")

        def train_step(state, batch):
            rng = jax.random.fold_in(self._base_rng, state.step)

            # auto-picked 1F1B degrades to fill-drain for masked batches
            # (the interleaved schedule doesn't thread attention_mask);
            # batch STRUCTURE is static under jit, so this is a trace-time
            # branch, not data-dependent control flow
            use_1f1b = schedule == "1f1b" and not (
                auto_picked and batch.get("attention_mask") is not None)
            if use_1f1b:
                # interleaved one-pass schedule: fwd+bwd per tick, per-stage
                # activation liveness O(stages) (reference TrainSchedule 1F1B)
                p_c = jax.tree_util.tree_map(lambda x: jnp.asarray(x, self.compute_dtype),
                                             state.params)
                p_c = jax.lax.with_sharding_constraint(p_c, self.planner.param_shardings(p_c))
                loss, grads = self.module.pipeline_value_and_grad(p_c, batch, rng,
                                                                  mesh=self.mesh)
                coef = state.loss_scale.cur_scale * gas
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(jnp.float32) * coef, grads)
                return self._apply_grads(state, grads, loss)

            def scaled_loss(p):
                p_c = jax.tree_util.tree_map(lambda x: jnp.asarray(x, self.compute_dtype), p)
                p_c = jax.lax.with_sharding_constraint(p_c, self.planner.param_shardings(p_c))
                loss = self.module.pipeline_loss(p_c, batch, rng, mesh=self.mesh)
                # x gas: _apply_grads divides by scale*gas (sum convention)
                return loss.astype(jnp.float32) * state.loss_scale.cur_scale * gas, loss

            grads, loss = jax.grad(scaled_loss, has_aux=True)(state.params)
            return self._apply_grads(state, grads, loss)

        return jax.jit(train_step,
                       donate_argnums=(0, ),
                       in_shardings=(self.state_shardings, self._batch_shardings_cache()),
                       out_shardings=(self.state_shardings, NamedSharding(self.mesh, P())))

    def _build_onebit_train_fn(self):
        """1-bit / 0-1 Adam fused step (reference ``fp16/onebit/adam.py:13``
        wired through ``engine.py:1197``): the whole step runs in a
        ``shard_map`` over the data axis. Gradients are computed and kept
        per-DP-shard — the error-compensated compressed-momentum exchange
        inside the optimizer (``runtime/comm/compressed.onebit_all_reduce``)
        is the ONLY cross-DP communication, so past ``freeze_step`` the wire
        carries ~1/32 of a dense allreduce's bytes (sign plane + scale)."""
        gas = self._config.gradient_accumulation_steps
        axis = dist.DATA_AXIS
        dp = self.mesh.shape[axis]
        compute_dtype = self.compute_dtype
        loss_fn = self.loss_fn
        tx = self.tx
        base_rng = self._base_rng

        def shard_fn(params, opt_state, scale, step, batch_shard):
            opt_local = jax.tree_util.tree_map(lambda x: x[0], opt_state)
            rng = jax.random.fold_in(jax.random.fold_in(base_rng, step),
                                     jax.lax.axis_index(axis))

            def scaled_loss(p, mb, r):
                p_c = jax.tree_util.tree_map(lambda x: jnp.asarray(x, compute_dtype), p)
                out = loss_fn(p_c, mb, r)
                loss = out[0] if isinstance(out, tuple) else out
                return loss.astype(jnp.float32) * scale, loss

            def micro(carry, mb):
                acc, loss_sum, i = carry
                grads, loss = jax.grad(scaled_loss, has_aux=True)(params, mb,
                                                                  jax.random.fold_in(rng, i))
                acc = jax.tree_util.tree_map(lambda a, g: a + g.astype(jnp.float32), acc, grads)
                return (acc, loss_sum + loss.astype(jnp.float32), i + 1), None

            zero_acc = jax.tree_util.tree_map(lambda x: jnp.zeros(x.shape, jnp.float32), params)
            (grads, loss_sum, _), _ = jax.lax.scan(
                micro, (zero_acc, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
                batch_shard)

            denom = self._grad_denom(scale)
            grads = jax.tree_util.tree_map(lambda g: g / denom, grads)
            sumsq = sum(jnp.sum(jnp.square(g)) for g in jax.tree_util.tree_leaves(grads))
            mean_sq = jax.lax.psum(sumsq, axis) / dp
            overflow = ~jnp.isfinite(mean_sq)
            # proxy norm (see _configure_optimizer warning): upper bound on
            # the averaged-gradient norm without a dense allreduce
            gnorm = jnp.sqrt(mean_sq)
            coef = self._clip_coef(gnorm)
            if coef is not None:
                grads = jax.tree_util.tree_map(lambda g: g * coef, grads)
            # overflow: feed zeros through the exchange (keeps it finite),
            # then discard every result below
            grads = jax.tree_util.tree_map(
                lambda g: jnp.where(overflow, jnp.zeros_like(g), g), grads)
            updates, new_opt = tx.update(grads, opt_local, params)
            new_params = optax.apply_updates(params, updates)

            def sel(new, old):
                return jax.tree_util.tree_map(lambda n, o: jnp.where(overflow, o, n), new, old)

            new_params = sel(new_params, params)
            new_opt = sel(new_opt, opt_local)
            loss_mean = jax.lax.pmean(loss_sum, axis) / gas
            return (new_params, jax.tree_util.tree_map(lambda x: x[None], new_opt),
                    loss_mean, gnorm, overflow)

        def train_step(state, batch):
            # dim 0 is the gas scan dim; dim 1 (when present) is the batch dim
            # sharded over data; rank-1 leaves (e.g. __pld_theta__) replicate
            batch_specs = jax.tree_util.tree_map(
                lambda x: P(*(([None, axis] + [None] * max(x.ndim - 2, 0))[:x.ndim])), batch)
            opt_specs = jax.tree_util.tree_map(lambda _: P(axis), state.opt_state)
            from ..ops.pallas import shard_map_compat
            new_params, new_opt, loss_mean, gnorm, overflow = shard_map_compat(
                shard_fn, self.mesh,
                (P(), opt_specs, P(), P(), batch_specs),
                (P(), opt_specs, P(), P(), P()))(
                    state.params, state.opt_state, state.loss_scale.cur_scale,
                    state.step, batch)
            new_scale = self.loss_scaler.update(state.loss_scale, overflow)
            new_state = state._replace(
                step=state.step + jnp.where(overflow, 0, 1),
                params=new_params,
                opt_state=new_opt,
                micro_step=jnp.zeros((), jnp.int32),
                loss_scale=new_scale,
                skipped_steps=state.skipped_steps + overflow.astype(jnp.int32),
            )
            metrics = {
                "loss": loss_mean,
                "grad_norm": gnorm,
                "lr": self.lr_schedule_fn(state.step.astype(jnp.float32)),
                "overflow": overflow,
                "loss_scale": state.loss_scale.cur_scale,
            }
            return new_state, metrics

        return jax.jit(train_step,
                       donate_argnums=(0, ),
                       out_shardings=(self.state_shardings, NamedSharding(self.mesh, P())))

    def _build_train_batch_fn(self):
        """Fused step: scan over gas microbatches, then update. ONE pjit."""
        if self.mesh.shape[dist.PIPE_AXIS] > 1:
            return self._build_pp_train_fn()

        def train_step(state, batch):
            rng = jax.random.fold_in(self._base_rng, state.step)

            def micro(carry, mb):
                acc, loss_sum, i = carry
                loss, grads = self._micro_loss_and_grads(state.params, mb, jax.random.fold_in(rng, i),
                                                         state.loss_scale.cur_scale)
                acc = jax.tree_util.tree_map(jnp.add, acc, grads)
                return (acc, loss_sum + loss.astype(jnp.float32), i + 1), None

            zero_acc = jax.tree_util.tree_map(jnp.zeros_like, state.params)
            (grads, loss_sum, _), _ = jax.lax.scan(micro, (zero_acc, jnp.zeros((), jnp.float32),
                                                           jnp.zeros((), jnp.int32)), batch)
            loss_mean = loss_sum / self._config.gradient_accumulation_steps
            return self._apply_grads(state, grads, loss_mean)

        return jax.jit(train_step,
                       donate_argnums=(0, ),
                       in_shardings=(self.state_shardings, self._batch_shardings_cache()),
                       out_shardings=(self.state_shardings, NamedSharding(self.mesh, P())))

    def _batch_shardings_cache(self):
        return None  # resolved per-call from batch structure

    # ZeRO-Offload path ---------------------------------------------------
    def _build_offload_grad_fn(self):
        """Device half of the offloaded step: fwd+bwd over gas microbatches,
        emitting compute-dtype summed grads + the raw grad-norm. The
        unscale/clip/update half runs on the host (reference
        stage_1_and_2.py:1031 CPU accumulation + cpu_adam step)."""

        gas = self._config.gradient_accumulation_steps

        def fp32_norm(tree):
            return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                                for g in jax.tree_util.tree_leaves(tree)))

        def grad_step(state, batch):
            rng = jax.random.fold_in(self._base_rng, state.step)

            if gas == 1:
                # no accumulator at all: grads stay in compute dtype, which is
                # what makes 1.5B-class models fit a single 16 GB chip
                # (an fp32 accumulator alone would add 6 GB at 1.5B params)
                mb = jax.tree_util.tree_map(lambda x: x[0], batch)
                loss, grads = self._micro_loss_and_grads(state.params, mb,
                                                         jax.random.fold_in(rng, 0),
                                                         state.loss_scale.cur_scale)
                return grads, {"loss_sum": loss.astype(jnp.float32), "gnorm_raw": fp32_norm(grads)}

            def micro(carry, mb):
                acc, loss_sum, i = carry
                loss, grads = self._micro_loss_and_grads(state.params, mb, jax.random.fold_in(rng, i),
                                                         state.loss_scale.cur_scale)
                # accumulate in fp32 regardless of compute dtype
                acc = jax.tree_util.tree_map(lambda a, g: a + g.astype(jnp.float32), acc, grads)
                return (acc, loss_sum + loss.astype(jnp.float32), i + 1), None

            zero_acc = jax.tree_util.tree_map(lambda p: jnp.zeros(p.shape, jnp.float32), state.params)
            (acc, loss_sum, _), _ = jax.lax.scan(micro, (zero_acc, jnp.zeros((), jnp.float32),
                                                         jnp.zeros((), jnp.int32)), batch)
            gnorm_raw = optax.global_norm(acc)
            # ship grads at compute precision (half the host-link bytes)
            grads_out = jax.tree_util.tree_map(lambda g: g.astype(self.compute_dtype), acc)
            return grads_out, {"loss_sum": loss_sum, "gnorm_raw": gnorm_raw}

        scalar = NamedSharding(self.mesh, P())
        # grads leave the device reduce-scattered into the offload layout so
        # each host fetches only its partition's shards (reference
        # stage_1_and_2.py:1031; fixes the fetch-the-world gather)
        grad_shardings = self.planner.shardings(self.planner.offload_specs(self.state.params))
        return jax.jit(grad_step,
                       in_shardings=(self.state_shardings, self._batch_shardings_cache()),
                       out_shardings=(grad_shardings,
                                      {"loss_sum": scalar, "gnorm_raw": scalar}))

    def _offload_train_batch(self, stacked):
        """Host half of the offloaded step: fetch grads, fused C AdamW over
        host-resident master/moments, push the bf16 compute params back."""
        cfg = self._config
        gas = cfg.gradient_accumulation_steps
        fn = self._get("offload_grads", self._build_offload_grad_fn)
        if self.telemetry.enabled and self._step_flops is None:
            self._step_flops = self._cost_analysis_flops(fn, self.state, stacked)
        with self.mesh:
            grads, dev_metrics = fn(self.state, stacked)

        gnorm_raw = float(dev_metrics["gnorm_raw"])
        loss_mean = float(dev_metrics["loss_sum"]) / gas
        scale = float(self.state.loss_scale.cur_scale)
        denom = scale * gas
        if cfg.prescale_gradients:
            denom *= cfg.gradient_predivide_factor
        overflow = not np.isfinite(gnorm_raw)
        gnorm = gnorm_raw / denom
        # LR keyed on applied steps (state.step), matching the fused path's
        # schedule position even across overflow-skipped steps
        lr = float(self.lr_schedule_fn(jnp.asarray(int(self.state.step), jnp.float32)))

        if not overflow:
            coef = 1.0 / denom
            clip = cfg.gradient_clipping
            if clip and clip > 0:
                coef *= min(1.0, clip / (gnorm + 1e-6))
            with self.telemetry.span("offload"):
                host_grads = self.host_opt.fetch_grads(grads)
                self.host_opt.step(host_grads, coef, lr)
                new_params = self.host_opt.compute_params(self.compute_dtype,
                                                          self.state_shardings.params)
        else:
            new_params = self.state.params

        new_scale = self.loss_scaler.update(self.state.loss_scale, jnp.asarray(overflow))
        self.state = self.state._replace(
            step=self.state.step + (0 if overflow else 1),
            params=new_params,
            loss_scale=new_scale,
            skipped_steps=self.state.skipped_steps + int(overflow),
        )
        metrics = {"loss": loss_mean, "grad_norm": gnorm, "lr": lr, "overflow": overflow,
                   "loss_scale": scale}
        # loss was computed against pre-update params; report it as the step loss
        return metrics

    # facade pieces -----------------------------------------------------
    def _build_micro_fn(self):

        def micro_step(state, batch):
            rng = jax.random.fold_in(jax.random.fold_in(self._base_rng, state.step), state.micro_step)
            loss, grads = self._micro_loss_and_grads(state.params, batch, rng, state.loss_scale.cur_scale)
            grads = jax.lax.with_sharding_constraint(
                grads, self.planner.shardings(self.planner.grad_specs(state.params)))
            new_state = state._replace(
                grad_acc=jax.tree_util.tree_map(jnp.add, state.grad_acc, grads),
                micro_step=state.micro_step + 1,
            )
            return new_state, loss

        return jax.jit(micro_step, donate_argnums=(0, ),
                       out_shardings=(self.state_shardings, NamedSharding(self.mesh, P())))

    def _build_apply_fn(self):

        def apply_step(state, loss_mean):
            return self._apply_grads(state, state.grad_acc, loss_mean)

        return jax.jit(apply_step, donate_argnums=(0, ),
                       out_shardings=(self.state_shardings, NamedSharding(self.mesh, P())))

    def _build_eval_fn(self):

        def eval_step(state, batch):
            p_c = jax.tree_util.tree_map(lambda x: jnp.asarray(x, self.compute_dtype), state.params)
            if self.mesh.shape[dist.PIPE_AXIS] > 1:
                batch_mb = jax.tree_util.tree_map(lambda x: x[None], batch)
                return self.module.pipeline_loss(p_c, batch_mb, None, mesh=self.mesh)
            out = self.loss_fn(p_c, batch, None)
            loss, aux = (out if isinstance(out, tuple) else (out, None))
            return loss

        return jax.jit(eval_step, out_shardings=NamedSharding(self.mesh, P()))

    def _get(self, name, builder):
        if name not in self._compiled:
            self._compiled[name] = builder()
        return self._compiled[name]

    # ------------------------------------------------------------------ data placement
    def _shard_batch(self, batch, leading_scan_dim=False):
        """Place host arrays onto the mesh: batch dim over the DP axes, the
        sequence dim over ``seq`` when sequence parallelism is on."""
        dp = [a for a in (dist.EXPERT_AXIS, dist.DATA_AXIS) if self.mesh.shape[a] > 1]
        seq_on = self.mesh.shape[dist.SEQ_AXIS] > 1
        batch_dim = 1 if leading_scan_dim else 0
        track = self.telemetry.enabled
        if track:
            self.telemetry.counter(
                "comm/host_to_device/bytes",
                int(sum(np.asarray(x).nbytes for x in jax.tree_util.tree_leaves(batch))))
            t_place = time.perf_counter()

        def place(x):
            x = np.asarray(x)
            entries = [None] * x.ndim
            if x.ndim > batch_dim and dp:
                dp_size = int(np.prod([self.mesh.shape[a] for a in dp]))
                # each process holds 1/process_count of the global batch dim
                global_dim = x.shape[batch_dim] * jax.process_count()
                if global_dim % dp_size != 0:
                    raise ValueError(
                        f"global batch dim {global_dim} (local {x.shape[batch_dim]} x "
                        f"{jax.process_count()} processes) not divisible by the data-parallel "
                        f"degree {dp_size} (mesh axes {dp}); pad or resize the batch — "
                        f"silent replication would drop data parallelism")
                entries[batch_dim] = tuple(dp) if len(dp) > 1 else dp[0]
            if seq_on and x.ndim > batch_dim + 1 and x.shape[batch_dim + 1] % self.mesh.shape[dist.SEQ_AXIS] == 0:
                entries[batch_dim + 1] = dist.SEQ_AXIS
            sharding = NamedSharding(self.mesh, P(*entries))
            if jax.process_count() > 1:
                return jax.make_array_from_process_local_data(sharding, x)
            return jax.device_put(x, sharding)

        placed = jax.tree_util.tree_map(place, batch)
        if track:
            # dispatch/realized split for the batch placement: device_put is
            # asynchronous, so the realized span (fence on the observer pool,
            # busy-interval union — comm/overlap.py) separates DMA completion
            # from the dispatch cost the hot loop actually paid
            dist.get_overlap_tracker().track_async("host_to_device", placed,
                                                   t0=t_place)
        return placed

    def _next_microbatches(self, data_iter, n):
        batches = []
        for _ in range(n):
            batch = next(data_iter)
            if self.collate_fn is not None:
                batch = self.collate_fn(batch)
            batches.append(batch)
        return batches

    # ------------------------------------------------------------------ public API
    def train_batch(self, data_iter=None, batch=None):
        """Run one full training step (gas microbatches + optimizer update)
        as a single compiled program. Returns the mean loss.

        Pass either ``data_iter`` (pulls ``gradient_accumulation_steps``
        microbatches, PipelineEngine-style reference pipe/engine.py:285) or a
        ``batch`` whose leaves carry this process's share of the train batch
        (``train_batch_size / process_count``; with a single controller that
        is the whole batch).
        """
        gas = self.gradient_accumulation_steps()
        if self.param_stream is not None:
            if batch is None:
                it = data_iter if data_iter is not None else iter(self.training_dataloader)
                micro = self._next_microbatches(it, gas)
                batch = jax.tree_util.tree_map(lambda *xs: np.concatenate([np.asarray(x) for x in xs]),
                                               *micro)
            self.tput_timer.start()
            t0 = time.perf_counter() if self.telemetry.enabled else None
            metrics = self.param_stream.train_batch(batch)
            # overflow steps don't advance the runner's (or Adam's) counter;
            # mirror it so checkpoints and the lr schedule stay in sync
            self.global_steps = self.param_stream.global_steps
            self.global_samples += self.train_batch_size()
            self.micro_steps += gas
            self._last_metrics = metrics
            self.tput_timer.stop(global_step=True)
            if t0 is not None:
                dur = time.perf_counter() - t0
                self._last_step_dur = dur
                pt = self.param_stream.last_phase_times or {}
                self.telemetry.record_span(
                    "step", self.telemetry.now() - dur, dur,
                    attrs={"path": "param_stream",
                           "overlap_efficiency": round(pt.get("overlap_efficiency", 0.0), 4)})
                # realized (not dispatched) transfer-overlap evidence: the
                # executor fences every put, so these separate issue time
                # from transfer completion from critical-path exposure
                self.telemetry.gauges([
                    ("offload/put_dispatch_ms", pt.get("put_dispatch_s", 0.0) * 1e3,
                     self.global_samples),
                    ("offload/put_realized_ms", pt.get("put_realized_s", 0.0) * 1e3,
                     self.global_samples),
                    ("offload/fetch_wait_ms", pt.get("drain_s", 0.0) * 1e3,
                     self.global_samples),
                    ("offload/overlap_efficiency", pt.get("overlap_efficiency", 0.0),
                     self.global_samples),
                ])
                self._emit_comm_overlap()
            self._report(metrics)
            if self.lr_scheduler is not None:
                self.lr_scheduler.last_batch_iteration = self.global_steps
            return metrics["loss"]
        if batch is not None:
            # each feeding process supplies its share of the global batch
            # (single-controller: one process feeds everything)
            if self.train_batch_size() % jax.process_count() != 0:
                raise ValueError(f"train_batch_size {self.train_batch_size()} not divisible by "
                                 f"process count {jax.process_count()}")
            expected = self.train_batch_size() // jax.process_count()
            if expected % gas != 0:
                raise ValueError(f"per-process batch share {expected} not divisible by "
                                 f"gradient_accumulation_steps {gas}")
            leading = {np.shape(x)[0] for x in jax.tree_util.tree_leaves(batch)}
            if leading != {expected}:
                raise ValueError(
                    f"train_batch(batch=...) leaves have leading dim {sorted(leading)}; expected "
                    f"this process's share of {expected} samples (train_batch "
                    f"{self.train_batch_size()} = micro {self.train_micro_batch_size_per_gpu()} x "
                    f"gas {gas} x dp {self.dp_world_size()}, over {jax.process_count()} processes)")
            stacked = jax.tree_util.tree_map(
                lambda x: np.asarray(x).reshape((gas, -1) + np.shape(x)[1:]), batch)
        else:
            it = data_iter if data_iter is not None else iter(self.training_dataloader)
            micro = self._next_microbatches(it, gas)
            stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *micro)
        if self.curriculum_scheduler is not None and self.curriculum_type == "seqlen":
            seqlen = self.curriculum_scheduler.update_difficulty(self.global_steps + 1)
            # truncate only the known sequence-bearing keys (reference
            # engine.py:1663 curriculum_seqlen); other leaves pass untouched
            stacked = {k: (v[:, :, :seqlen] if k in ("input_ids", "labels", "attention_mask")
                           and np.ndim(v) >= 3 else v)
                       for k, v in stacked.items()}
        if self.progressive_layer_drop is not None:
            self.progressive_layer_drop.update_state(self.global_steps)
            if getattr(self.module, "supports_pld", False):
                stacked = dict(stacked)
                # one theta per microbatch: every batch leaf must carry the
                # gas leading dim the fused step scans over
                stacked["__pld_theta__"] = np.full((gas, ), self.progressive_layer_drop.get_theta(),
                                                   np.float32)
            else:
                from ..utils.logging import warning_once
                warning_once("progressive_layer_drop enabled but the model does not consume it "
                             "(no supports_pld attribute; deepspeed_tpu.models transformers do) "
                             "— schedule advances with NO effect")
        if self.random_ltd_scheduler is not None:
            keep = int(self.random_ltd_scheduler.update_seq(self.global_steps))
            # clamp to the batch's sequence length: values past it are inert,
            # so advancing within the inert range must not retrace
            ref_leaf = stacked.get("input_ids", jax.tree_util.tree_leaves(stacked)[0])
            keep = min(keep, int(np.shape(ref_leaf)[-1]))
            if keep != self._ltd_current:
                self.module.set_random_ltd(keep, self.random_ltd_scheduler.random_ltd_layer_id)
                for name in ("train_batch", "offload_grads", "micro"):
                    self._compiled.pop(name, None)  # new static keep -> retrace
                self._ltd_current = keep
        stacked = self._shard_batch(stacked, leading_scan_dim=True)

        self.tput_timer.start()
        # compression scheduler (reference engine.py:1268): advance the step
        # and re-trace the compiled step when the compression graph changes
        # (a transform activates, MoQ drops a bit, act-quant switches on)
        if hasattr(self.module, "transforms") and hasattr(self.module, "_active"):
            self._maybe_update_eigenvalue(stacked)
            sig = getattr(self.module, "compression_signature", None)
            before = sig() if sig else len(self.module._active())
            self.module.global_step = self.global_steps
            after = sig() if sig else len(self.module._active())
            if after != before:
                self._compiled.clear()
        t0 = time.perf_counter() if self.telemetry.enabled else None
        if self.offload_optimizer:
            metrics = self._offload_train_batch(stacked)
        else:
            fn = self._get("train_batch", self._build_onebit_train_fn if self._onebit
                           else self._build_train_batch_fn)
            if self.telemetry.enabled and self._step_flops is None:
                self._step_flops = self._cost_analysis_flops(fn, self.state, stacked)
            with self.mesh:
                self.state, metrics = fn(self.state, stacked)
        if t0 is not None:
            _device_sync()
            dur = time.perf_counter() - t0
            self._last_step_dur = dur
            self.telemetry.record_span(
                "step", self.telemetry.now() - dur, dur,
                attrs={"path": "offload" if self.offload_optimizer else "fused",
                       "micro_batches": gas})
            self._emit_step_counters()
        self.global_steps += 1
        self.global_samples += self.train_batch_size()
        self.micro_steps += gas
        self._last_metrics = metrics
        self.tput_timer.stop(global_step=True)
        self._maybe_profile_flops(stacked)
        self._report(metrics)
        if self.lr_scheduler is not None:
            self.lr_scheduler.last_batch_iteration = self.global_steps
        return metrics["loss"]

    def forward(self, batch):
        """Facade: compute microbatch loss + gradients, buffer them.
        (Forward/backward fuse under XLA; splitting them would double
        compute, so `forward` does both and `backward` is the accumulation
        boundary bookkeeping — semantics match the reference 3-call API.)"""
        if self.mesh.shape[dist.PIPE_AXIS] > 1:
            raise RuntimeError(
                "the forward/backward/step facade is not supported under pipeline parallelism; "
                "use train_batch() (the reference PipelineEngine likewise only supports "
                "train_batch, pipe/engine.py:285)")
        if self.offload_optimizer or self.param_stream is not None:
            raise RuntimeError("the forward/backward/step facade is not supported with "
                               "offload_optimizer/offload_param; use train_batch()")
        if self._onebit:
            raise RuntimeError("the forward/backward/step facade is not supported with 1-bit "
                               "optimizers (the compressed exchange lives inside the fused "
                               "shard_map step); use train_batch()")
        tel = self.telemetry
        if self._trace_spans:
            self.timers(FORWARD_GLOBAL_TIMER).start()
        self._ensure_grad_acc()
        batch = self._shard_batch(batch)
        fn = self._get("micro", self._build_micro_fn)
        if tel.enabled:
            if self._fwd_since_step == 0:
                self._facade_t0 = time.perf_counter()
            self._fwd_since_step += 1
            if self._step_flops is None:
                # one micro-step's cost × gas ≈ the full step (the apply
                # half is negligible next to fwd+bwd)
                self._step_flops = (self._cost_analysis_flops(fn, self.state, batch)
                                    * self.gradient_accumulation_steps())
        with self.mesh:
            self.state, loss = fn(self.state, batch)
        if self._trace_spans:
            t = self.timers(FORWARD_GLOBAL_TIMER)
            # NOT synchronized: a fence here would serialize host and device
            # every micro-step (the facade's whole point is async dispatch);
            # on async backends this span measures dispatch + compile, and
            # the fenced step() span carries the true device time
            t.stop()
            if tel.enabled:
                dur = t.last()
                tel.record_span("fwd", tel.now() - dur, dur)
        # keep the device array: no host sync per micro-step
        self._pending_batches.append(loss)
        return loss

    def backward(self, loss=None, allreduce_gradients=True, retain_graph=False):
        """Facade: gradients were produced in forward(); this marks the
        micro-step boundary (reference engine.py:1765)."""
        if self._trace_spans:
            t = self.timers(BACKWARD_GLOBAL_TIMER)
            t.start()
            t.stop()
            if self.telemetry.enabled:
                # gradients were already produced inside forward() (fwd+bwd
                # fuse under XLA); the span marks the micro-step boundary
                dur = t.last()
                self.telemetry.record_span("bwd", self.telemetry.now() - dur, dur,
                                           attrs={"fused_into": "fwd"})
        self.micro_steps += 1
        return loss

    def is_gradient_accumulation_boundary(self):
        return int(self.state.micro_step) % self.gradient_accumulation_steps() == 0

    def step(self, lr_kwargs=None):
        """Facade: apply the buffered gradients if at a boundary (reference
        engine.py:1961)."""
        if int(self.state.micro_step) < self.gradient_accumulation_steps():
            return  # not at boundary yet
        if self._trace_spans:
            self.timers(STEP_GLOBAL_TIMER).start()
        pending = self._pending_batches[-self.gradient_accumulation_steps():]
        loss_mean = (jnp.mean(jnp.stack([jnp.asarray(p, jnp.float32) for p in pending]))
                     if pending else jnp.zeros((), jnp.float32))
        fn = self._get("apply", self._build_apply_fn)
        with self.mesh:
            self.state, metrics = fn(self.state, loss_mean)
        self.global_steps += 1
        self.global_samples += self.train_batch_size()
        self._pending_batches = []
        self._last_metrics = metrics
        if self._trace_spans:
            t = self.timers(STEP_GLOBAL_TIMER)
            t.stop(synchronize=self.telemetry.enabled)
            if self.telemetry.enabled:
                dur = t.last()
                self.telemetry.record_span("step", self.telemetry.now() - dur, dur,
                                           attrs={"path": "facade"})
        if self.telemetry.enabled:
            if self._facade_t0 is not None:
                # fwd..step wall time of the whole accumulation window — the
                # denominator the MFU gauge uses on the facade path
                self._last_step_dur = time.perf_counter() - self._facade_t0
            self._facade_t0 = None
            self._fwd_since_step = 0
            self._emit_step_counters()
        self._report(metrics)
        if self.lr_scheduler is not None:
            self.lr_scheduler.last_batch_iteration = self.global_steps
        return metrics

    def eval_batch(self, batch):
        if self.param_stream is not None:
            return jnp.asarray(self.param_stream.eval_batch(batch)["loss"])
        batch = self._shard_batch(batch)
        fn = self._get("eval", self._build_eval_fn)
        with self.mesh:
            return fn(self.state, batch)

    def __call__(self, batch):
        return self.eval_batch(batch)

    def allreduce_gradients(self, bucket_size=None):
        """No-op: gradient reduction is inside the compiled step (XLA
        collectives inserted by the partitioner). Kept for API parity."""

    def zero_grad(self):
        zero_fn = self._get(
            "zero_grad",
            lambda: jax.jit(lambda s: s._replace(grad_acc=jax.tree_util.tree_map(jnp.zeros_like, s.grad_acc),
                                                 micro_step=jnp.zeros((), jnp.int32)),
                            donate_argnums=(0, ), out_shardings=self.state_shardings))
        with self.mesh:
            self.state = zero_fn(self.state)

    # ------------------------------------------------------------------ reporting
    def _maybe_update_eigenvalue(self, stacked):
        """MoQ curvature schedule (reference engine.py:1268 eigenvalue hook):
        at ``gas_boundary_resolution`` intervals, power-iterate the loss
        Hessian and scale the compressed model's quantize periods by
        ``1 + floor(ev_norm * 4)`` — high-curvature phases quantize slower.
        Simplification vs the per-layer reference factors, documented: one
        global factor from the max-normalized mean of the subtree values."""
        ev_cfg = dict(self._config.raw_config.get("eigenvalue", {}))
        if not ev_cfg.get("enabled") or not hasattr(self.module, "eigenvalue_factor"):
            return
        if self._eigenvalue is None:
            from .eigenvalue import Eigenvalue
            keys = ("verbose", "max_iter", "tol", "stability", "gas_boundary_resolution",
                    "layer_name", "layer_num")
            self._eigenvalue = Eigenvalue(**{k: ev_cfg[k] for k in keys if k in ev_cfg})
        res = max(1, int(self._eigenvalue.gas_boundary_resolution))
        if self.global_steps == 0 or self.global_steps % res != 0:
            return
        import math
        mb = jax.tree_util.tree_map(lambda x: x[0], stacked)
        try:
            evs = self._eigenvalue.compute_eigenvalue(self.module.loss, self.state.params, mb)
        except Exception as e:
            logger.warning(f"eigenvalue: computation failed ({e}); keeping factor "
                           f"{self.module.eigenvalue_factor}")
            return
        vals = np.asarray([abs(v) for v in evs.values()], np.float64)
        if vals.size and vals.max() > 0:
            ev_norm = float(np.mean(vals / vals.max()))
            self.module.eigenvalue_factor = 1 + math.floor(ev_norm * 4)
            log_dist(f"eigenvalue: factor={self.module.eigenvalue_factor} "
                     f"(normalized mean {ev_norm:.3f})", [0])

    def _maybe_profile_flops(self, stacked):
        """flops_profiler section: at profile_step, read XLA's cost analysis
        of the compiled train step and log achieved vs peak (reference
        engine.py:1636 flops_profiler integration; here the counts come from
        the compiler, not module hooks)."""
        fp = self._config.flops_profiler
        if not fp.enabled or self.global_steps != fp.profile_step:
            return
        from ..profiling.flops_profiler.profiler import profile_compiled, number_to_string
        name = "offload_grads" if self.offload_optimizer else "train_batch"
        fn = self._compiled.get(name)
        if fn is None:
            return
        try:
            stats = profile_compiled(fn, self.state, stacked)
        except Exception as e:
            logger.warning(f"flops_profiler: cost analysis unavailable ({e})")
            return
        self.flops_profile = stats
        peak = get_accelerator().peak_flops()
        msg = (f"flops profile @ step {self.global_steps}: "
               f"{number_to_string(stats['flops'], 'FLOPs')}/step, "
               f"{number_to_string(stats.get('bytes_accessed', 0), 'B')} accessed")
        if peak:
            msg += f", peak {number_to_string(peak, 'FLOP/s')}"
        log_dist(msg, [0])
        if fp.output_file:
            import json as _json
            with open(fp.output_file, "w") as f:
                _json.dump(stats, f, indent=2)

    def _cost_analysis_flops(self, fn, *args):
        """XLA cost-analysis FLOPs of one compiled step, read from the
        lowering (trace-only; see ``profiling/flops_profiler``). 0.0 when
        unavailable — the MFU gauge is then simply not emitted."""
        try:
            from ..profiling.flops_profiler.profiler import profile_compiled
            with self.mesh:
                return float(profile_compiled(fn, *args).get("flops", 0.0))
        except Exception as e:
            logger.warning(f"telemetry: step cost analysis unavailable ({e})")
            return 0.0

    def _emit_step_counters(self):
        """Per-step analytic comms accounting. XLA inserts the gradient
        collectives inside the compiled step (no host-observable per-op
        hook, by design — see comm/comm.py), so DP gradient-sync traffic is
        accounted from the sharding plan: ring all-reduce moves
        2(n-1)/n × fp32 grad bytes per step."""
        tel = self.telemetry
        if not tel.enabled:
            return
        if self._grad_sync_bytes_cached is None:
            n = self.dp_world_size()
            param_bytes = 4 * sum(int(np.prod(x.shape))
                                  for x in jax.tree_util.tree_leaves(self.state.params))
            self._grad_sync_bytes_cached = (int(param_bytes * 2 * (n - 1) / n)
                                            if n > 1 else 0)
        if self._grad_sync_bytes_cached:
            tel.counter("comm/grad_sync/bytes", self._grad_sync_bytes_cached,
                        attrs={"estimate": "ring_all_reduce", "dp": self.dp_world_size()})
        self._emit_comm_overlap()

    def _emit_comm_overlap(self):
        """Drain this step's comm realized/overlap accounting
        (``comm/overlap.py`` — host->device batch placement, control-plane
        collectives) into gauges: ``comm/{op}/realized_ms``,
        ``comm/{op}/dispatch_ms``, ``comm/overlap_efficiency``. Same
        realized-vs-exposed definition as ``offload/overlap_efficiency``
        (PR 5), so the two read on one scale."""
        tel = self.telemetry
        if not tel.enabled:
            return
        stats = dist.get_overlap_tracker().collect(reset=True)
        if not stats["ops"]:
            return
        gauges = []
        for op, s in sorted(stats["ops"].items()):
            gauges.append((f"comm/{op}/realized_ms", s["realized_s"] * 1e3,
                           self.global_samples))
            gauges.append((f"comm/{op}/dispatch_ms", s["dispatch_s"] * 1e3,
                           self.global_samples))
        gauges.append(("comm/overlap_efficiency", stats["overlap_efficiency"],
                       self.global_samples))
        tel.gauges(gauges)

    def _interval_gauges(self):
        """MFU + device/host memory watermark gauges for one logging
        interval, as (name, value, step) tuples. Step axis is
        ``global_samples`` — the same axis the Train/Samples scalars use, so
        monitor backends see one monotonic step stream."""
        out = []
        if self._step_flops and self._last_step_dur:
            peak = get_accelerator().peak_flops()
            if peak:
                mfu = self._step_flops / self._last_step_dur / (peak * jax.device_count())
                out.append(("mfu", mfu, self.global_samples))
        try:
            stats = get_accelerator().memory_stats() or {}
        except Exception:
            stats = {}
        if "bytes_in_use" in stats:
            out.append(("memory/device_bytes_in_use", stats["bytes_in_use"], self.global_samples))
        if "peak_bytes_in_use" in stats:
            out.append(("memory/device_peak_bytes", stats["peak_bytes_in_use"], self.global_samples))
        try:
            import psutil
            out.append(("memory/host_rss_bytes", psutil.Process().memory_info().rss,
                        self.global_samples))
        except Exception:
            pass
        return out

    def _report(self, metrics):
        if self.global_steps % self.steps_per_print() == 0:
            # single host sync per print interval
            loss = float(metrics["loss"])
            lr = float(metrics["lr"])
            scale = float(metrics["loss_scale"])
            norm = float(metrics["grad_norm"])
            msg = (f"step={self.global_steps} loss={loss:.4f} lr={lr:.3e} grad_norm={norm:.3f}")
            if self.fp16_enabled():
                msg += f" loss_scale={scale:g}"
            log_dist(msg, [0])
            # single reporting call site: ONE batched sink call per interval
            # fans these out to the tb/wandb/csv monitor backends (one
            # write_events/flush) and, when telemetry is enabled, into the
            # JSONL/trace as gauges
            tel = self.telemetry
            scalars = [("Train/Samples/train_loss", loss, self.global_samples),
                       ("Train/Samples/lr", lr, self.global_samples)]
            if self.fp16_enabled():
                scalars.append(("Train/Samples/loss_scale", scale, self.global_samples))
            if tel.enabled:
                scalars.append(("Train/Samples/grad_norm", norm, self.global_samples))
                scalars.extend(self._interval_gauges())
            tel.gauges(scalars)
            if self._slo is not None:
                self._slo.maybe_evaluate()
            if self.profiler is not None:
                # report-boundary capture point: starts a pending
                # request_profile() and reaps an overdue capture
                started = self.profiler.maybe_capture(tag="report")
                if started is not None:
                    log_dist(f"xla profile capture started: {started}", [0])

    def request_profile(self, duration_s=1.0):
        """Arm a duration-bounded XLA device-trace capture that begins at
        the next report interval (``steps_per_print`` boundary) — traces
        land under the telemetry output path, one ``xla_trace_*`` directory
        per capture. Raises when telemetry is disabled; raises
        :class:`~deepspeed_tpu.telemetry.profiler.ProfileBusy` when a
        capture is already in flight or pending."""
        if self.profiler is None:
            raise RuntimeError("request_profile requires telemetry.enabled "
                               "(the trace needs an output path)")
        self.profiler.request(duration_s)

    # ------------------------------------------------------------------ data
    def deepspeed_io(self, dataset, batch_size=None, route=None, data_sampler=None, collate_fn=None, num_local_io_workers=None):
        from .dataloader import DeepSpeedDataLoader
        # one JAX process feeds every device it controls (single-controller
        # model), so the loader yields the process-local share of the global
        # microbatch — micro_bs × dp ÷ processes — not the per-device size,
        # and each process reads a disjoint interleaved shard of the dataset
        if batch_size is None:
            global_micro = self.train_micro_batch_size_per_gpu() * self.dp_world_size()
            if global_micro % jax.process_count() != 0:
                raise ValueError(
                    f"global microbatch {global_micro} not divisible by process count "
                    f"{jax.process_count()}; adjust train_micro_batch_size_per_gpu")
            batch_size = global_micro // jax.process_count()
        if (data_sampler is None and self._data_sampling_cfg.get("enabled")
                and route in (None, "train") and self._data_sampler is not None):
            # a later train loader (e.g. per-epoch rebuild) REUSES the live
            # sampler: its curriculum position and checkpoint state carry over
            data_sampler = self._data_sampler
        elif (data_sampler is None and self._data_sampling_cfg.get("enabled")
                and route in (None, "train") and self._data_sampler is None
                and hasattr(dataset, "__len__")):
            # train route only (reference wires ROUTE_TRAIN only): eval
            # loaders must see one ordered pass, and the training sampler's
            # checkpoint state must not be clobbered by later loaders
            # curriculum-clustered sampling wired into the loader (reference
            # builds DeepSpeedDataSampler inside deepspeed_io,
            # data_pipeline/data_sampler.py:36). One feeding process = one
            # "rank" of the sampler; it yields that process's micro-batch
            # index lists.
            from .data_pipeline.data_sampler import DeepSpeedDataSampler
            data_sampler = DeepSpeedDataSampler(
                {"data_sampling": self._data_sampling_cfg,
                 "seed": self._data_sampling_cfg.get("seed", self._seed)},
                one_epoch_total_samples=len(dataset),
                micro_batch_size=batch_size,
                data_parallel_rank=jax.process_index(),
                data_parallel_size=jax.process_count(),
                gradient_accumulation_steps=self.gradient_accumulation_steps(),
                drop_last=self._config.dataloader_drop_last)
            self._data_sampler = data_sampler
            if self._pending_sampler_state is not None:
                # checkpoint loaded before the sampler existed: apply now
                data_sampler.load_state_dict(self._pending_sampler_state)
                self._pending_sampler_state = None
                log_dist("deepspeed_io: restored data-sampler state from the loaded "
                         "checkpoint", [0])
            log_dist(f"deepspeed_io: DeepSpeedDataSampler wired "
                     f"(curriculum={'on' if data_sampler.curriculum_enabled else 'off'}, "
                     f"{len(dataset)} samples/epoch)", [0])
        return DeepSpeedDataLoader(dataset,
                                   batch_size=batch_size,
                                   collate_fn=collate_fn or self.collate_fn,
                                   drop_last=self._config.dataloader_drop_last,
                                   seed=self._seed,
                                   data_sampler=data_sampler,
                                   num_shards=jax.process_count(),
                                   shard_index=jax.process_index())

    # ------------------------------------------------------------------ checkpoint
    def save_checkpoint(self, save_dir, tag=None, client_state=None, save_latest=True, exclude_frozen_parameters=False):
        """Sharded, layout-independent checkpoint (reference engine.py:2802;
        the universal-checkpoint property — resumable onto a different mesh —
        comes free because arrays are saved as global logical tensors).

        **Shared-filesystem requirement (param offload)**: on the
        param-offload path only RANK 0 writes the store/client/latest files
        (the host-resident state is replicated, and per-rank writes would
        race on the same paths), so ``save_dir`` MUST be on a filesystem
        visible to every process (NFS/GCS-fuse/Lustre). With per-host local
        dirs, non-zero hosts end up with an empty ``save_dir`` and a later
        ``load_checkpoint`` there returns ``(None, None)``. The non-offload
        path has no such requirement: every host writes (and reads back) its
        own shard files."""
        from .checkpoint_engine.engine import save_checkpoint as _save
        tag = tag or f"global_step{self.global_steps}"
        client_sd = dict(client_state or {})
        client_sd.update({
            "global_steps": self.global_steps,
            "global_samples": self.global_samples,
            "micro_steps": self.micro_steps,
            "skipped_steps": int(self.state.skipped_steps),
            "lr_scheduler": self.lr_scheduler.state_dict() if self.lr_scheduler is not None else None,
            "data_sampler": (self._data_sampler.state_dict()
                             if self._data_sampler is not None else None),
            "ds_config": self._config.raw_config,
            # elastic resume: the restore side compares this against its own
            # world to detect (and validate) a resize across the checkpoint
            "world_size": self._config.world_size,
        })
        if self.param_stream is not None:
            # param offload: every block (master + moments) is host-resident
            # and replicated across processes, so only rank 0 writes the
            # store/client files into a shared checkpoint dir (a per-rank
            # write would race on the same npz/meta/json paths)
            tag_dir = os.path.join(save_dir, str(tag))
            if jax.process_index() == 0:
                self.param_stream.save_checkpoint(tag_dir)
                with open(os.path.join(tag_dir, "client_state.json"), "w") as f:
                    import json as _json
                    _json.dump({k: v for k, v in client_sd.items()
                                if isinstance(v, (int, float, str, bool, dict, list, type(None)))}, f)
                if save_latest:
                    with open(os.path.join(save_dir, "latest"), "w") as f:
                        f.write(str(tag))
            # non-zero ranks must not report success (or start a dependent
            # load/eviction) while rank 0 is still writing
            dist.barrier()
            log_dist(f"saved param-offload checkpoint {save_dir}/{tag}", [0])
            return True
        # grad_acc is in-flight facade scratch, not training state — always
        # checkpoint the canonical (empty) structure so resume works from
        # either API path (the reference likewise never checkpoints IPG
        # buffers, engine.py:3012)
        _save(save_dir, tag, self.state._replace(grad_acc={}), client_sd, save_latest=save_latest,
              use_async=self._config.checkpoint.async_save)
        if self.offload_optimizer:
            # every host saves ITS partition of the offloaded master/moments
            # (streamed block npz, shared by both tiers); the loader
            # reassembles across rank files, so resume survives mesh resize
            self.host_opt.save_to(os.path.join(save_dir, str(tag)))
        log_dist(f"saved checkpoint {save_dir}/{tag}", [0])
        return True

    def wait_checkpoint_saves(self):
        """Block until any in-flight async checkpoint (checkpoint.async_save)
        is committed and its 'latest' pointer written."""
        from .checkpoint_engine.engine import wait_pending_saves
        wait_pending_saves()

    def load_checkpoint(self, load_dir, tag=None, load_module_strict=True, load_optimizer_states=True,
                        load_lr_scheduler_states=True, load_module_only=False, custom_load_fn=None):
        """Load a checkpoint saved by :meth:`save_checkpoint`. Param-offload
        checkpoints are written by rank 0 only, so ``load_dir`` must be the
        SHARED directory every process can see (see the save-side
        docstring); a host-local dir on non-zero ranks silently has no
        checkpoint and returns ``(None, None)``."""
        from .checkpoint_engine.engine import load_checkpoint as _load
        if self.param_stream is not None:
            from .checkpoint_engine.engine import get_latest_tag
            tag_used = tag or get_latest_tag(load_dir)
            if tag_used is None:
                return None, None
            tag_dir = os.path.join(os.path.abspath(load_dir), str(tag_used))
            load_opt = load_optimizer_states and not load_module_only
            if not self.param_stream.load_checkpoint(tag_dir, load_optimizer_states=load_opt):
                return None, None
            client_sd = {}
            cs = os.path.join(tag_dir, "client_state.json")
            if os.path.isfile(cs):
                import json as _json
                with open(cs) as f:
                    client_sd = _json.load(f)
            if load_module_only:
                self.loaded_checkpoint_tag = tag_used
                return load_dir, client_sd
            self.global_steps = client_sd.get("global_steps", self.param_stream.global_steps)
            self.param_stream.global_steps = self.global_steps
            self.global_samples = client_sd.get("global_samples", 0)
            self.micro_steps = client_sd.get("micro_steps", 0)
            if load_lr_scheduler_states and self.lr_scheduler is not None and client_sd.get("lr_scheduler"):
                self.lr_scheduler.load_state_dict(client_sd["lr_scheduler"])
            self._elastic_on_restore(client_sd)
            self.loaded_checkpoint_tag = tag_used
            return load_dir, client_sd
        state, client_sd = _load(load_dir, tag, self.state_shardings._replace(grad_acc={}), self.mesh,
                                 template=self.state._replace(grad_acc={}),
                                 load_optimizer_states=load_optimizer_states,
                                 load_module_only=load_module_only)
        if state is None:
            return None, None
        self._drop_grad_acc()
        self.state = state
        if self.offload_optimizer:
            tag_used = tag or client_sd.get("__tag__") or None
            from .checkpoint_engine.engine import get_latest_tag
            tag_dir = os.path.join(os.path.abspath(load_dir),
                                   str(tag_used or get_latest_tag(load_dir)))
            if not (load_optimizer_states and self.host_opt.load_from(tag_dir)):
                logger.warning("offload_optimizer: checkpoint carries no offloaded optimizer "
                               "state (saved without offload?); rebuilding fp32 master from "
                               "loaded params with fresh moments")
                self.host_opt.reset_from_params(self.state.params,
                                                client_sd.get("global_steps", 0))
            # device params re-derive from master so both views agree exactly
            self.state = self.state._replace(params=self.host_opt.compute_params(
                self.compute_dtype, self.state_shardings.params))
        self.global_steps = client_sd.get("global_steps", int(self.state.step))
        self.global_samples = client_sd.get("global_samples", 0)
        self.micro_steps = client_sd.get("micro_steps", 0)
        if load_lr_scheduler_states and self.lr_scheduler is not None and client_sd.get("lr_scheduler"):
            self.lr_scheduler.load_state_dict(client_sd["lr_scheduler"])
        if client_sd.get("data_sampler"):
            if self._data_sampler is not None:
                self._data_sampler.load_state_dict(client_sd["data_sampler"])
            else:
                # loader not built yet (load-then-deepspeed_io order): stash
                # and apply when the sampler is created
                self._pending_sampler_state = client_sd["data_sampler"]
        self._elastic_on_restore(client_sd)
        self.loaded_checkpoint_tag = tag
        return load_dir, client_sd

    def _elastic_on_restore(self, client_sd):
        """Elastic resume validation: with the ``elasticity`` section
        enabled and a checkpoint stamped at a DIFFERENT world size, the
        :class:`~deepspeed_tpu.elasticity.ElasticityManager` re-solves the
        batch tiling for this world and asserts the effective train batch
        did not move across the resize (incompatibility raises — resuming
        with a bent loss curve is worse than failing loudly)."""
        from ..elasticity import ElasticityManager, elasticity_enabled
        if not elasticity_enabled(self._config.raw_config):
            return
        ElasticityManager(self._config.raw_config).on_restore(
            self._config.world_size, client_sd, telemetry=self.telemetry)

    def save_16bit_model(self, save_dir, save_filename="pytree_model.msgpack", exclude_frozen_parameters=False):
        """Consolidated compute-dtype export (reference engine.py:3223
        ``save_16bit_model`` / ``_zero3_consolidated_16bit_state_dict``)."""
        import flax.serialization
        os.makedirs(save_dir, exist_ok=True)
        # stream one leaf at a time: gather → host fetch → free, so peak HBM
        # overhead is one tensor, not the whole model replicated per device
        # (the reference's stage-3 consolidation likewise walks params in
        # groups, engine.py:3156)
        replicated = NamedSharding(self.mesh, P())
        cast_one = jax.jit(lambda x: jnp.asarray(x, self.compute_dtype), out_shardings=replicated)
        leaves, treedef = jax.tree_util.tree_flatten(self.state.params)
        host_leaves = []
        with self.mesh:
            for leaf in leaves:
                host_leaves.append(jax.device_get(cast_one(leaf)))
        full = jax.tree_util.tree_unflatten(treedef, host_leaves)
        path = os.path.join(save_dir, save_filename)
        if jax.process_index() == 0:
            with open(path, "wb") as f:
                f.write(flax.serialization.to_bytes(full))
        return path
