"""Loss scaling.

Analogue of reference ``deepspeed/runtime/fp16/loss_scaler.py``
(``LossScaler``/``DynamicLossScaler``). Functional: scaler state is a small
pytree carried inside the compiled train step so scale adjustment and
overflow-skip happen on-device with no host sync.

TPU note: bf16 is the native dtype and needs no loss scaling; this exists for
fp16 parity mode.
"""

from typing import NamedTuple

import jax.numpy as jnp

INITIAL_LOSS_SCALE = "init_scale"
SCALE_WINDOW = "scale_window"
DELAYED_SHIFT = "delayed_shift"
CONSECUTIVE_HYSTERESIS = "consecutive_hysteresis"
MIN_LOSS_SCALE = "min_scale"


class LossScaleState(NamedTuple):
    cur_scale: jnp.ndarray  # f32 scalar
    cur_hysteresis: jnp.ndarray  # i32 scalar
    last_overflow_iter: jnp.ndarray  # i32 scalar
    iteration: jnp.ndarray  # i32 scalar


class LossScalerBase:
    """Static loss scaler (reference ``LossScaler``)."""

    dynamic = False

    def __init__(self, scale=1.0):
        self.loss_scale = float(scale)

    def init_state(self):
        return LossScaleState(
            cur_scale=jnp.asarray(self.loss_scale, jnp.float32),
            cur_hysteresis=jnp.asarray(0, jnp.int32),
            last_overflow_iter=jnp.asarray(-1, jnp.int32),
            iteration=jnp.asarray(0, jnp.int32),
        )

    def update(self, state, has_overflow):
        return state._replace(iteration=state.iteration + 1)

    def backward(self, loss):
        return loss * self.loss_scale


LossScaler = LossScalerBase


class DynamicLossScaler(LossScalerBase):
    """Dynamic scaler (reference ``DynamicLossScaler``): halve on overflow
    (with hysteresis), double after ``scale_window`` clean steps."""

    dynamic = True

    def __init__(self,
                 init_scale=2**32,
                 scale_factor=2.0,
                 scale_window=1000,
                 min_scale=1.0,
                 delayed_shift=1,
                 consecutive_hysteresis=False,
                 raise_error_at_min_scale=False,
                 dtype=jnp.float16):
        super().__init__(init_scale)
        self.scale_factor = float(scale_factor)
        self.scale_window = int(scale_window)
        self.min_scale = float(min_scale)
        self.delayed_shift = int(delayed_shift)
        self.consecutive_hysteresis = consecutive_hysteresis

    def init_state(self):
        return LossScaleState(
            cur_scale=jnp.asarray(self.loss_scale, jnp.float32),
            cur_hysteresis=jnp.asarray(self.delayed_shift, jnp.int32),
            last_overflow_iter=jnp.asarray(-1, jnp.int32),
            iteration=jnp.asarray(0, jnp.int32),
        )

    def update(self, state, has_overflow):
        """Pure update; ``has_overflow`` is a traced bool scalar."""
        it = state.iteration

        # overflow branch
        depleted = state.cur_hysteresis <= 1
        ovf_scale = jnp.where(depleted,
                              jnp.maximum(state.cur_scale / self.scale_factor, self.min_scale),
                              state.cur_scale)
        ovf_hyst = jnp.where(depleted, state.cur_hysteresis, state.cur_hysteresis - 1)

        # clean branch (reference loss_scaler.py:195: consecutive_hysteresis
        # re-arms every clean step; otherwise re-arm on each full clean window).
        # With last_overflow_iter=-1 and window W the first doubling lands on
        # iteration W-1, i.e. after exactly W clean updates — matching the
        # reference's (cur_iter - last_overflow_iter) % window == 0 check.
        window_full = (it - state.last_overflow_iter) % self.scale_window == 0
        ok_scale = jnp.where(window_full, state.cur_scale * self.scale_factor, state.cur_scale)
        rearm = jnp.logical_or(jnp.asarray(self.consecutive_hysteresis), window_full)
        ok_hyst = jnp.where(rearm, jnp.asarray(self.delayed_shift, jnp.int32), state.cur_hysteresis)

        return LossScaleState(
            cur_scale=jnp.where(has_overflow, ovf_scale, ok_scale),
            cur_hysteresis=jnp.where(has_overflow, ovf_hyst, ok_hyst),
            last_overflow_iter=jnp.where(has_overflow, it, state.last_overflow_iter),
            iteration=it + 1,
        )


def create_loss_scaler(fp16_config=None, dtype=jnp.float16):
    """Build scaler from the ``fp16`` config section (reference
    ``CreateLossScaler``)."""
    if fp16_config is None or not fp16_config.enabled:
        return LossScalerBase(1.0)
    if fp16_config.loss_scale and fp16_config.loss_scale > 0:
        return LossScalerBase(fp16_config.loss_scale)
    return DynamicLossScaler(
        init_scale=2**fp16_config.initial_scale_power,
        scale_window=fp16_config.loss_scale_window,
        min_scale=fp16_config.min_loss_scale,
        delayed_shift=fp16_config.hysteresis,
    )
