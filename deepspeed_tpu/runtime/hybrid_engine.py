"""Hybrid engine (RLHF).

TPU-native analogue of reference ``runtime/hybrid_engine.py:32``
(``DeepSpeedHybridEngine``): ONE engine that both trains (ZeRO) and serves
``generate()`` for the RLHF actor — the DeepSpeed-Chat pattern where rollout
generation alternates with PPO updates every step.

Design translation: the reference flips between ZeRO-3 training modules and
kernel-injected inference containers that share weight storage
(``create_inference_module`` :298, ``_zero3_forward`` :333). Here both modes
are pure functions over the same logical parameter pytree, so "sharing"
is the identity: ``generate()`` casts the fp32 master params to the compute
dtype inside jit (out-shardings = the inference layout) and runs the
KV-cache generation program; XLA inserts whatever resharding collectives the
ZeRO/TP layouts require — the reference's gather/scatter bookkeeping
(``fuse_lora_weight`` :129, container weight aliasing) has no equivalent to
maintain.

The cast+reshard runs once per generate() call and is cached against
``state.step``, so repeated rollouts between updates reuse the copy.
"""

import jax
import jax.numpy as jnp

from ..inference.config import DeepSpeedInferenceConfig
from ..inference.engine import InferenceEngine
from ..utils.logging import log_dist
from .engine import DeepSpeedEngine


class DeepSpeedHybridEngine(DeepSpeedEngine):
    """Training engine + shared-weight generation (reference :32)."""

    def __init__(self, model, **kwargs):
        super().__init__(model, **kwargs)
        hcfg = dict(self._config.raw_config.get("hybrid_engine", {}))
        hcfg.pop("enabled", None)
        # inference side runs on the SAME mesh; tp degree is the mesh's
        infer_cfg = {
            "dtype": "bfloat16" if self.compute_dtype == jnp.bfloat16 else
                     ("float16" if self.compute_dtype == jnp.float16 else "float32"),
            "max_out_tokens": hcfg.pop("max_out_tokens", 2048),
            "kernel_inject": hcfg.pop("kernel_inject",
                                      getattr(getattr(model, "cfg", None), "attention_impl", "xla")
                                      == "flash"),
        }
        self._infer = InferenceEngine.__new__(InferenceEngine)  # shared-weight construction below
        self._init_shared_inference(model, infer_cfg)
        self._gen_params_step = None
        self._in_train_mode = True
        log_dist("HybridEngine ready: train + shared-weight generate() on one mesh", [0])

    def _init_shared_inference(self, model, infer_cfg):
        """Build the inference engine around the live training params instead
        of letting it materialize its own."""
        import dataclasses
        from .lora import LoRAModel
        inf = self._infer
        inf._config = DeepSpeedInferenceConfig(infer_cfg)
        overrides = {"dtype": self.compute_dtype}
        if inf._config.kernel_inject:
            overrides["attention_impl"] = "flash"
        # generation always runs the INNER model over merged/fused weights;
        # the LoRA wrapper only matters on the training side
        inner = model.inner if isinstance(model, LoRAModel) else model
        inf.module = type(inner)(dataclasses.replace(inner.cfg, **overrides))
        inf.model_config = inf.module.cfg
        inf.mesh = self.mesh
        inf.planner = self.planner
        inf.params = None  # refreshed per generate()
        inf._compiled = {}
        inf._cache_pool = {}

    # ------------------------------------------------------------------ modes
    def eval(self):
        """Switch to generation mode (reference ``eval()`` path)."""
        self._in_train_mode = False
        return self

    def train(self, mode=True):
        self._in_train_mode = mode
        return self

    # ------------------------------------------------------------------ weights
    def _refresh_generation_params(self):
        """Cast master -> compute dtype in the inference layout (merging LoRA
        adapters unless they are already fused into base); cached until the
        next optimizer step changes the weights."""
        step = int(self.state.step)
        fused = getattr(self, "_lora_fused", False)
        if self._gen_params_step == (step, fused) and self._infer.params is not None:
            return
        lora = self._lora()
        cast = lambda t: jax.tree_util.tree_map(
            lambda x: jnp.asarray(x, self.compute_dtype), t)
        if self.offload_optimizer and lora is None:
            # compute params ARE the live weights already
            self._infer.params = self.state.params
        else:
            key = "hybrid_cast_fused" if fused else "hybrid_cast"
            if key not in self._compiled:
                if lora is None:
                    fn = cast
                elif fused:
                    fn = lambda p: cast(p["base"])
                else:
                    fn = lambda p: cast(lora.merge(p))
                abstract = jax.eval_shape(fn, self.state.params)
                shardings = self.planner.shardings(self.planner.master_specs(abstract))
                self._compiled[key] = jax.jit(fn, out_shardings=shardings)
            with self.mesh:
                self._infer.params = self._compiled[key](self.state.params)
        self._gen_params_step = (step, fused)

    # ------------------------------------------------------------------ generate
    def generate(self, input_ids, **kwargs):
        """RLHF rollout generation against the current training weights
        (reference ``generate`` :168). Accepts the InferenceEngine.generate
        signature."""
        self._refresh_generation_params()
        return self._infer.generate(input_ids, **kwargs)

    def infer_forward(self, input_ids, attention_mask=None):
        """Inference-mode logits over full sequences (scoring/reward paths)."""
        self._refresh_generation_params()
        return self._infer.forward(input_ids, attention_mask)

    # ------------------------------------------------------------------ LoRA
    # Reference fuse_lora_weight :129: DeepSpeed-Chat bakes the adapters into
    # the base weights around the rollout phase so generation pays no per-call
    # merge. Here the module is a runtime.lora.LoRAModel and fusing rewrites
    # state.params["base"] in place (donated jit); generate() then skips the
    # per-call merge by handing the INNER model the fused base directly.
    def _lora(self):
        from .lora import LoRAModel
        return self.module if isinstance(self.module, LoRAModel) else None

    def fuse_lora_weight(self):
        lora = self._lora()
        if lora is None:
            return None  # no adapters: API-parity no-op
        if getattr(self, "_lora_fused", False):
            return None
        if "lora_fuse" not in self._compiled:
            shardings = self.planner.shardings(self.planner.master_specs(self.state.params))
            self._compiled["lora_fuse"] = jax.jit(lora.fuse_params, donate_argnums=(0, ),
                                                  out_shardings=shardings)
            self._compiled["lora_unfuse"] = jax.jit(lora.unfuse_params, donate_argnums=(0, ),
                                                    out_shardings=shardings)
        with self.mesh:
            self.state = self.state._replace(params=self._compiled["lora_fuse"](self.state.params))
        self._lora_fused = True
        self._gen_params_step = None  # generation cache now stale
        return None

    def unfuse_lora_weight(self, quantize=False):
        lora = self._lora()
        if lora is None or not getattr(self, "_lora_fused", False):
            return None
        with self.mesh:
            self.state = self.state._replace(params=self._compiled["lora_unfuse"](self.state.params))
        self._lora_fused = False
        self._gen_params_step = None
        return None
