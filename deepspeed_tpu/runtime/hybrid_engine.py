"""Hybrid engine (RLHF).

TPU-native analogue of reference ``runtime/hybrid_engine.py:32``
(``DeepSpeedHybridEngine``): ONE engine that both trains (ZeRO) and serves
rollout generation for the RLHF actor — the DeepSpeed-Chat pattern where
rollout generation alternates with PPO updates every step.

Design translation (rebuilt on the modern serving stack — see
``deepspeed_tpu/rlhf/`` and ``benchmarks/RLHF.md``): the reference flips
between ZeRO-3 training modules and kernel-injected inference containers
that share weight storage (``create_inference_module`` :298,
``_zero3_forward`` :333). Here the two sides are pure functions over
parameter pytrees, so "sharing" is a versioned in-memory publication: a
:class:`~deepspeed_tpu.rlhf.WeightPublisher` casts+reshards the fp32
masters into the inference compute layout ONCE per optimizer update (cached
against the training step, so repeated rollouts between updates reuse the
copy — the seed-era stub's step-keyed cache idea, now done through the
scheduler's swap protocol so the identity-keyed ``_fast_tree_cache`` and
the radix prefix cache stay coherent), and rollout generation runs through
the continuous-batching :class:`DecodeScheduler` — chunked prefill, prefix
cache over the shared prompt template, speculative decoding, per-request
traces — instead of the static-batch ``generate()`` program.

Config (``hybrid_engine`` section)::

    "hybrid_engine": {
        "enabled": true,
        "max_out_tokens": 2048,     # inference-side cache budget
        "kernel_inject": false,     # Pallas decode path (default: model's)
        "gen_steps": 1,             # N rollout collect rounds per publication
        "ppo_epochs": 1,            # M update passes per rollout buffer
        "pad_token_id": 0,
        "rollout": {"num_slots": 8, ...}   # continuous_batching overrides
    }
"""

import jax
import jax.numpy as jnp

from ..inference.engine import InferenceEngine
from ..rlhf import RolloutBuffer, RolloutCollector, WeightPublisher
from ..utils.logging import log_dist
from .engine import DeepSpeedEngine


def default_ppo_update(engine, batch):
    """The minimal PPO-shaped update hook: one ``train_batch`` on the
    rollout sequences (language-model loss over prompt+completion — the
    DeepSpeed-Chat actor's pretraining-mix step). ``labels`` carries the
    pre-shifted targets with ``-100`` on padding, so ragged rollouts never
    spend gradient learning to emit the pad token. The full PPO-shaped
    batch (``loss_mask``/``old_logprobs``/``rewards``/``advantages``) is
    on ``batch`` for custom hooks that implement a clipped policy-gradient
    objective."""
    return engine.train_batch(batch={"input_ids": batch["input_ids"],
                                     "labels": batch["labels"]})


class DeepSpeedHybridEngine(DeepSpeedEngine):
    """Training engine + shared-weight rollout generation (reference :32)."""

    def __init__(self, model, **kwargs):
        super().__init__(model, **kwargs)
        hcfg = dict(self._config.raw_config.get("hybrid_engine", {}))
        hcfg.pop("enabled", None)
        self.gen_steps = int(hcfg.pop("gen_steps", 1))
        self.ppo_epochs = int(hcfg.pop("ppo_epochs", 1))
        self.pad_token_id = int(hcfg.pop("pad_token_id", 0))
        rollout = dict(hcfg.pop("rollout", {}))
        rollout.setdefault("enabled", True)
        # inference side runs on the SAME mesh; tp degree is the mesh's
        infer_cfg = {
            "dtype": "bfloat16" if self.compute_dtype == jnp.bfloat16 else
                     ("float16" if self.compute_dtype == jnp.float16 else "float32"),
            "max_out_tokens": hcfg.pop("max_out_tokens", 2048),
            "kernel_inject": hcfg.pop("kernel_inject",
                                      getattr(getattr(model, "cfg", None), "attention_impl", "xla")
                                      == "flash"),
            "continuous_batching": rollout,
        }
        # generation always runs the INNER model over merged/fused weights;
        # the LoRA wrapper only matters on the training side
        from .lora import LoRAModel
        inner = model.inner if isinstance(model, LoRAModel) else model
        # the supported shared-params construction path: full config
        # validation + engine setup, weights installed by the publisher
        self._infer = InferenceEngine.from_shared_params(inner, infer_cfg)
        self.publisher = WeightPublisher(self, self._infer)
        self.collector = RolloutCollector(self._infer)
        self._in_train_mode = True
        log_dist("HybridEngine ready: train + scheduler-served rollouts with "
                 "in-memory weight publication on one mesh", [0])

    # ------------------------------------------------------------------ modes
    def eval(self):
        """Switch to generation mode (reference ``eval()`` path)."""
        self._in_train_mode = False
        return self

    def train(self, mode=True):
        self._in_train_mode = mode
        return self

    # ------------------------------------------------------------------ weights
    def publish_weights(self):
        """Publish the current training weights to the inference side — an
        in-memory cast+reshard installed through the scheduler's
        ``pause/flush/swap/resume`` protocol (no checkpoint round-trip, no
        new XLA programs after the first cycle, all retained KV and prefix
        registrations invalidated). No-op while the live publication is
        already current. Returns the live
        :class:`~deepspeed_tpu.rlhf.Publication`."""
        # build the scheduler first so even the FIRST publication lands
        # through the swap protocol (published_version tagged from cycle 1)
        return self.publisher.publish(self._infer.scheduler())

    # ------------------------------------------------------------------ rollouts
    def rollout_scheduler(self, **overrides):
        """The inference side's continuous-batching scheduler (built from
        ``hybrid_engine.rollout`` on first use). The live weights are
        (re-)published through it on first use, so a bare ``submit()``
        never dispatches against an empty shared-params engine and the
        scheduler's version bookkeeping can't desync from a publication
        installed before the scheduler existed (legacy ``generate()``
        first). Publishing NEW weights stays explicit
        (:meth:`publish_weights` / :meth:`rlhf_step`) — this only repairs
        a missing install."""
        sched = self._infer.scheduler(**overrides)
        if (self.publisher.live is None
                or sched.published_version != self.publisher.live.version):
            self.publisher.publish(sched)
        return sched

    def collect_rollouts(self, prompts, buffer=None, reward_fn=None, **gen_kwargs):
        """One rollout round under the CURRENT weights: publish (cached),
        then every prompt through the scheduler — chunked prefill, radix
        hits on shared prompt prefixes, speculation if configured — into a
        :class:`~deepspeed_tpu.rlhf.RolloutBuffer` with old-logprob capture
        at the publication version."""
        pub = self.publish_weights()
        buf = self.collector.collect(prompts, buffer=buffer, reward_fn=reward_fn,
                                     version=pub.version, **gen_kwargs)
        if self.telemetry.enabled:
            self.telemetry.gauge("rlhf/staleness_steps",
                                 self.publisher.staleness_steps())
        return buf

    def rlhf_step(self, prompts, reward_fn=None, update_fn=None, gen_steps=None,
                  ppo_epochs=None, seed=0, **gen_kwargs):
        """One full train -> generate -> train cycle (the DeepSpeed-Chat
        alternation): publish the current weights, run ``gen_steps`` rollout
        rounds over ``prompts`` through the scheduler, then ``ppo_epochs``
        update passes over the collected buffer via ``update_fn(engine,
        ppo_batch)`` (default: :func:`default_ppo_update`). Returns
        ``(buffer, losses)``; the NEXT call publishes the updated weights,
        so staleness is bounded by ``ppo_epochs`` optimizer steps."""
        n = self.gen_steps if gen_steps is None else int(gen_steps)
        m = self.ppo_epochs if ppo_epochs is None else int(ppo_epochs)
        pub = self.publish_weights()
        buf = RolloutBuffer()
        for i in range(n):
            self.collector.collect(prompts, buffer=buf, reward_fn=reward_fn,
                                   version=pub.version,
                                   seed=seed + i * len(prompts), **gen_kwargs)
        update = default_ppo_update if update_fn is None else update_fn
        bs = self.train_batch_size() // jax.process_count()
        mc = getattr(self.module, "cfg", None) or \
            getattr(getattr(self.module, "inner", None), "cfg", None)
        losses = []
        for i in range(m):
            batch = buf.ppo_batch(bs, pad_token_id=self.pad_token_id, start=i * bs,
                                  max_len=getattr(mc, "max_seq_len", None))
            losses.append(float(update(self, batch)))
        if self.telemetry.enabled:
            self.telemetry.gauge("rlhf/staleness_steps",
                                 self.publisher.staleness_steps())
        return buf, losses

    # ------------------------------------------------------------------ generate
    def generate(self, input_ids, **kwargs):
        """RLHF rollout generation against the current training weights
        (reference ``generate`` :168). Accepts the InferenceEngine.generate
        signature; batch-shaped legacy path — :meth:`collect_rollouts` is
        the scheduler-served loop."""
        self.publisher.publish()
        return self._infer.generate(input_ids, **kwargs)

    def infer_forward(self, input_ids, attention_mask=None):
        """Inference-mode logits over full sequences (scoring/reward paths)."""
        self.publisher.publish()
        return self._infer.forward(input_ids, attention_mask)

    # ------------------------------------------------------------------ LoRA
    # Reference fuse_lora_weight :129: DeepSpeed-Chat bakes the adapters into
    # the base weights around the rollout phase so generation pays no per-call
    # merge. Here the module is a runtime.lora.LoRAModel and fusing rewrites
    # state.params["base"] in place (donated jit); the publisher's snapshot
    # key includes the fusion flag, so the next publish re-casts.
    def _lora(self):
        from .lora import LoRAModel
        return self.module if isinstance(self.module, LoRAModel) else None

    def fuse_lora_weight(self):
        lora = self._lora()
        if lora is None:
            return None  # no adapters: API-parity no-op
        if getattr(self, "_lora_fused", False):
            return None
        if "lora_fuse" not in self._compiled:
            shardings = self.planner.shardings(self.planner.master_specs(self.state.params))
            self._compiled["lora_fuse"] = jax.jit(lora.fuse_params, donate_argnums=(0, ),
                                                  out_shardings=shardings)
            self._compiled["lora_unfuse"] = jax.jit(lora.unfuse_params, donate_argnums=(0, ),
                                                    out_shardings=shardings)
        with self.mesh:
            self.state = self.state._replace(params=self._compiled["lora_fuse"](self.state.params))
        self._lora_fused = True
        return None

    def unfuse_lora_weight(self, quantize=False):
        lora = self._lora()
        if lora is None or not getattr(self, "_lora_fused", False):
            return None
        with self.mesh:
            self.state = self.state._replace(params=self._compiled["lora_unfuse"](self.state.params))
        self._lora_fused = False
        return None
