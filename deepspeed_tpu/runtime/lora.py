"""LoRA: low-rank adapters as first-class pytree leaves.

The reference treats LoRA as a core RLHF memory lever — DeepSpeed-Chat
trains actors with adapters only and the hybrid engine fuses/unfuses them
around generation (``runtime/hybrid_engine.py:129 fuse_lora_weight``).
TPU-native form: ``LoRAModel`` wraps any zoo model and splits the parameter
pytree into ``{"base": ..., "lora": ...}``:

- ``base`` keeps the inner model's tree (frozen by default: the loss sees it
  through ``stop_gradient``, so XLA dead-code-eliminates the entire base
  backward pass and the optimizer holds state for adapters only — the
  ``only_optimize_lora`` memory profile).
- ``lora`` mirrors every kernel matched by ``target_modules`` with a pair
  ``{"a": (..., in, r), "b": (..., r, out)}``; scanned stacks keep their
  leading layer dim on both halves.

The merge ``W + (alpha/r) * a @ b`` happens functionally inside ``loss``/
``apply`` — there is no module surgery, and "fusing" for generation is just
baking the same delta into the base leaves (``fuse_params``), which the
hybrid engine does once per rollout phase instead of per call.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np


def _slash(path):
    parts = []
    for p in path:
        parts.append(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))))
    return "/".join(parts)


DEFAULT_TARGETS = (r"attn/(q|k|v|o)_proj/kernel", r"mlp/(gate|up|down)_proj/kernel")

# projection-site names of the batched multi-adapter serving path
# (deepspeed_tpu/adapters/): the leaf names LoRAModel.init_lora mints map
# onto them 1:1 ("lora_q_proj" -> "q", ...)
SERVING_SITES = ("q", "k", "v", "o", "gate", "up", "down")


def site_adapters(lora_tree):
    """Flatten a ``LoRAModel`` adapter tree into the serving-site form the
    paged adapter store registers: ``{site: (a, b)}`` host float32 arrays
    with a LEADING LAYER AXIS — ``a`` (L, in..., r), ``b`` (L, r, out...).
    Scanned trees (``layers/...``) already carry the layer dim; unrolled
    trees (``layer_0/...``) are stacked in layer order. Non-layer adapters
    (none under DEFAULT_TARGETS) are rejected — the batched serving path
    gathers per-layer pages."""
    per_layer = {}  # site -> {layer_idx or None: (a, b)}

    def walk(node, path):
        for k, v in node.items():
            p = path + (k, )
            if isinstance(v, dict) and "a" in v and "b" in v \
                    and not isinstance(v["a"], dict):
                # init_lora mints "lora_kernel" under the projection scope
                # ("layers/attn/q_proj/lora_kernel"): the SITE is the scope
                # name; a flat "lora_q_proj" spelling is accepted too
                if k == "lora_kernel" and len(p) >= 2:
                    scope = p[-2]
                elif k.startswith("lora_"):
                    scope = k[len("lora_"):]
                else:
                    raise ValueError(f"unrecognized adapter leaf {'/'.join(p)!r}")
                site = scope[:-len("_proj")] if scope.endswith("_proj") else scope
                if site not in SERVING_SITES:
                    raise ValueError(f"adapter site {site!r} has no batched "
                                     f"serving path (sites: {SERVING_SITES})")
                root = p[0]
                if root == "layers":
                    idx = None  # stacked: layer dim already leading
                elif root.startswith("layer_"):
                    idx = int(root[len("layer_"):])
                else:
                    raise ValueError(
                        f"adapter {'/'.join(p)!r} is not under a layer stack; "
                        f"the batched serving path pages per-layer adapters only")
                per_layer.setdefault(site, {})[idx] = (
                    np.asarray(v["a"], np.float32), np.asarray(v["b"], np.float32))
            elif isinstance(v, dict):
                walk(v, p)

    walk(lora_tree, ())
    if not per_layer:
        raise ValueError("adapter tree holds no lora_* leaves")
    out = {}
    for site, layers in per_layer.items():
        if None in layers:  # scanned
            out[site] = layers[None]
        else:
            order = sorted(layers)
            out[site] = (np.stack([layers[i][0] for i in order]),
                         np.stack([layers[i][1] for i in order]))
    return out


def _split_dims(path, ndim, scanned):
    """(n_lead, n_in) split of a kernel's dims under the zoo layouts:
    (in, out) MLP / 2-D, (in, heads, hd) qkv, (heads, hd, out) o_proj —
    each with a leading layer dim when scanned."""
    lead = 1 if scanned else 0
    nd = ndim - lead
    if nd == 2:
        n_in = 1
    elif "o_proj" in path:
        n_in = 2  # (heads, hd) jointly form the input
    else:
        n_in = 1  # (in, heads, hd): heads*hd form the output
    return lead, n_in


class LoRAModel:
    """Engine-facing wrapper: ``params = {"base", "lora"}``; delegates the
    zoo model protocol with path adjustments."""

    def __init__(self, inner, r=8, alpha=16.0, target_modules=DEFAULT_TARGETS,
                 only_optimize_lora=True, rng_seed=0):
        self.inner = inner
        self.cfg = getattr(inner, "cfg", None)
        self.r = int(r)
        self.alpha = float(alpha)
        self.scale = self.alpha / self.r
        self.patterns = [re.compile(p) for p in target_modules]
        self.only_optimize_lora = bool(only_optimize_lora)
        self._seed = rng_seed

    # ---- params -----------------------------------------------------------
    def _matches(self, path):
        return any(p.search(path) for p in self.patterns)

    def _adapter_shapes(self, path, shape):
        scanned = path.split("/", 1)[0] == "layers"
        lead, n_in = _split_dims(path, len(shape), scanned)
        lead_s = shape[:lead]
        in_s = shape[lead:lead + n_in]
        out_s = shape[lead + n_in:]
        return (lead_s + in_s + (self.r, ), lead_s + (self.r, ) + out_s)

    def init_lora(self, base_params, rng):
        """Adapter tree: ``a`` ~ N(0, 1/r) (reference kaiming-ish), ``b`` = 0
        so training starts at the base function exactly."""
        flat = jax.tree_util.tree_flatten_with_path(base_params)
        out = {}
        i = 0
        for p, leaf in flat[0]:
            path = _slash(p)
            if getattr(leaf, "ndim", 0) >= 2 and self._matches(path):
                sa, sb = self._adapter_shapes(path, tuple(leaf.shape))
                ra = jax.random.fold_in(rng, i)
                node = out
                for part in path.split("/")[:-1]:
                    node = node.setdefault(part, {})
                # "lora_<leaf>" (not "<leaf>/a"): nesting under the kernel
                # name would make TP-rule regexes ending in /kernel match the
                # adapter leaves and demand the base kernel's rank
                node["lora_" + path.split("/")[-1]] = {
                    "a": jax.random.normal(ra, sa, jnp.float32) / np.sqrt(self.r),
                    "b": jnp.zeros(sb, jnp.float32),
                }
                i += 1
        if not out:
            raise ValueError(f"LoRA target_modules matched no kernels: "
                             f"{[p.pattern for p in self.patterns]}")
        return out

    def init_params(self, rng):
        base = self.inner.init_params(rng)
        return {"base": base, "lora": self.init_lora(base, jax.random.fold_in(rng, 0x10A))}

    def merge(self, params):
        """Effective inner-model params: base + scale * a@b on every adapted
        leaf (traceable; runs inside the compiled step)."""
        base, lora = params["base"], params["lora"]

        # path-keyed merge: align adapter pairs to base leaves by path
        flat_b = jax.tree_util.tree_flatten_with_path(base)
        lora_flat = {}
        for p, leaf in jax.tree_util.tree_flatten_with_path(lora)[0]:
            path = _slash(p)
            lora_flat.setdefault(path.rsplit("/", 1)[0], {})[path.rsplit("/", 1)[1]] = leaf
        out = []
        for p, w in flat_b[0]:
            path = _slash(p)
            head, _, last = path.rpartition("/")
            pair = lora_flat.get((head + "/" if head else "") + "lora_" + last)
            if pair is None:
                out.append(w)
                continue
            a, bm = pair["a"], pair["b"]
            scanned = path.split("/", 1)[0] == "layers"
            lead, n_in = _split_dims(path, w.ndim, scanned)
            lead_s = w.shape[:lead]
            in_n = int(np.prod(w.shape[lead:lead + n_in], dtype=np.int64))
            out_n = int(np.prod(w.shape[lead + n_in:], dtype=np.int64))
            al = a.reshape(lead_s + (in_n, self.r)).astype(jnp.float32)
            bl = bm.reshape(lead_s + (self.r, out_n)).astype(jnp.float32)
            delta = (self.scale * (al @ bl)).reshape(w.shape)
            out.append((w.astype(jnp.float32) + delta).astype(w.dtype))
        return jax.tree_util.tree_unflatten(flat_b[1], out)

    def fuse_params(self, params):
        """Bake the adapters into base (generation-time fuse). Returns a new
        ``{"base": merged, "lora": unchanged}`` tree."""
        return {"base": self.merge(params), "lora": params["lora"]}

    def unfuse_params(self, params):
        """Inverse of ``fuse_params`` (subtract the delta): negate the 'b'
        halves so a@b flips sign exactly once."""
        def flip(node):
            if isinstance(node, dict) and "a" in node and "b" in node \
                    and not isinstance(node["a"], dict):
                return {"a": node["a"], "b": -node["b"]}
            return {k: flip(v) for k, v in node.items()} if isinstance(node, dict) else node
        merged = self.merge({"base": params["base"], "lora": flip(params["lora"])})
        return {"base": merged, "lora": params["lora"]}

    # ---- model protocol ---------------------------------------------------
    def _train_view(self, params):
        base = params["base"]
        if self.only_optimize_lora:
            base = jax.lax.stop_gradient(base)
        return self.merge({"base": base, "lora": params["lora"]})

    def loss(self, params, batch, rng):
        return self.inner.loss(self._train_view(params), batch, rng)

    def apply(self, params, *a, **kw):
        return self.inner.apply(self.merge(params), *a, **kw)

    def apply_with_cache(self, params, *a, **kw):
        return self.inner.apply_with_cache(self.merge(params), *a, **kw)

    def init_cache(self, *a, **kw):
        return self.inner.init_cache(*a, **kw)

    def tp_rules(self):
        # re.search, so inner patterns still hit "base/..." paths; adapter
        # leaves are small and stay replicated
        return self.inner.tp_rules() if hasattr(self.inner, "tp_rules") else []

    def expert_pattern(self):
        return self.inner.expert_pattern() if hasattr(self.inner, "expert_pattern") else None

    def pipeline_pattern(self):
        return None  # LoRA + PP not composed (reference RLHF actors run ZeRO)

    def optimizer_mask(self, params):
        """optax.masked mask: True = trainable (adapters; base too unless
        only_optimize_lora)."""
        t = self.only_optimize_lora
        return {"base": jax.tree_util.tree_map(lambda _: not t, params["base"]),
                "lora": jax.tree_util.tree_map(lambda _: True, params["lora"])}

    def __getattr__(self, name):
        return getattr(self.inner, name)
