"""LR schedules.

Analogue of reference ``deepspeed/runtime/lr_schedules.py`` (``LRRangeTest``
:258, ``OneCycle`` :361, ``WarmupLR`` :626, ``WarmupDecayLR`` :715, plus
``WarmupCosineLR`` from later versions). Two call styles:

- **functional** (idiomatic): every schedule exposes ``__call__(step) -> lr``
  and is jit-traceable (pure jnp math), so the engine folds it into the
  compiled train step.
- **stateful facade**: ``step()`` / ``get_lr()`` / ``state_dict()`` /
  ``load_state_dict()`` for reference API parity.
"""

import math

import jax.numpy as jnp

VALID_LR_SCHEDULES = ["LRRangeTest", "OneCycle", "WarmupLR", "WarmupDecayLR", "WarmupCosineLR"]

WARMUP_LOG_RATE = "log"
WARMUP_LINEAR_RATE = "linear"


class _LRSchedule:
    """Base: stateful facade over a pure ``step -> lr`` function."""

    def __init__(self, optimizer=None, last_batch_iteration=-1):
        self.optimizer = optimizer
        self.last_batch_iteration = last_batch_iteration

    def __call__(self, step):
        raise NotImplementedError

    def get_lr(self):
        return [float(self(jnp.maximum(self.last_batch_iteration, 0)))]

    def get_last_lr(self):
        assert getattr(self, "_last_lr", None) is not None, "need to call step() first"
        return self._last_lr

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration
        self._last_lr = [float(self(jnp.asarray(last_batch_iteration, dtype=jnp.float32)))]
        if self.optimizer is not None and hasattr(self.optimizer, "set_lr"):
            self.optimizer.set_lr(self._last_lr[0])

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


class LRRangeTest(_LRSchedule):
    """LR range test (reference :258): linear or continuous staircase ramp."""

    def __init__(self,
                 optimizer=None,
                 lr_range_test_min_lr=1e-3,
                 lr_range_test_step_size=2000,
                 lr_range_test_step_rate=1.0,
                 lr_range_test_staircase=False,
                 last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase

    def __call__(self, step):
        step = jnp.asarray(step, dtype=jnp.float32)
        if self.staircase:
            interval = jnp.floor(step / self.step_size)
        else:
            interval = step / self.step_size
        return self.min_lr * (1 + interval * self.step_rate)


class OneCycle(_LRSchedule):
    """1-cycle policy (reference :361): cycle lr up/down then decay."""

    def __init__(self,
                 optimizer=None,
                 cycle_min_lr=0.0,
                 cycle_max_lr=1e-3,
                 decay_lr_rate=0.0,
                 cycle_first_step_size=2000,
                 cycle_second_step_size=None,
                 cycle_first_stair_count=0,
                 cycle_second_stair_count=None,
                 decay_step_size=0,
                 cycle_momentum=True,
                 cycle_min_mom=0.85,
                 cycle_max_mom=0.99,
                 decay_mom_rate=0.0,
                 last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first_size = cycle_first_step_size
        self.second_size = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
        self.decay_step_size = decay_step_size
        self.total_size = self.first_size + self.second_size
        self.cycle_momentum = cycle_momentum
        self.cycle_min_mom = cycle_min_mom
        self.cycle_max_mom = cycle_max_mom
        self.decay_mom_rate = decay_mom_rate

    def __call__(self, step):
        step = jnp.asarray(step, dtype=jnp.float32)
        in_cycle_lr = self._cycle_lr(step)
        decay_lr = self._decay_lr(step)
        return jnp.where(step <= self.total_size, in_cycle_lr, decay_lr)

    def _cycle_lr(self, step):
        up = jnp.clip(step / self.first_size, 0.0, 1.0)
        down = jnp.clip((step - self.first_size) / self.second_size, 0.0, 1.0)
        scale = jnp.where(step <= self.first_size, up, 1.0 - down)
        return self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * scale

    def _decay_lr(self, step):
        if self.decay_step_size > 0:
            decay_steps = (step - self.total_size) / self.decay_step_size
        else:
            decay_steps = jnp.zeros_like(step)
        return self.cycle_min_lr / (1.0 + decay_steps * self.decay_lr_rate)

    def get_mom(self, step):
        step = jnp.asarray(step, dtype=jnp.float32)
        up = jnp.clip(step / self.first_size, 0.0, 1.0)
        down = jnp.clip((step - self.first_size) / self.second_size, 0.0, 1.0)
        scale = jnp.where(step <= self.first_size, up, 1.0 - down)
        return self.cycle_max_mom - (self.cycle_max_mom - self.cycle_min_mom) * scale


class WarmupLR(_LRSchedule):
    """Warmup then hold (reference :626)."""

    def __init__(self,
                 optimizer=None,
                 warmup_min_lr=0.0,
                 warmup_max_lr=0.001,
                 warmup_num_steps=1000,
                 warmup_type=WARMUP_LOG_RATE,
                 last_batch_iteration=-1):
        super().__init__(optimizer, last_batch_iteration)
        self.warmup_min_lr = warmup_min_lr
        self.warmup_max_lr = warmup_max_lr
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.warmup_type = warmup_type
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)

    def _warmup_gamma(self, step):
        if self.warmup_type == WARMUP_LOG_RATE:
            return self.inverse_log_warm_up * jnp.log(jnp.maximum(step, 1.0))
        return step / self.warmup_num_steps

    def __call__(self, step):
        step = jnp.asarray(step, dtype=jnp.float32)
        gamma = jnp.clip(self._warmup_gamma(step), 0.0, 1.0)
        warm = self.warmup_min_lr + (self.warmup_max_lr - self.warmup_min_lr) * gamma
        return jnp.where(step < self.warmup_num_steps, warm, self._post_warmup_lr(step))

    def _post_warmup_lr(self, step):
        return jnp.asarray(self.warmup_max_lr, dtype=jnp.float32)


class WarmupDecayLR(WarmupLR):
    """Warmup then linear decay to zero over total_num_steps (reference :715)."""

    def __init__(self,
                 optimizer=None,
                 total_num_steps=10000,
                 warmup_min_lr=0.0,
                 warmup_max_lr=0.001,
                 warmup_num_steps=1000,
                 warmup_type=WARMUP_LOG_RATE,
                 last_batch_iteration=-1):
        self.total_num_steps = total_num_steps
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type,
                         last_batch_iteration)

    def _post_warmup_lr(self, step):
        frac = (self.total_num_steps - step) / max(1.0, self.total_num_steps - self.warmup_num_steps)
        return self.warmup_max_lr * jnp.clip(frac, 0.0, 1.0)


class WarmupCosineLR(WarmupLR):
    """Warmup then cosine decay (upstream post-0.9 schedule, included for the
    target capability set)."""

    def __init__(self,
                 optimizer=None,
                 total_num_steps=10000,
                 warmup_min_ratio=0.0,
                 warmup_num_steps=1000,
                 cos_min_ratio=0.0001,
                 warmup_max_lr=0.001,
                 warmup_type=WARMUP_LOG_RATE,
                 last_batch_iteration=-1):
        self.total_num_steps = total_num_steps
        self.cos_min_ratio = cos_min_ratio
        super().__init__(optimizer, warmup_min_ratio * warmup_max_lr, warmup_max_lr, warmup_num_steps,
                         warmup_type, last_batch_iteration)

    def _post_warmup_lr(self, step):
        frac = jnp.clip(
            (step - self.warmup_num_steps) / max(1.0, self.total_num_steps - self.warmup_num_steps), 0.0, 1.0)
        cos = 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
        ratio = self.cos_min_ratio + (1 - self.cos_min_ratio) * cos
        return self.warmup_max_lr * ratio


SCHEDULE_CLASSES = {
    "LRRangeTest": LRRangeTest,
    "OneCycle": OneCycle,
    "WarmupLR": WarmupLR,
    "WarmupDecayLR": WarmupDecayLR,
    "WarmupCosineLR": WarmupCosineLR,
}


def get_lr_schedule(name, params, optimizer=None):
    if name not in SCHEDULE_CLASSES:
        raise ValueError(f"Unknown lr schedule {name}; valid: {VALID_LR_SCHEDULES}")
    return SCHEDULE_CLASSES[name](optimizer=optimizer, **params)
