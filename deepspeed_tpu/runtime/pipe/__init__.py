from .schedule import spmd_pipeline  # noqa: F401
from .module import LayerSpec, TiedLayerSpec, PipelineModule, partition_balanced  # noqa: F401
