"""Pipeline module / layer partitioning.

API parity with reference ``runtime/pipe/module.py`` (``LayerSpec`` :29,
``TiedLayerSpec`` :76, ``PipelineModule`` :85, ``_partition_layers`` :353)
translated to the functional world: a LayerSpec is a lazy ``(init, apply)``
factory instead of a lazy ``nn.Module`` constructor, and partitioning
produces stage boundaries consumed by the SPMD pipeline schedule.
"""

import re

import numpy as np

from ...utils.logging import logger


class LayerSpec:
    """Lazy layer: build only on the owning stage (reference ``module.py:29``).

    ``typename``: a class or factory; called with ``*args, **kwargs`` by
    ``build()``.
    """

    def __init__(self, typename, *args, **kwargs):
        self.typename = typename
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.typename(*self.args, **self.kwargs)

    @property
    def name(self):
        return getattr(self.typename, "__name__", str(self.typename))

    def __repr__(self):
        return f"LayerSpec({self.name})"


class TiedLayerSpec(LayerSpec):
    """Layer whose parameters are shared across stages by key (reference
    ``module.py:76``; e.g. tied embeddings). In the SPMD pipeline tied
    parameters live *outside* the pipelined segment (embed/head run
    replicated over ``pipe``), so the reference's tied-grad allreduce
    (``pipe/engine.py:223``) happens implicitly in the backward pass."""

    def __init__(self, key, typename, *args, forward_fn=None, tied_weight_attr="weight", **kwargs):
        super().__init__(typename, *args, **kwargs)
        self.key = key
        self.forward_fn = forward_fn
        self.tied_weight_attr = tied_weight_attr


def partition_uniform(num_items, num_parts):
    """Balanced contiguous split: boundaries array of len num_parts+1."""
    base = num_items // num_parts
    extra = num_items % num_parts
    counts = [base + (1 if i < extra else 0) for i in range(num_parts)]
    bounds = [0]
    for c in counts:
        bounds.append(bounds[-1] + c)
    return bounds


def partition_balanced(weights, num_parts):
    """Split ``weights`` into ``num_parts`` contiguous groups minimizing the
    heaviest group (reference ``ds_utils.partition_balanced``): binary search
    over the bottleneck + greedy packing."""
    weights = [float(w) for w in weights]
    n = len(weights)
    if num_parts >= n:
        return list(range(n + 1)) + [n] * (num_parts - n)

    def fits(cap):
        parts, cur = 1, 0.0
        for w in weights:
            if w > cap:
                return False
            if cur + w > cap:
                parts += 1
                cur = w
            else:
                cur += w
        return parts <= num_parts

    lo, hi = max(weights), sum(weights)
    for _ in range(64):
        mid = (lo + hi) / 2
        if fits(mid):
            hi = mid
        else:
            lo = mid
    cap = hi
    bounds, cur = [0], 0.0
    for i, w in enumerate(weights):
        if cur + w > cap and len(bounds) < num_parts:
            bounds.append(i)
            cur = w
        else:
            cur += w
    bounds.append(n)
    while len(bounds) < num_parts + 1:
        bounds.insert(-1, bounds[-1])
    return bounds


class PipelineModule:
    """Sequence-of-layers container partitioned across pipeline stages
    (reference ``module.py:85``).

    ``layers``: list of LayerSpec (or callables). ``num_stages``: pipe size.
    ``partition_method``: 'uniform' | 'parameters' | 'type:<regex>'
    (reference ``_partition_layers`` :353).
    """

    def __init__(self, layers, num_stages, partition_method="parameters", loss_fn=None,
                 activation_checkpoint_interval=0):
        self.specs = [l if isinstance(l, LayerSpec) else LayerSpec(lambda l=l: l) for l in layers]
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.parts = self._partition(partition_method)
        self.tied_keys = sorted({s.key for s in self.specs if isinstance(s, TiedLayerSpec)})

    def _partition(self, method):
        n = len(self.specs)
        method = method.lower()
        if method in ("uniform", "uniform:"):
            return partition_uniform(n, self.num_stages)
        if method == "parameters":
            weights = [self._spec_param_count(s) for s in self.specs]
            return partition_balanced(weights, self.num_stages)
        if method.startswith("type:"):
            pat = re.compile(method[len("type:"):], re.IGNORECASE)
            weights = [1 if pat.search(s.name) else 0 for s in self.specs]
            return partition_balanced([max(w, 1e-6) for w in weights], self.num_stages)
        raise ValueError(f"Unknown partition_method {method!r}")

    @staticmethod
    def _spec_param_count(spec):
        built = spec.build()
        if hasattr(built, "num_params"):
            return max(1, built.num_params())
        if hasattr(built, "cfg") and hasattr(built.cfg, "num_params"):
            return max(1, built.cfg.num_params())
        return 1

    def stage_layers(self, stage_id):
        lo, hi = self.parts[stage_id], self.parts[stage_id + 1]
        return self.specs[lo:hi]

    def stage_owner(self, layer_idx):
        return int(np.searchsorted(np.asarray(self.parts[1:]), layer_idx, side="right"))

    def describe(self):
        lines = []
        for s in range(self.num_stages):
            names = [spec.name for spec in self.stage_layers(s)]
            lines.append(f"stage {s}: layers[{self.parts[s]}:{self.parts[s+1]}] {names}")
        return "\n".join(lines)
