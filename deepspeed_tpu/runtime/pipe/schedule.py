"""SPMD pipeline schedule.

TPU-native replacement for the reference's pipeline instruction interpreter
(``runtime/pipe/engine.py:40`` ``PipelineEngine``, ``schedule.py:189``
``TrainSchedule`` 1F1B, ``p2p.py`` wire). Design translation (SURVEY §7):
instead of N processes interpreting per-rank instruction streams and
exchanging tensors over NCCL P2P, ONE compiled program runs a circular
pipeline inside ``jax.shard_map`` that is *manual only over the* ``pipe``
*axis* — activations move between stages with ``lax.ppermute`` over ICI
neighbors while the other mesh axes (data/tensor/expert/seq) stay under the
automatic SPMD partitioner. Backward is just ``jax.grad`` through the scan:
``ppermute`` differentiates to the reverse permute, which reproduces the
backward P2P exchange of the reference schedule without an interpreter.

Schedule shape: with M microbatches and S stages, the scan runs M+S-1 steps;
stage s works on microbatch t-s at step t (classic fill/drain pipeline).
The reference's 1F1B ordering is an eager-mode *memory* optimization; under
XLA the whole program is compiled and activation liveness is bounded by
rematerialization instead (pass ``remat_policy``).
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...comm import comm as dist


def num_pipeline_steps(num_microbatches, num_stages):
    return num_microbatches + num_stages - 1


def spmd_pipeline(stage_fn, stage_params, x_stream, mesh=None, remat=False, with_aux=False):
    """Run ``x_stream`` through a ``pipe``-partitioned layer stack.

    ``stage_fn(local_params, x, t) -> y`` (or ``(y, aux)`` with
    ``with_aux=True``): applies one stage's layer slice at pipeline step
    ``t`` (an i32 scalar; use it to decorrelate per-step rngs); ``x``/``y``
    may be pytrees — non-activation leaves (e.g. an attention mask) ride
    along with their microbatch through every stage; ``stage_params``:
    pytree whose leaves have leading layer dim divisible by the ``pipe``
    axis size (sharded dim 0 across stages); ``x_stream``: pytree of
    (M, ...) microbatch streams entering stage 0.

    Returns the stream leaving the last stage, replicated over pipe; with
    ``with_aux`` also a scalar: the sum of ``aux`` over every VALID
    (stage, microbatch) tick, psum'd across stages — fill/drain ticks
    compute on garbage activations and are masked out. This is how
    per-stage side losses (MoE load-balancing aux, reference
    ``engine.py:2880`` composes MoE under PP) survive the pipeline.
    """
    mesh = mesh or dist.get_mesh()
    n_stages = mesh.shape[dist.PIPE_AXIS]
    if n_stages == 1:
        return _single_stage(stage_fn, stage_params, x_stream, remat, with_aux)
    M = jax.tree_util.tree_leaves(x_stream)[0].shape[0]
    steps = num_pipeline_steps(M, n_stages)
    fn = jax.checkpoint(stage_fn, static_argnums=()) if remat else stage_fn

    def tmap(f, *trees):
        return jax.tree_util.tree_map(f, *trees)

    def run(local_params, xs):
        stage = jax.lax.axis_index(dist.PIPE_AXIS)
        # carries become stage-varying inside the loop; mark them so upfront
        pvary = lambda v: jax.lax.pvary(v, (dist.PIPE_AXIS, ))
        state = tmap(lambda x: pvary(jnp.zeros_like(x[0])), xs)
        out_stream = tmap(lambda x: pvary(jnp.zeros_like(x)), xs)
        aux_total = pvary(jnp.zeros((), jnp.float32))
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def step(carry, t):
            state, out_stream, aux_total = carry
            feed = tmap(lambda x: jax.lax.dynamic_index_in_dim(x, jnp.minimum(t, M - 1), 0,
                                                               keepdims=False), xs)
            cur = tmap(lambda f, s: jnp.where(stage == 0, f, s), feed, state)
            out = fn(local_params, cur, t)
            y, aux = out if with_aux else (out, None)
            if with_aux:
                # stage s holds microbatch t-s; outside [0, M) it's fill/drain
                mb = t - stage
                valid = (mb >= 0) & (mb < M)
                aux_total = aux_total + jnp.where(valid, aux.astype(jnp.float32), 0.0)
            nxt = tmap(lambda v: jax.lax.ppermute(v, dist.PIPE_AXIS, perm), y)
            out_idx = t - (n_stages - 1)
            write = (stage == n_stages - 1) & (out_idx >= 0)
            out_stream = tmap(
                lambda os, v: jnp.where(
                    write, jax.lax.dynamic_update_index_in_dim(os, v, jnp.maximum(out_idx, 0), 0),
                    os), out_stream, y)
            return (nxt, out_stream, aux_total), None

        (_, out_stream, aux_total), _ = jax.lax.scan(
            step, (state, out_stream, aux_total), jnp.arange(steps))
        # deliver the last stage's stream to every stage (head/loss run replicated)
        out_stream = tmap(
            lambda os: jax.lax.psum(jnp.where(stage == n_stages - 1, os, jnp.zeros_like(os)),
                                    dist.PIPE_AXIS), out_stream)
        if with_aux:
            return out_stream, jax.lax.psum(aux_total, dist.PIPE_AXIS)
        return out_stream

    in_specs = (jax.tree_util.tree_map(lambda _: P(dist.PIPE_AXIS), stage_params),
                jax.tree_util.tree_map(lambda _: P(), x_stream))
    out_specs = jax.tree_util.tree_map(lambda _: P(), x_stream)
    if with_aux:
        out_specs = (out_specs, P())
    with dist.manual_axes({dist.PIPE_AXIS}):
        return jax.shard_map(run, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs,
                             axis_names={dist.PIPE_AXIS})(stage_params, x_stream)


def _single_stage(stage_fn, stage_params, x_stream, remat, with_aux=False):
    fn = jax.checkpoint(stage_fn, static_argnums=()) if remat else stage_fn
    M = jax.tree_util.tree_leaves(x_stream)[0].shape[0]

    def one(x_and_t):
        x, t = x_and_t
        return fn(stage_params, x, t)

    out = jax.lax.map(one, (x_stream, jnp.arange(M)))
    if with_aux:
        stream, aux = out
        return stream, jnp.sum(aux.astype(jnp.float32))
    return out
