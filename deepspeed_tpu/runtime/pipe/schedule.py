"""SPMD pipeline schedule.

TPU-native replacement for the reference's pipeline instruction interpreter
(``runtime/pipe/engine.py:40`` ``PipelineEngine``, ``schedule.py:189``
``TrainSchedule`` 1F1B, ``p2p.py`` wire). Design translation (SURVEY §7):
instead of N processes interpreting per-rank instruction streams and
exchanging tensors over NCCL P2P, ONE compiled program runs a circular
pipeline inside ``jax.shard_map`` that is *manual only over the* ``pipe``
*axis* — activations move between stages with ``lax.ppermute`` over ICI
neighbors while the other mesh axes (data/tensor/expert/seq) stay under the
automatic SPMD partitioner. Backward is just ``jax.grad`` through the scan:
``ppermute`` differentiates to the reverse permute, which reproduces the
backward P2P exchange of the reference schedule without an interpreter.

Schedule shape: with M microbatches and S stages, the scan runs M+S-1 steps;
stage s works on microbatch t-s at step t (classic fill/drain pipeline).
The reference's 1F1B ordering is an eager-mode *memory* optimization; under
XLA the whole program is compiled and activation liveness is bounded by
rematerialization instead (pass ``remat_policy``).
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...comm import comm as dist


def num_pipeline_steps(num_microbatches, num_stages):
    return num_microbatches + num_stages - 1


def _pvary(v, axes):
    """``jax.lax.pvary`` (the >=0.6 varying-manual-axes annotation) or
    identity on jax 0.4.x, whose shard_map tracks no vma types — the
    annotation exists only for the new API's replication checker."""
    pv = getattr(jax.lax, "pvary", None)
    return pv(v, axes) if pv is not None else v


def _vma(v):
    """The value's varying-manual-axes set (empty on jax 0.4.x, which has
    neither ``jax.typeof`` nor vma tracking — every pvary is then identity,
    so 'not yet varying' is always the right answer)."""
    tf = getattr(jax, "typeof", None)
    return getattr(tf(v), "vma", frozenset()) if tf is not None else frozenset()


def _pipe_shard_map(fn, mesh, in_specs, out_specs, grad_through):
    """Manual-over-``pipe`` shard_map spanning the jax API move.

    jax >= 0.6: ``jax.shard_map(axis_names={pipe})`` — manual over the pipe
    axis, every other mesh axis stays under the automatic partitioner
    (UNCHANGED from the call these schedules always made; the chip rounds
    validated it).

    jax 0.4.x has no ``jax.shard_map``, and its
    ``jax.experimental.shard_map`` partial-auto mode is unimplemented for
    scan/ppermute bodies (the PR 10 note). FULL-manual is an exact
    substitute in two cases:

    - every non-pipe mesh axis has size 1 (unmentioned spec axes replicate;
      psum/transpose over a size-1 axis is identity), or
    - the caller never differentiates THROUGH the shard_map
      (``grad_through=False`` — the 1F1B schedule computes its grads
      INSIDE and returns them as plain outputs, so the replicated-input
      transpose rule that would scale cotangents by the unmentioned axis
      sizes is never exercised; forward values are genuinely replicated
      over non-pipe axes, so ``P()`` outputs are exact).

    Differentiating through a full-manual region with a >1 auto axis WOULD
    silently scale ``P()``-input cotangents by that axis size (the
    check_rep=False transpose psums over every manual axis), so that mix
    raises a structured NotImplementedError instead — callers (the
    multichip dryrun) skip the leg with the reason rather than training on
    wrong gradients."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs,
                             axis_names={dist.PIPE_AXIS})
    from jax.experimental.shard_map import shard_map as _sm
    other = [ax for ax in mesh.axis_names
             if ax != dist.PIPE_AXIS and mesh.shape[ax] > 1]
    if grad_through and other:
        raise NotImplementedError(
            f"fill-drain pipeline backward needs partial-manual shard_map "
            f"(manual over '{dist.PIPE_AXIS}', auto over {other}); jax "
            f"{jax.__version__} has neither jax.shard_map nor a working "
            f"partial-auto jax.experimental.shard_map for scan/ppermute "
            f"bodies, and the full-manual fallback would mis-scale "
            f"replicated-input gradients by the {other} axis sizes — use a "
            f"pipe-only (x size-1) mesh on this jax, or jax >= 0.6")
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def spmd_pipeline(stage_fn, stage_params, x_stream, mesh=None, remat=False, with_aux=False):
    """Run ``x_stream`` through a ``pipe``-partitioned layer stack.

    ``stage_fn(local_params, x, t) -> y`` (or ``(y, aux)`` with
    ``with_aux=True``): applies one stage's layer slice at pipeline step
    ``t`` (an i32 scalar; use it to decorrelate per-step rngs); ``x``/``y``
    may be pytrees — non-activation leaves (e.g. an attention mask) ride
    along with their microbatch through every stage; ``stage_params``:
    pytree whose leaves have leading layer dim divisible by the ``pipe``
    axis size (sharded dim 0 across stages); ``x_stream``: pytree of
    (M, ...) microbatch streams entering stage 0.

    Returns the stream leaving the last stage, replicated over pipe; with
    ``with_aux`` also a scalar: the sum of ``aux`` over every VALID
    (stage, microbatch) tick, psum'd across stages — fill/drain ticks
    compute on garbage activations and are masked out. This is how
    per-stage side losses (MoE load-balancing aux, reference
    ``engine.py:2880`` composes MoE under PP) survive the pipeline.
    """
    mesh = mesh or dist.get_mesh()
    n_stages = mesh.shape[dist.PIPE_AXIS]
    if n_stages == 1:
        return _single_stage(stage_fn, stage_params, x_stream, remat, with_aux)
    M = jax.tree_util.tree_leaves(x_stream)[0].shape[0]
    steps = num_pipeline_steps(M, n_stages)
    fn = jax.checkpoint(stage_fn, static_argnums=()) if remat else stage_fn

    def tmap(f, *trees):
        return jax.tree_util.tree_map(f, *trees)

    def run(local_params, xs):
        stage = jax.lax.axis_index(dist.PIPE_AXIS)
        # carries become stage-varying inside the loop; mark them so upfront
        pvary = lambda v: _pvary(v, (dist.PIPE_AXIS, ))
        state = tmap(lambda x: pvary(jnp.zeros_like(x[0])), xs)
        out_stream = tmap(lambda x: pvary(jnp.zeros_like(x)), xs)
        aux_total = pvary(jnp.zeros((), jnp.float32))
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def step(carry, t):
            state, out_stream, aux_total = carry
            feed = tmap(lambda x: jax.lax.dynamic_index_in_dim(x, jnp.minimum(t, M - 1), 0,
                                                               keepdims=False), xs)
            cur = tmap(lambda f, s: jnp.where(stage == 0, f, s), feed, state)
            out = fn(local_params, cur, t)
            y, aux = out if with_aux else (out, None)
            if with_aux:
                # stage s holds microbatch t-s; outside [0, M) it's fill/drain
                mb = t - stage
                valid = (mb >= 0) & (mb < M)
                aux_total = aux_total + jnp.where(valid, aux.astype(jnp.float32), 0.0)
            nxt = tmap(lambda v: jax.lax.ppermute(v, dist.PIPE_AXIS, perm), y)
            out_idx = t - (n_stages - 1)
            write = (stage == n_stages - 1) & (out_idx >= 0)
            out_stream = tmap(
                lambda os, v: jnp.where(
                    write, jax.lax.dynamic_update_index_in_dim(os, v, jnp.maximum(out_idx, 0), 0),
                    os), out_stream, y)
            return (nxt, out_stream, aux_total), None

        (_, out_stream, aux_total), _ = jax.lax.scan(
            step, (state, out_stream, aux_total), jnp.arange(steps))
        # deliver the last stage's stream to every stage (head/loss run replicated)
        out_stream = tmap(
            lambda os: jax.lax.psum(jnp.where(stage == n_stages - 1, os, jnp.zeros_like(os)),
                                    dist.PIPE_AXIS), out_stream)
        if with_aux:
            return out_stream, jax.lax.psum(aux_total, dist.PIPE_AXIS)
        return out_stream

    in_specs = (jax.tree_util.tree_map(lambda _: P(dist.PIPE_AXIS), stage_params),
                jax.tree_util.tree_map(lambda _: P(), x_stream))
    out_specs = jax.tree_util.tree_map(lambda _: P(), x_stream)
    if with_aux:
        out_specs = (out_specs, P())
    with dist.manual_axes({dist.PIPE_AXIS}):
        # grad_through: the engine differentiates jax.grad-style THROUGH
        # this call (backward is the transposed scan/ppermute)
        return _pipe_shard_map(run, mesh, in_specs, out_specs,
                               grad_through=True)(stage_params, x_stream)


def spmd_pipeline_1f1b(stage_fn, loss_head, stage_params, head_params, x_stream,
                       mesh=None, loss_denom=None):
    """One-pass interleaved 1F1B (reference ``TrainSchedule``,
    ``pipe/schedule.py:189``): every tick runs one (masked) forward micro-step
    AND one (masked) backward micro-step, so a stage holds at most
    ``2*(S-1-s)+1`` in-flight activations instead of all M — the 1F1B memory
    bound, here enforced by a ring buffer of stored stage INPUTS whose
    backward rematerializes the stage (activation-checkpoint style, the same
    recompute jax.grad-through-scan performs for the fill-drain schedule).

    ``stage_fn(local_params, x, t) -> y`` — fill-drain contract;
    ``loss_head(head_params, y, m) -> scalar`` — microbatch ``m``'s RAW loss
    contribution (e.g. summed token CE), evaluated at the last stage the
    moment its forward finishes — that is what lets backward start
    immediately (the 1F1B property). ``loss_denom``: global normalizer (e.g.
    total valid-token count) the SCHEDULE divides by, so summing microbatch
    contributions reproduces the fill-drain mean — callers cannot
    mis-normalize (pass None only if loss_head already returns its share of
    the final mean).

    Returns ``(loss, stage_grads, head_grads, dx_stream)``: total loss;
    gradients of the pipe-sharded stage params (same layout as
    ``stage_params``); head gradients (replicated; zero except the last
    stage's contribution, psum'd); and the gradient w.r.t. ``x_stream`` for
    the caller's embedding backward.
    """
    if loss_denom is not None:
        raw_head = loss_head
        loss_head = lambda hp, y, m: raw_head(hp, y, m) / loss_denom
    mesh = mesh or dist.get_mesh()
    n = mesh.shape[dist.PIPE_AXIS]
    M = jax.tree_util.tree_leaves(x_stream)[0].shape[0]
    if n == 1:
        return _single_stage_1f1b(stage_fn, loss_head, stage_params, head_params, x_stream)
    R = min(M, 2 * (n - 1) + 1)  # ring slots (worst-case in-flight at stage 0)
    T = M + 2 * (n - 1)

    def tmap(f, *trees):
        return jax.tree_util.tree_map(f, *trees)

    def run(local_params, head_p, xs):
        stage = jax.lax.axis_index(dist.PIPE_AXIS)

        def pvary(v):
            # idempotent invariant->varying promotion (stage params arrive
            # already pipe-varying; the replicated streams do not)
            return (v if dist.PIPE_AXIS in _vma(v)
                    else _pvary(v, (dist.PIPE_AXIS, )))

        # head params MUST be promoted to pipe-varying before value_and_grad:
        # differentiating a varying loss w.r.t. an INVARIANT input makes
        # shard_map's transpose psum the cotangent across stages, polluting
        # the last stage's head grad with every other stage's masked-out
        # garbage ticks (the loss VALUE is unaffected — only grads)
        head_p = tmap(pvary, head_p)
        zero_x = tmap(lambda x: pvary(jnp.zeros_like(x[0])), xs)
        ring = tmap(lambda x: pvary(jnp.zeros((R, ) + x.shape[1:], x.dtype)), xs)
        carry = {
            "fwd_in": zero_x,  # activation arriving from stage-1
            "bwd_in": tmap(lambda x: jnp.zeros_like(x), zero_x),  # dy from stage+1
            "ring": ring,
            "dstage": tmap(lambda p: pvary(jnp.zeros_like(p)), local_params),
            "dhead": tmap(lambda p: pvary(jnp.zeros_like(p)), head_p),
            "dxs": tmap(lambda x: pvary(jnp.zeros_like(x)), xs),
            "loss": pvary(jnp.zeros((), jnp.float32)),
        }
        fwd_perm = [(i, (i + 1) % n) for i in range(n)]
        bwd_perm = [(i, (i - 1) % n) for i in range(n)]

        def tick(c, t):
            f = t - stage
            b = t - 2 * (n - 1) + stage
            f_ok = (f >= 0) & (f < M)
            b_ok = (b >= 0) & (b < M)
            f_idx = jnp.clip(f, 0, M - 1)
            b_idx = jnp.clip(b, 0, M - 1)

            # ---- forward half: mb f through this stage ----
            x_in = tmap(lambda x, s: jnp.where(stage == 0,
                                               jax.lax.dynamic_index_in_dim(x, f_idx, 0,
                                                                            keepdims=False), s),
                        xs, c["fwd_in"])
            y = stage_fn(local_params, x_in, t)
            # last stage: this microbatch's loss + dy, fed to backward NOW
            (loss_f, (dhead_f, dy_self)) = jax.value_and_grad(
                lambda hp, yy: loss_head(hp, yy, f_idx), argnums=(0, 1))(head_p, y)
            is_last = stage == n - 1
            take_loss = f_ok & is_last
            c_loss = c["loss"] + jnp.where(take_loss, loss_f, 0.0)
            c_dhead = tmap(lambda a, g: a + jnp.where(take_loss, g, jnp.zeros_like(g)),
                           c["dhead"], dhead_f)
            # store this stage's INPUT for the recompute at backward time
            slot_w = jnp.mod(f_idx, R)
            c_ring = tmap(lambda r, v: jnp.where(
                f_ok, jax.lax.dynamic_update_index_in_dim(r, v, slot_w, 0), r),
                c["ring"], x_in)

            # ---- backward half: mb b (rematerialized from the ring) ----
            x_b = tmap(lambda r: jax.lax.dynamic_index_in_dim(r, jnp.mod(b_idx, R), 0,
                                                              keepdims=False), c_ring)
            t_b = b_idx + stage  # the tick mb b was forwarded at this stage
            _, vjp = jax.vjp(lambda p, x: stage_fn(p, x, t_b), local_params, x_b)
            dy = jnp.where(is_last, dy_self, c["bwd_in"])
            dp, dx = vjp(dy)
            c_dstage = tmap(lambda a, g: a + jnp.where(b_ok, g, jnp.zeros_like(g)),
                            c["dstage"], dp)
            # stage 0: dx is the embedding-output gradient for mb b
            c_dxs = tmap(lambda acc, g: jnp.where(
                b_ok & (stage == 0),
                jax.lax.dynamic_update_index_in_dim(acc, g, b_idx, 0), acc),
                c["dxs"], dx)

            # ---- wire: activations forward, grads backward ----
            fwd_in = jax.lax.ppermute(y, dist.PIPE_AXIS, fwd_perm)
            bwd_in = jax.lax.ppermute(dx, dist.PIPE_AXIS, bwd_perm)
            return {"fwd_in": fwd_in, "bwd_in": bwd_in, "ring": c_ring,
                    "dstage": c_dstage, "dhead": c_dhead, "dxs": c_dxs,
                    "loss": c_loss}, None

        c, _ = jax.lax.scan(tick, carry, jnp.arange(T))
        sel_last = lambda v: jax.lax.psum(jnp.where(stage == n - 1, v, jnp.zeros_like(v)),
                                          dist.PIPE_AXIS)
        sel_first = lambda v: jax.lax.psum(jnp.where(stage == 0, v, jnp.zeros_like(v)),
                                           dist.PIPE_AXIS)
        loss = sel_last(c["loss"])
        dhead = tmap(sel_last, c["dhead"])
        dxs = tmap(sel_first, c["dxs"])
        return loss, c["dstage"], dhead, dxs

    in_specs = (jax.tree_util.tree_map(lambda _: P(dist.PIPE_AXIS), stage_params),
                jax.tree_util.tree_map(lambda _: P(), head_params),
                jax.tree_util.tree_map(lambda _: P(), x_stream))
    out_specs = (P(),
                 jax.tree_util.tree_map(lambda _: P(dist.PIPE_AXIS), stage_params),
                 jax.tree_util.tree_map(lambda _: P(), head_params),
                 jax.tree_util.tree_map(lambda _: P(), x_stream))
    with dist.manual_axes({dist.PIPE_AXIS}):
        # 1F1B computes loss AND grads inside the region and returns them
        # as plain outputs — nothing transposes through the shard_map, so
        # the full-manual jax 0.4.x fallback is exact on any mesh
        return _pipe_shard_map(run, mesh, in_specs, out_specs,
                               grad_through=False)(stage_params, head_params,
                                                   x_stream)


def _single_stage_1f1b(stage_fn, loss_head, stage_params, head_params, x_stream):
    """n=1 degenerate case: per-microbatch fwd+loss+bwd, accumulated."""
    M = jax.tree_util.tree_leaves(x_stream)[0].shape[0]

    def one(m, acc):
        dstage, dhead, dxs, loss = acc
        x = jax.tree_util.tree_map(
            lambda v: jax.lax.dynamic_index_in_dim(v, m, 0, keepdims=False), x_stream)

        def f(p, hp, x):
            y = stage_fn(p, x, m)
            y = y[0] if isinstance(y, tuple) else y
            return loss_head(hp, y, m)

        l, (dp, dh, dx) = jax.value_and_grad(f, argnums=(0, 1, 2))(stage_params,
                                                                   head_params, x)
        add = lambda a, g: jax.tree_util.tree_map(jnp.add, a, g)
        dxs = jax.tree_util.tree_map(
            lambda acc_, g: jax.lax.dynamic_update_index_in_dim(acc_, g, m, 0), dxs, dx)
        return add(dstage, dp), add(dhead, dh), dxs, loss + l

    zeros = lambda t: jax.tree_util.tree_map(jnp.zeros_like, t)
    acc = (zeros(stage_params), zeros(head_params), zeros(x_stream),
           jnp.zeros((), jnp.float32))
    acc = jax.lax.fori_loop(0, M, lambda m, a: one(m, a), acc)
    dstage, dhead, dxs, loss = acc
    return loss, dstage, dhead, dxs


def _single_stage(stage_fn, stage_params, x_stream, remat, with_aux=False):
    fn = jax.checkpoint(stage_fn, static_argnums=()) if remat else stage_fn
    M = jax.tree_util.tree_leaves(x_stream)[0].shape[0]

    def one(x_and_t):
        x, t = x_and_t
        return fn(stage_params, x, t)

    out = jax.lax.map(one, (x_stream, jnp.arange(M)))
    if with_aux:
        stream, aux = out
        return stream, jnp.sum(aux.astype(jnp.float32))
    return out
