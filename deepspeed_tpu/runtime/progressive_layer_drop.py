"""Progressive layer drop (PLD).

Analogue of reference ``deepspeed/runtime/progressive_layer_drop.py``: the
keep-probability schedule theta(t) = (1 - theta_bar) * gamma^t ... in the
reference's form ``theta(t) = theta_bar + (1 - theta_bar) * exp(-gamma t)``
applied as stochastic depth across transformer blocks. The engine advances
the schedule each global step and models consume ``pld_theta`` as the
per-layer keep probability (``CausalLM`` applies it inside the layer scan
with a per-(step, layer) folded rng).
"""


class ProgressiveLayerDrop:

    def __init__(self, theta=0.5, gamma=0.001):
        self.theta = float(theta)  # asymptotic keep probability
        self.gamma = float(gamma)
        self.current_theta = 1.0
        from ..utils.logging import log_dist
        log_dist(f"Enabled progressive layer dropping (theta = {self.theta})", [0])

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def get_theta(self):
        return self.current_theta

    def update_state(self, global_step):
        import math
        self.current_theta = (1.0 - self.theta) * math.exp(-self.gamma * global_step) + self.theta
