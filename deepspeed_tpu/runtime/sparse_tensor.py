"""SparseTensor: (indices, values) gradient representation.

Counterpart of reference ``runtime/sparse_tensor.py`` (``SparseTensor`` :12,
wrapping torch sparse grads for the ``sparse_gradients`` allreduce path).
On TPU, XLA produces *dense* embedding gradients (scatter-add fused into the
backward), so sparsity is not free at the autodiff layer; this class instead
provides the row-sparse container + conversions, and
``sparse_allreduce`` exchanges only the nonzero rows over the mesh — the
bandwidth win the reference's sparse allreduce targets, expressed as a
gather-of-rows collective (``comm.all_gather``) instead of NCCL v2v.
"""

import numpy as np

import jax
import jax.numpy as jnp

from ..comm import comm as dist


class SparseTensor:
    """Row-sparse view of a 2-D tensor: ``indices`` (n,) int32 row ids,
    ``values`` (n, cols). Mirrors the reference's attribute surface
    (indices/values/dense_size, to_dense, sparse_size)."""

    def __init__(self, indices, values, dense_size):
        self.indices = jnp.asarray(indices, jnp.int32)
        self.values = jnp.asarray(values)
        self.dense_size = tuple(dense_size)

    @classmethod
    def from_dense(cls, x, threshold=0.0):
        """Rows with any |value| > threshold become the sparse payload.
        Host-side (numpy) selection: row count is data-dependent, which jit
        cannot express — this path is for the host gradient-exchange tier."""
        arr = np.asarray(x)
        mask = np.abs(arr).max(axis=tuple(range(1, arr.ndim))) > threshold
        idx = np.nonzero(mask)[0]
        return cls(idx, arr[idx], arr.shape)

    def to_dense(self):
        out = jnp.zeros(self.dense_size, self.values.dtype)
        return out.at[self.indices].add(self.values)

    def sparse_size(self):
        """(payload elements, dense elements) — reference returns the pair
        for its compression-ratio logging."""
        dense = int(np.prod(self.dense_size))
        return int(np.prod(self.values.shape)) + int(self.indices.size), dense

    def type(self):
        return "deepspeed_tpu.SparseTensor"


def sparse_allreduce(sp, axis_name):
    """All-reduce a row-sparse gradient inside shard_map: all-gather each
    shard's (indices, values) and scatter-add into the dense result. Correct
    for duplicate rows across shards (contributions sum, as in the
    reference's sparse allreduce for embedding grads)."""
    all_idx = dist.all_gather(sp.indices, axis_name)  # (world*n,)
    all_val = dist.all_gather(sp.values, axis_name)  # (world*n, cols)
    out = jnp.zeros(sp.dense_size, sp.values.dtype)
    return out.at[all_idx.reshape(-1)].add(all_val.reshape((-1, ) + sp.dense_size[1:]))
