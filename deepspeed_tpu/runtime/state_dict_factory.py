"""State-dict loaders for tensor-parallel checkpoint families.

Counterpart of reference ``runtime/state_dict_factory.py`` (``SDLoaderFactory``
:21, ``MegatronSDLoader`` :190): inference checkpoints sharded over N model-
parallel ranks must be *merged* when serving with fewer ranks, or *split*
when serving with more. The reference re-slices torch tensors per rank; here
the merge target is one logical (host numpy) state dict — the sharding onto
the serving mesh is then a PartitionSpec concern, so only the merge direction
needs real tensor surgery, and "split" is layout metadata (a key difference
called out in the docstring so users porting split-configs aren't surprised).

Megatron conventions handled (same rules as the reference's merge):
- column-parallel weights (qkv ``attention.query_key_value``, MLP
  ``dense_h_to_4h``): concatenate along the output dim (0 in torch (out,in)).
- row-parallel weights (``attention.dense``, ``mlp.dense_4h_to_h``):
  concatenate along the input dim (1).
- embeddings (``word_embeddings``, ``lm_head``): concatenate along vocab (0).
- replicated (norms, biases of row-parallel, positional embeddings): take
  rank 0, verify equality.
"""

import json
import os

import numpy as np

from ..utils.logging import logger

_COLUMN_CAT0 = ("dense_h_to_4h.weight", "dense_h_to_4h.bias",
                "word_embeddings.weight", "lm_head.weight")
_ROW_CAT1 = ("attention.dense.weight", "mlp.dense_4h_to_h.weight", "out_proj.weight")


class SDLoaderFactory:

    @staticmethod
    def get_sd_loader_json(json_file, checkpoint_engine=None):
        """Reference API: a 'ds_inference' checkpoint description json with
        {"type": ..., "checkpoints": [...], "version": ...}."""
        if isinstance(json_file, dict):
            data = json_file
        else:
            with open(json_file) as f:
                data = json.load(f)
        sd_type = data["type"]
        ckpt_list = data["checkpoints"]
        version = data.get("version")
        base_dir = data.get("base_dir", "")
        if base_dir:
            ckpt_list = [os.path.join(base_dir, c) for c in ckpt_list]
        return SDLoaderFactory.get_sd_loader(ckpt_list, sd_type=sd_type, version=version)

    @staticmethod
    def get_sd_loader(ckpt_list, checkpoint_engine=None, sd_type="Megatron", version=None):
        if sd_type.lower() in ("megatron", "ds_model", "bloom"):
            return MegatronSDLoader(ckpt_list, version)
        raise ValueError(f"unsupported checkpoint type {sd_type!r}")


class SDLoaderBase:

    def __init__(self, ckpt_list, version=None):
        self.ckpt_list = list(ckpt_list)
        self.version = version

    def _load_one(self, path):
        import torch
        sd = torch.load(path, map_location="cpu", weights_only=False)
        for key in ("module", "model"):
            if key in sd:
                sd = sd[key]
                break
        return {k: (v.detach().float().numpy() if hasattr(v, "detach") else np.asarray(v))
                for k, v in sd.items() if hasattr(v, "shape")}

    def load(self, mp_world_size=1, mp_rank=0):
        """Return the merged logical state dict for serving. The reference
        signature returns per-rank slices; here merging to the logical dict
        is the whole job (rank placement is a PartitionSpec downstream)."""
        if not 0 <= mp_rank < mp_world_size:
            raise ValueError(f"mp_rank {mp_rank} out of range for mp_world_size {mp_world_size}")
        n = len(self.ckpt_list)
        if n == 1:
            return self._load_one(self.ckpt_list[0])
        sds = [self._load_one(p) for p in self.ckpt_list]
        return self.merge_state_dicts(sds)

    def merge_state_dicts(self, sds):
        raise NotImplementedError


class MegatronSDLoader(SDLoaderBase):

    def _merge_qkv(self, parts):
        """Version-dependent fused-QKV merge (reference
        ``merge_query_key_value``): version 0 stores [q;k;v] blocked per rank
        — components must be regrouped across ranks; versions 1.0/2.0 store
        head-major layouts where plain rank concatenation is correct."""
        ver = 1.0 if self.version is None else self.version
        if ver == 0:
            if parts[0].shape[0] % 3 != 0:
                raise ValueError(f"v0 fused qkv dim {parts[0].shape[0]} not divisible by 3")
            thirds = [np.split(p, 3, axis=0) for p in parts]
            return np.concatenate([np.concatenate([t[i] for t in thirds], axis=0)
                                   for i in range(3)], axis=0)
        if ver in (1.0, 2.0):
            return np.concatenate(parts, axis=0)
        raise ValueError(f"unsupported Megatron checkpoint version {ver}")

    def merge_state_dicts(self, sds):
        keys = set(sds[0])
        for sd in sds[1:]:
            if set(sd) != keys:
                diff = keys.symmetric_difference(sd)
                raise ValueError(f"mp-rank checkpoints disagree on parameter names: {sorted(diff)[:5]}")
        out = {}
        for k in sds[0]:
            parts = [sd[k] for sd in sds]
            if "query_key_value" in k:
                out[k] = self._merge_qkv(parts)
            elif any(k.endswith(s) for s in _COLUMN_CAT0):
                out[k] = np.concatenate(parts, axis=0)
            elif any(s in k for s in _ROW_CAT1):
                out[k] = np.concatenate(parts, axis=1)
            elif parts[0].ndim == 0 or all(np.array_equal(parts[0], p) for p in parts[1:]):
                out[k] = parts[0]  # replicated
            else:
                raise ValueError(
                    f"MegatronSDLoader: key {k!r} differs across mp ranks but matches no "
                    f"known partitioning rule; extend _COLUMN_CAT0/_ROW_CAT1 for this model")
        return out
