from .aio_config import get_aio_config  # noqa: F401
from .optimizer_swapper import NVMeOffloadOptimizer  # noqa: F401
from .read_window import AioReadWindow  # noqa: F401
