"""``aio`` config section (reference ``runtime/swap_tensor/aio_config.py`` /
``constants.py``: AIO_BLOCK_SIZE .. AIO_OVERLAP_EVENTS — same keys, same
defaults).

``block_size``, ``thread_count`` and ``single_submit`` drive the native pool
directly. ``queue_depth`` and ``overlap_events`` are accepted for config
parity but advisory here: the pthread pool's request queue is unbounded and
read/write overlap comes from the dual read/write handles, not from a
libaio-style event window."""

AIO_BLOCK_SIZE = "block_size"
AIO_QUEUE_DEPTH = "queue_depth"
AIO_THREAD_COUNT = "thread_count"
AIO_SINGLE_SUBMIT = "single_submit"
AIO_OVERLAP_EVENTS = "overlap_events"

AIO_DEFAULTS = {
    AIO_BLOCK_SIZE: 1048576,
    AIO_QUEUE_DEPTH: 8,
    AIO_THREAD_COUNT: 1,
    AIO_SINGLE_SUBMIT: False,
    AIO_OVERLAP_EVENTS: True,
}


def get_aio_config(param_dict):
    """Merge the user ``aio`` section over reference defaults; unknown keys
    are rejected so config typos fail loudly."""
    user = dict(param_dict.get("aio") or {})
    unknown = set(user) - set(AIO_DEFAULTS)
    if unknown:
        raise ValueError(f"aio config: unknown keys {sorted(unknown)}; "
                         f"valid: {sorted(AIO_DEFAULTS)}")
    cfg = dict(AIO_DEFAULTS)
    cfg.update(user)
    return cfg
