"""ZeRO-Infinity optimizer tier: fp32 master + Adam moments on NVMe,
partitioned across DP ranks/hosts.

Counterpart of the reference's ``partitioned_optimizer_swapper.py:40`` /
``pipelined_optimizer_swapper.py:164`` + the libaio engine. Host DRAM holds
only a small rotating window of blocks; everything else lives in three flat
files per owned block (master/m/v) under ``nvme_path``. Each host owns only
the blocks its devices hold in the offload sharding (reference
``stage3.py:463 _configure_tensor_swapping`` swaps per-rank subgroups), so
NVMe capacity scales with the number of feeding hosts. The step pipeline is

    read[i+1] in flight  |  C AdamW on block i  |  write[i-1] in flight

using two AsyncIOHandle pools (reads / writes) so a block's write-back
overlaps the next block's read AND the compute — the reference's
"pipelined read/write" mode (``pipeline_read``/``pipeline_write``).

DRAM high-water mark is O(3 largest-block buffers x 2) + the transient bf16
compute copy, independent of model size — how a model whose optimizer state
exceeds both HBM *and* host DRAM still steps (ZeRO-Infinity's pitch,
reference blog "10x bigger models").
"""

import os

import numpy as np

import jax

from ...ops.aio import AsyncIOHandle, aligned_empty
from ...utils.logging import log_dist
from ..zero.offload import HostOffloadOptimizer, _TRANSFER_POOL


class NVMeOffloadOptimizer(HostOffloadOptimizer):
    """Drop-in for HostOffloadOptimizer with NVMe-resident block state."""

    def __init__(self, optimizer_config, lr_schedule_fn, nvme_path, aio_config=None,
                 pipeline_read=True, pipeline_write=True):
        super().__init__(optimizer_config, lr_schedule_fn)
        from .aio_config import get_aio_config
        aio = aio_config if aio_config is not None else get_aio_config({})
        # two pools so write-back of block i-1 overlaps the read of block i+1;
        # per-pool threads double the configured count for the same reason
        # the reference's overlap_events mode uses separate submit/complete
        # threads
        handle_kw = dict(block_size=aio["block_size"], queue_depth=aio["queue_depth"],
                         single_submit=aio["single_submit"], overlap_events=aio["overlap_events"],
                         thread_count=max(1, aio["thread_count"]) * 2)
        # rank-scoped so hosts sharing one NVMe namespace never collide
        self.swap_dir = os.path.join(nvme_path,
                                     f"zero_stage_opt_swap_rank{jax.process_index():05d}")
        os.makedirs(self.swap_dir, exist_ok=True)
        self._read_h = AsyncIOHandle(**handle_kw)
        self._write_h = AsyncIOHandle(**handle_kw)
        self.pipeline_read = pipeline_read
        self.pipeline_write = pipeline_write
        self._out = None  # transient compute-dtype leaves produced by step()
        self.compute_dtype = None  # set by the engine before the first step

    def _paths(self, i):
        return {kind: os.path.join(self.swap_dir, f"blk{i:05d}.{kind}")
                for kind in ("master", "m", "v")}

    # -- state lifecycle -------------------------------------------------
    def init_from_device(self, params_off):
        self._record_layout(params_off)
        pairs = self._discover_blocks(jax.tree_util.tree_leaves(params_off))
        window = 0
        zeros = np.zeros(max(blk.size for blk, _ in pairs), np.float32)
        for i, (blk, data) in enumerate(pairs):
            host = np.array(jax.device_get(data), np.float32, copy=True).reshape(-1)
            paths = self._paths(i)
            self._write_h.async_pwrite(host, paths["master"])  # keepalive pins host
            for kind in ("m", "v"):
                self._write_h.async_pwrite(zeros[:host.size], paths[kind])
            window += 1
            if window >= 4:  # bound pinned DRAM to a few blocks, keep IO deep
                self._write_h.wait()
                window = 0
        self._write_h.wait()
        # master/m/v intentionally stay None: all access goes through files
        total = self.num_params()
        log_dist(f"ZeRO-Infinity: {total:,} params' optimizer state on NVMe "
                 f"({3 * total * 4 / 2**30:.2f} GiB under {self.swap_dir}, this host's "
                 f"partition)", ranks=[0])

    # -- the pipelined step ----------------------------------------------
    def _read_block(self, i):
        blk = self.blocks[i]
        paths = self._paths(i)
        bufs = {kind: aligned_empty((blk.size, ), np.float32) for kind in ("master", "m", "v")}
        for kind, buf in bufs.items():
            self._read_h.async_pread(buf, paths[kind])
        if not self.pipeline_read:
            self._read_h.wait()
        return bufs

    def step(self, grad_blocks, grad_coef, lr):
        self.t += 1
        assert len(grad_blocks) == len(self.blocks), "grad blocks do not match optimizer state"
        self._out = [None] * len(self.blocks)

        pending_write = None  # bufs kept alive until their write completes
        nxt = self._read_block(0)
        for i, blk in enumerate(self.blocks):
            bufs = nxt
            self._read_h.wait()  # block i resident
            if i + 1 < len(self.blocks):
                nxt = self._read_block(i + 1)  # overlap next read
            g = np.asarray(grad_blocks[i]).reshape(-1)
            self.opt.step(bufs["master"], bufs["m"], bufs["v"], g, self.t,
                          lr=lr, grad_coef=grad_coef)
            self._out[i] = self._cast(bufs["master"], self.compute_dtype).reshape(blk.shape)
            if pending_write is not None:
                self._write_h.wait()
            paths = self._paths(i)
            for kind in ("master", "m", "v"):
                self._write_h.async_pwrite(bufs[kind], paths[kind])
            if not self.pipeline_write:
                self._write_h.wait()
                pending_write = None
            else:
                pending_write = bufs
        self._write_h.wait()

    def _block_data(self, kind, i):
        """Serial file read of one owned block (debug/full-leaf accessors;
        must run on the caller thread — the AIO handles are not re-entrant)."""
        blk = self.blocks[i]
        buf = aligned_empty((blk.size, ), np.float32)
        self._read_h.async_pread(buf, self._paths(i)[kind])
        self._read_h.wait()
        return buf

    def _block_out(self, i, compute_dtype):
        return self._out[i]

    def compute_params(self, compute_dtype, shardings):
        if self._out is None:
            # checkpoint restore: materialize the compute blocks SERIALLY
            # before the (thread-pooled) assembly — the AIO handles are not
            # safe to drive from multiple _TRANSFER_POOL threads
            self._out = [self._cast(self._block_data("master", i),
                                    compute_dtype).reshape(blk.shape)
                         for i, blk in enumerate(self.blocks)]
        out = super().compute_params(compute_dtype, shardings)
        self._out = None  # free the transient window
        return out

    # -- checkpoint: stream blocks through the shared npz format ----------
    def _iter_state_blocks(self):
        for kind in ("master", "m", "v"):
            for i, blk in enumerate(self.blocks):
                buf = aligned_empty((blk.size, ), np.float32)
                self._read_h.async_pread(buf, self._paths(i)[kind])
                self._read_h.wait()
                yield kind, i, buf

    def save_to(self, tag_dir):
        self._write_h.wait()  # no in-flight writes while reading back
        super().save_to(tag_dir)

    def _set_block(self, kind, i, data):
        self._write_h.async_pwrite(np.ascontiguousarray(data, np.float32).reshape(-1),
                                   self._paths(i)[kind])
        self._write_h.wait()

    def reset_from_params(self, params, step):
        """Rewrite master files from (already-loaded) device params, zero
        moments — streamed per block like init_from_device."""
        import jax.numpy as jnp
        reshard = jax.jit(lambda t: jax.tree_util.tree_map(lambda x: x.astype(jnp.float32), t),
                          out_shardings=jax.tree_util.tree_unflatten(self._treedef,
                                                                     self._off_shardings))
        from ..zero.offload import _norm_index
        leaves = jax.tree_util.tree_leaves(reshard(params))
        by_key = {}
        for li, arr in enumerate(leaves):
            for shard in arr.addressable_shards:
                by_key.setdefault((li, _norm_index(shard.index, arr.shape)), shard.data)
        zeros = np.zeros(max(b.size for b in self.blocks), np.float32)
        for i, blk in enumerate(self.blocks):
            host = np.asarray(jax.device_get(by_key[(blk.leaf, blk.index)]),
                              np.float32).reshape(-1)
            paths = self._paths(i)
            self._write_h.async_pwrite(host, paths["master"])
            self._write_h.wait()  # host buffer reused next iteration
            for kind in ("m", "v"):
                self._write_h.async_pwrite(zeros[:blk.size], paths[kind])
            self._write_h.wait()
        self.t = step
