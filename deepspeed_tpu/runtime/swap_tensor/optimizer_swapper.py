"""ZeRO-Infinity optimizer tier: fp32 master + Adam moments on NVMe.

Counterpart of the reference's ``partitioned_optimizer_swapper.py:40`` /
``pipelined_optimizer_swapper.py:164`` + the libaio engine. Host DRAM holds
only a small rotating window of leaves; everything else lives in three flat
files per leaf (master/m/v) under ``nvme_path``. The step pipeline is

    read[i+1] in flight  |  C AdamW on leaf i  |  write[i-1] in flight

using two AsyncIOHandle pools (reads / writes) so a leaf's write-back
overlaps the next leaf's read AND the compute — the reference's
"pipelined read/write" mode (``pipeline_read``/``pipeline_write``).

DRAM high-water mark is O(3 largest-leaf buffers x 2) + the transient bf16
compute copy, independent of model size — how a model whose optimizer state
exceeds both HBM *and* host DRAM still steps (ZeRO-Infinity's pitch,
reference blog "10x bigger models").
"""

import os

import numpy as np

import jax

from ...ops.adam.cpu_adam import f32_to_bf16
from ...ops.aio import AsyncIOHandle
from ...utils.logging import log_dist
from ..zero.offload import HostOffloadOptimizer, _TRANSFER_POOL


class _LeafStore:
    """Three flat fp32 files per leaf under ``dir_``."""

    def __init__(self, dir_, index, shape):
        self.shape = shape
        self.paths = {kind: os.path.join(dir_, f"leaf{index:05d}.{kind}") for kind in ("master", "m", "v")}

    def nbytes(self):
        return int(np.prod(self.shape, dtype=np.int64)) * 4


class NVMeOffloadOptimizer(HostOffloadOptimizer):
    """Drop-in for HostOffloadOptimizer with NVMe-resident state."""

    def __init__(self, optimizer_config, lr_schedule_fn, nvme_path, aio_config=None,
                 pipeline_read=True, pipeline_write=True):
        super().__init__(optimizer_config, lr_schedule_fn)
        from .aio_config import get_aio_config
        aio = aio_config if aio_config is not None else get_aio_config({})
        # two pools so write-back of leaf i-1 overlaps the read of leaf i+1;
        # per-pool threads double the configured count for the same reason
        # the reference's overlap_events mode uses separate submit/complete
        # threads
        handle_kw = dict(block_size=aio["block_size"], queue_depth=aio["queue_depth"],
                         single_submit=aio["single_submit"], overlap_events=aio["overlap_events"],
                         thread_count=max(1, aio["thread_count"]) * 2)
        self.swap_dir = os.path.join(nvme_path, "zero_stage_opt_swap")
        os.makedirs(self.swap_dir, exist_ok=True)
        self._read_h = AsyncIOHandle(**handle_kw)
        self._write_h = AsyncIOHandle(**handle_kw)
        self.pipeline_read = pipeline_read
        self.pipeline_write = pipeline_write
        self._stores = None  # list[_LeafStore]
        self._treedef = None
        self._out = None  # transient compute-dtype leaves produced by step()
        self.compute_dtype = None  # set by the engine before the first step

    # -- state lifecycle -------------------------------------------------
    def init_from_device(self, params_f32):
        leaves, treedef = jax.tree_util.tree_flatten(params_f32)
        self._treedef = treedef
        self._stores = []
        zeros = np.zeros(max(int(np.prod(l.shape)) for l in leaves), np.float32)
        window = 0
        for i, leaf in enumerate(leaves):
            host = np.array(jax.device_get(leaf), dtype=np.float32, copy=True)
            store = _LeafStore(self.swap_dir, i, host.shape)
            self._write_h.async_pwrite(host, store.paths["master"])  # keepalive pins host
            for kind in ("m", "v"):
                self._write_h.async_pwrite(zeros[:host.size], store.paths[kind])
            self._stores.append(store)
            window += 1
            if window >= 4:  # bound pinned DRAM to a few leaves, keep IO deep
                self._write_h.wait()
                window = 0
        self._write_h.wait()
        total = sum(int(np.prod(s.shape)) for s in self._stores)
        log_dist(f"ZeRO-Infinity: {total:,} params' optimizer state on NVMe "
                 f"({3 * total * 4 / 2**30:.2f} GiB under {self.swap_dir})", ranks=[0])
        # master/m/v intentionally stay None: all access goes through files

    def num_params(self):
        return sum(int(np.prod(s.shape)) for s in self._stores)

    # -- the pipelined step ----------------------------------------------
    def _read_leaf(self, store):
        bufs = {kind: np.empty(int(np.prod(store.shape)), np.float32) for kind in ("master", "m", "v")}
        for kind, buf in bufs.items():
            self._read_h.async_pread(buf, store.paths[kind])
        if not self.pipeline_read:
            self._read_h.wait()
        return bufs

    def _cast_out(self, master_flat, shape):
        """Updated master -> one compute-dtype leaf (bf16 via the native
        round-to-nearest-even kernel; anything else via numpy astype)."""
        import ml_dtypes
        dt = np.dtype(self.compute_dtype) if self.compute_dtype is not None \
            else np.dtype(ml_dtypes.bfloat16)
        if dt == np.dtype(ml_dtypes.bfloat16):
            return f32_to_bf16(master_flat).reshape(shape)
        return master_flat.astype(dt).reshape(shape)

    def step(self, grads, grad_coef, lr):
        self.t += 1
        gleaves = jax.tree_util.tree_leaves(grads)
        assert len(gleaves) == len(self._stores), "grad tree does not match optimizer state"
        self._out = [None] * len(gleaves)

        pending_write = None  # bufs kept alive until their write completes
        nxt = self._read_leaf(self._stores[0])
        for i, store in enumerate(self._stores):
            bufs = nxt
            self._read_h.wait()  # leaf i resident
            if i + 1 < len(self._stores):
                nxt = self._read_leaf(self._stores[i + 1])  # overlap next read
            g = np.asarray(gleaves[i]).reshape(-1)
            self.opt.step(bufs["master"], bufs["m"], bufs["v"], g, self.t,
                          lr=lr, grad_coef=grad_coef)
            self._out[i] = self._cast_out(bufs["master"], store.shape)
            if pending_write is not None:
                self._write_h.wait()
            for kind in ("master", "m", "v"):
                self._write_h.async_pwrite(bufs[kind], store.paths[kind])
            if not self.pipeline_write:
                self._write_h.wait()
                pending_write = None
            else:
                pending_write = bufs
        self._write_h.wait()

    def compute_params(self, compute_dtype, shardings):
        """Push the compute-dtype leaves produced during step(); outside a
        step (checkpoint restore) stream the master back from NVMe."""
        if self._out is None:
            self._out = []
            for store in self._stores:
                buf = np.empty(int(np.prod(store.shape)), np.float32)
                self._read_h.async_pread(buf, store.paths["master"])
                self._read_h.wait()
                self._out.append(self._cast_out(buf, store.shape))
        s_leaves = jax.tree_util.tree_flatten(shardings)[0]
        srcs = [b if b.dtype == np.dtype(compute_dtype) else b.astype(np.dtype(compute_dtype))
                for b in self._out]
        out_leaves = list(_TRANSFER_POOL.map(lambda ms: jax.device_put(ms[0], ms[1]),
                                             zip(srcs, s_leaves)))
        out = jax.tree_util.tree_unflatten(self._treedef, out_leaves)
        jax.block_until_ready(out)
        self._out = None  # free the transient window
        return out

    # -- checkpoint -------------------------------------------------------
    def save_to(self, tag_dir):
        """Stream the swap files into the checkpoint directory (chunked file
        copy — never materializes the full state in DRAM, preserving the
        bounded-memory invariant; reference pipelined swapper checkpoints the
        same way, by file)."""
        import json
        import shutil
        out = os.path.join(tag_dir, "nvme_optimizer")
        os.makedirs(out, exist_ok=True)
        meta = {"step": int(self.t), "leaves": [list(map(int, s.shape)) for s in self._stores]}
        with open(os.path.join(out, "meta.json"), "w") as f:
            json.dump(meta, f)
        self._write_h.wait()  # no in-flight writes while copying
        for store in self._stores:
            for kind, src in store.paths.items():
                shutil.copyfile(src, os.path.join(out, os.path.basename(src)))

    def load_from(self, tag_dir):
        """Restore from ``save_to`` output, or from a host-DRAM-tier
        ``host_optimizer.npz`` (cross-tier resume). False when neither
        exists."""
        import json
        import shutil
        nv = os.path.join(tag_dir, "nvme_optimizer")
        if os.path.isdir(nv):
            with open(os.path.join(nv, "meta.json")) as f:
                meta = json.load(f)
            shapes = [tuple(s) for s in meta["leaves"]]
            ours = [tuple(map(int, s.shape)) for s in self._stores]
            if shapes != ours:
                raise ValueError(f"nvme optimizer checkpoint has {len(shapes)} leaves "
                                 f"{shapes[:3]}... but the model expects {ours[:3]}...")
            for store in self._stores:
                for kind, dst in store.paths.items():
                    shutil.copyfile(os.path.join(nv, os.path.basename(dst)), dst)
            self.t = int(meta["step"])
            return True
        npz = os.path.join(tag_dir, "host_optimizer.npz")
        if os.path.isfile(npz):
            with np.load(npz) as arrays:
                self.load_state_dict_arrays(arrays)
            return True
        return False

    def reset_from_params(self, params, step):
        """Rewrite master files from (already-loaded) device params, zero
        moments — streamed per leaf like init_from_device."""
        self.init_from_device(params)
        self.t = step

    def _tree_from_files(self, kind):
        leaves = []
        for store in self._stores:
            buf = np.empty(int(np.prod(store.shape)), np.float32)
            self._read_h.async_pread(buf, store.paths[kind])
            self._read_h.wait()
            leaves.append(buf.reshape(store.shape))
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    def state_dict_arrays(self):
        out = {"__step__": np.asarray(self.t, np.int64)}
        for kind, prefix in (("master", "master"), ("m", "m"), ("v", "v")):
            tree = self._tree_from_files(kind)
            flat = jax.tree_util.tree_flatten_with_path(tree)[0]
            for path, leaf in flat:
                out[prefix + "/" + jax.tree_util.keystr(path)] = leaf
        return out

    def load_state_dict_arrays(self, arrays):
        self.t = int(arrays["__step__"])
        # reconstruct file contents leaf-by-leaf in tree order
        example = jax.tree_util.tree_unflatten(
            self._treedef, [np.empty(s.shape, np.float32) for s in self._stores])
        flat, _ = jax.tree_util.tree_flatten_with_path(example)
        for kind in ("master", "m", "v"):
            for (path, leaf), store in zip(flat, self._stores):
                key = kind + "/" + jax.tree_util.keystr(path)
                src = np.ascontiguousarray(arrays[key], np.float32)
                if src.shape != tuple(store.shape):
                    raise ValueError(f"offload state {key}: shape {src.shape} != {store.shape}")
                self._write_h.async_pwrite(src, store.paths[kind])
                self._write_h.wait()
