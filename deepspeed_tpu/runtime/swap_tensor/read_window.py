"""Rotating NVMe read window: per-slot AIO handles + persistent buffers.

The ZeRO-Infinity streaming pipeline wants optimizer-state reads issued
``k`` blocks ahead of the block being applied (reference
``pipelined_optimizer_swapper.py:164`` keeps an ``aio_read``/``aio_write``
pair in flight around the CPU Adam step). A single shared
:class:`~deepspeed_tpu.ops.aio.AsyncIOHandle` cannot express that: its
``wait()`` fences *every* submitted request, so waiting for block ``i``'s
state would also wait for the look-ahead reads of ``i+1..i+k`` that were
just issued — serializing exactly the overlap the prefetch exists to buy.

:class:`AioReadWindow` rotates a small pool of slots. Each slot owns a
private AIO handle (so its ``wait()`` fences only its own block) plus
persistent 4096-aligned buffers, keyed by flat block size and reused across
steps instead of reallocated per prefetch — the staging-buffer half of the
pipeline (host DRAM high-water mark: ``slots x bufs_per_block x
max_block_bytes``, independent of step count).

A slot's buffers may still be riding a write-back when the slot would
otherwise be reused; callers hand such slots back through
``release(slot)`` only once the write has been fenced (see
``NVMeParamStore.apply_block``).
"""

import numpy as np

from ...ops.aio import AsyncIOHandle, aligned_empty


class _Slot:
    """One window slot: a private AIO handle + its persistent buffers."""

    __slots__ = ("handle", "_bufs")

    def __init__(self, handle_kw):
        self.handle = AsyncIOHandle(**handle_kw)
        self._bufs = {}  # (n, count) -> tuple of flat fp32 aligned buffers

    def buffers(self, n, count):
        """``count`` persistent aligned fp32 buffers of flat size ``n``."""
        key = (int(n), int(count))
        bufs = self._bufs.get(key)
        if bufs is None:
            bufs = tuple(aligned_empty((int(n), ), np.float32) for _ in range(count))
            self._bufs[key] = bufs
        return bufs


class AioReadWindow:
    """Pool of read slots; acquire one per in-flight block, release after
    the block's buffers are no longer referenced by any async request."""

    def __init__(self, slots, handle_kw):
        self._slots = [_Slot(handle_kw) for _ in range(max(1, int(slots)))]
        self._free = list(self._slots)

    def acquire(self):
        """A free slot, or None when the window is saturated (the caller
        falls back to its synchronous path)."""
        return self._free.pop() if self._free else None

    def release(self, slot):
        self._free.append(slot)

    @property
    def size(self):
        return len(self._slots)
