"""Runtime utilities: memory reporting and norm helpers.

Counterpart of reference ``runtime/utils.py`` (``see_memory_usage`` :40,
``get_global_norm`` / ``clip_grad_norm_`` :385, ``memory_status``): CUDA
allocator counters become XLA ``device.memory_stats()`` and host RSS.
"""

import gc

import jax
import jax.numpy as jnp

from ..utils.logging import logger


def _device_mem_line(dev):
    stats = dev.memory_stats() or {}
    in_use = stats.get("bytes_in_use", 0)
    peak = stats.get("peak_bytes_in_use", 0)
    limit = stats.get("bytes_limit", 0)
    return (f"{dev.platform}:{dev.id} in_use {in_use / 2**30:.2f}GB "
            f"peak {peak / 2**30:.2f}GB limit {limit / 2**30:.2f}GB")


def see_memory_usage(message, force=False, ranks=(0, )):
    """Log device + host memory (reference prints CUDA allocated/cached and
    host used; here XLA per-device stats and host RSS/available)."""
    if not force:
        return
    if jax.process_index() not in ranks:
        return
    lines = [message]
    for dev in jax.local_devices():
        try:
            lines.append("  " + _device_mem_line(dev))
        except Exception:  # backends without memory_stats (CPU)
            lines.append(f"  {dev.platform}:{dev.id} memory stats unavailable")
    try:
        import psutil
        vm = psutil.virtual_memory()
        lines.append(f"  host RSS {psutil.Process().memory_info().rss / 2**30:.2f}GB "
                     f"avail {vm.available / 2**30:.2f}GB ({vm.percent}% used)")
    except ImportError:
        pass
    logger.info("\n".join(lines))


def memory_status(msg="", reset_max=False):
    """Reference-shaped alias used by Megatron integrations."""
    see_memory_usage(msg or "memory_status", force=True)
    if reset_max:
        gc.collect()


def get_global_norm(norm_list=None, tensors=None):
    """L2 norm across a list of norms (reference semantics) or a pytree
    (same optax.global_norm the engine's clipping uses, fp32-accumulated)."""
    if tensors is not None:
        import optax
        return optax.global_norm(jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32), tensors))
    return float(sum(n**2 for n in norm_list))**0.5


def get_grad_norm(tree):
    """Global L2 norm of a gradient pytree (fp32 accumulate)."""
    return get_global_norm(tensors=tree)


def clip_grad_norm_(tree, max_norm):
    """Scale the pytree so its global norm is <= max_norm; returns
    (clipped tree, pre-clip norm) — functional, unlike the in-place torch
    version."""
    norm = get_grad_norm(tree)
    coef = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda x: (x.astype(jnp.float32) * coef).astype(x.dtype),
                                  tree), norm


def empty_cache():
    """CUDA empty_cache parity: XLA owns HBM for the process; only host-side
    garbage can be collected."""
    gc.collect()
