"""Inference weight quantization over parameter pytrees.

Counterpart of reference ``runtime/weight_quantizer.py`` (``WeightQuantization``
:10 — group-wise symmetric int8 of transformer matmul weights during
``init_inference``). Operates on this framework's pytrees: matmul kernels
(path ends in ``kernel`` or ``embedding``, ndim >= 2) are replaced by int8
arrays with per-group scales kept in a parallel ``scales`` tree; everything
else (norms, biases) stays fp32/bf16, matching the reference's
``model_quantize`` selection.
"""

import re

import jax
import jax.numpy as jnp

from ..ops.quantizer import dequantize, quantize
from ..utils.logging import logger

_DEFAULT_PATTERN = r"(kernel|embedding)$"


def _path_str(path):
    return "/".join(str(getattr(p, "key", getattr(p, "name", p))) for p in path)


class WeightQuantization:

    def __init__(self, quantize_bits=8, groups=1, mlp_extra_grouping=False,
                 pattern=_DEFAULT_PATTERN):
        self.quantize_bits = quantize_bits
        self.groups = groups
        self.mlp_extra_grouping = mlp_extra_grouping
        self.pattern = re.compile(pattern)

    def _groups_for(self, path, k_dim):
        g = self.groups
        if self.mlp_extra_grouping and ("mlp" in path or "fc" in path):
            g *= 2  # reference doubles MLP grouping for accuracy
        while g > 1 and k_dim % g != 0:
            g //= 2
        return max(1, g)

    def model_quantize(self, params):
        """params -> (quantized params, scales tree). Quantized leaves are
        int8 with the same shape; the scales tree holds (G, ...) fp32 leaves
        at the same paths (None where unquantized)."""
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)
        q_leaves, s_leaves = [], []
        n_q, bytes_before, bytes_after = 0, 0, 0
        for path, leaf in flat:
            p = _path_str(path)
            bytes_before += leaf.size * jnp.dtype(leaf.dtype).itemsize
            if leaf.ndim >= 2 and self.pattern.search(p):
                # group along the leading (contraction-or-row) axis
                g = self._groups_for(p, leaf.shape[0])
                q, scale, _ = quantize(leaf.reshape(leaf.shape[0], -1),
                                       bits=self.quantize_bits, groups=g, symmetric=True)
                q_leaves.append(q.reshape(leaf.shape))
                s_leaves.append(scale)
                n_q += 1
                bytes_after += leaf.size + scale.size * 4
            else:
                q_leaves.append(leaf)
                s_leaves.append(None)
                bytes_after += leaf.size * jnp.dtype(leaf.dtype).itemsize
        logger.info(f"WeightQuantization: {n_q} matmul weights -> int{self.quantize_bits}, "
                    f"{bytes_before / 2**20:.0f} MiB -> {bytes_after / 2**20:.0f} MiB")
        return (jax.tree_util.tree_unflatten(treedef, q_leaves),
                jax.tree_util.tree_unflatten(treedef, s_leaves))

    def model_dequantize(self, qparams, scales, dtype=jnp.bfloat16):
        """Inverse (for numerics checks / fallback execution paths)."""

        def deq(q, s):
            if s is None:
                return q
            w = dequantize(q.reshape(q.shape[0], -1), s, dtype=dtype)
            return w.reshape(q.shape)

        return jax.tree_util.tree_map(deq, qparams, scales,
                                      is_leaf=lambda x: x is None)
