from .config import DeepSpeedZeroConfig, ZeroStageEnum  # noqa: F401
from .sharding import ShardingPlanner, TensorParallelRules  # noqa: F401
from .tiling import TiledLinear, tiled_linear  # noqa: F401
