"""ZeRO config.

Analogue of reference ``deepspeed/runtime/zero/config.py`` (``ZeroStageEnum``
:263 area) and ``offload_config.py:94``. Same JSON keys. On TPU the stages map
to sharding rules over the ``data`` mesh axis (see ``zero/sharding.py``)
rather than hook-driven partitioning; the tuning knobs that only make sense
for hook scheduling (prefetch buckets, reuse distance) are accepted for config
compatibility and surfaced to the sharding planner where meaningful.
"""

from ..config_utils import DeepSpeedConfigModel, ConfigField


class ZeroStageEnum:
    disabled = 0
    optimizer_states = 1
    gradients = 2
    weights = 3
    max_stage = 3


class OffloadDeviceEnum:
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


def _check_offload_device(value):
    valid = (OffloadDeviceEnum.none, OffloadDeviceEnum.cpu, OffloadDeviceEnum.nvme)
    if value not in valid:
        raise ValueError(f"offload device must be one of {valid}, got {value}")
    return value


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    device = ConfigField(default=OffloadDeviceEnum.none, validator=_check_offload_device)
    nvme_path = ConfigField(default=None)
    buffer_count = ConfigField(default=5)
    buffer_size = ConfigField(default=int(1e8))
    max_in_cpu = ConfigField(default=int(1e9))
    pin_memory = ConfigField(default=False)


def _check_nonneg_int(value):
    value = int(value)
    if value < 0:
        raise ValueError(f"expected a non-negative integer, got {value}")
    return value


def _check_pos_int(value):
    value = int(value)
    if value < 1:
        raise ValueError(f"expected a positive integer, got {value}")
    return value


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    device = ConfigField(default=OffloadDeviceEnum.none, validator=_check_offload_device)
    nvme_path = ConfigField(default=None)
    buffer_count = ConfigField(default=4)
    pin_memory = ConfigField(default=False)
    pipeline_read = ConfigField(default=False)
    pipeline_write = ConfigField(default=False)
    fast_init = ConfigField(default=False)
    ratio = ConfigField(default=1.0)
    # ZeRO-Infinity streaming pipeline (zero/param_offload.py
    # LayerStreamExecutor): depth of the bidirectional host->device
    # parameter / NVMe optimizer-state look-ahead, and the max in-flight
    # gradient device->host fetches. prefetch_depth=0 is the fully
    # SYNCHRONOUS no-overlap step (every put fenced at point of use) — a
    # measurement/debug mode, slower than the pre-pipeline 1-deep async
    # look-ahead; use prefetch_depth=1 for that legacy behavior. Numerics
    # are bit-identical at any setting; each extra depth step costs ~one
    # layer block of HBM headroom.
    prefetch_depth = ConfigField(default=2, validator=_check_nonneg_int)
    fetch_window = ConfigField(default=4, validator=_check_pos_int)

    @property
    def pipeline(self):
        return self.pipeline_read or self.pipeline_write


def _check_stage(value):
    if value is True:
        return ZeroStageEnum.optimizer_states
    if value is False:
        return ZeroStageEnum.disabled
    value = int(value)
    if not (0 <= value <= ZeroStageEnum.max_stage):
        raise ValueError(f"zero stage must be in [0, {ZeroStageEnum.max_stage}]")
    return value


class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    """``zero_optimization`` section (same keys as the reference)."""

    stage = ConfigField(default=0, validator=_check_stage)
    contiguous_gradients = ConfigField(default=True)
    reduce_scatter = ConfigField(default=True)
    reduce_bucket_size = ConfigField(default=int(5e8))
    allgather_partitions = ConfigField(default=True)
    allgather_bucket_size = ConfigField(default=int(5e8))
    overlap_comm = ConfigField(default=None)  # resolved: default True at stage 3
    load_from_fp32_weights = ConfigField(default=True)
    elastic_checkpoint = ConfigField(default=False)
    offload_param = ConfigField(default=DeepSpeedZeroOffloadParamConfig)
    offload_optimizer = ConfigField(default=DeepSpeedZeroOffloadOptimizerConfig)
    sub_group_size = ConfigField(default=int(1e9))
    cpu_offload_param = ConfigField(default=None)  # deprecated in ref; kept
    cpu_offload_use_pin_memory = ConfigField(default=None)
    cpu_offload = ConfigField(default=None)
    stage3_max_live_parameters = ConfigField(default=int(1e9))
    stage3_max_reuse_distance = ConfigField(default=int(1e9))
    stage3_prefetch_bucket_size = ConfigField(default=int(5e7))
    stage3_param_persistence_threshold = ConfigField(default=int(1e5))
    stage3_gather_16bit_weights_on_model_save = ConfigField(
        default=False, aliases=("stage3_gather_fp16_weights_on_model_save",))
    ignore_unused_parameters = ConfigField(default=True)
    legacy_stage1 = ConfigField(default=False)
    round_robin_gradients = ConfigField(default=False)
    zero_hpz_partition_size = ConfigField(default=1)
    memory_efficient_linear = ConfigField(default=True)

    def __init__(self, param_dict=None):
        super().__init__(param_dict)
        if self.overlap_comm is None:
            self.overlap_comm = self.stage == ZeroStageEnum.weights
        # deprecated cpu_offload flags fold into offload_optimizer/param
        if self.cpu_offload:
            self.offload_optimizer.device = OffloadDeviceEnum.cpu
        if self.cpu_offload_param:
            self.offload_param.device = OffloadDeviceEnum.cpu
