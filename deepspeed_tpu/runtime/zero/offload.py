"""ZeRO-Offload: optimizer state in host DRAM, stepped by the native CPU
optimizer — PARTITIONED across DP ranks/hosts.

TPU-native analogue of the reference's ZeRO-Offload tier (optimizer-state
CPU offload: ``runtime/zero/stage_1_and_2.py:1031`` async CPU accumulation of
*this rank's partition* + ``csrc/adam/cpu_adam.cpp``; config surface
``zero/offload_config.py:94``). Design translation (SURVEY §7): instead of
hook-driven swap of partitioned torch tensors, the engine keeps only the
compute-dtype (bf16) parameters and activations in HBM; fp32 master
parameters and Adam moments live in host numpy buffers owned by this class.
One training step is:

  device: fwd+bwd (one pjit) -> reduce-scattered compute-dtype grads, loss
  host:   fetch THIS HOST's grad shards -> fused C AdamW over its
          (master, m, v) shards -> cast bf16
  device: push the shards back; XLA re-gathers to the compute layout

Partitioning model: every leaf is laid out in the planner's *offload
sharding* (scattered over the DP axes — ``ShardingPlanner.offload_spec``).
A host owns exactly the shards its local devices hold (deduplicated when an
axis replicates within the host, stepped redundantly when replication spans
hosts — correct either way since Adam is elementwise). At 70B scale the
840 GB of fp32 master+moments therefore spans the aggregate DRAM of all
feeding hosts instead of replicating per host.

HBM cost drops from 16 bytes/param (fp32 master + 2 moments + bf16 copy)
to ~4 (bf16 params + transient grads); host DRAM cost is 12 bytes/param
/ dp_world — how a 1.5B-param model trains on a single 16 GB chip and a
70B-param model's optimizer spans a pod's hosts.

The push uses ``jax.block_until_ready`` before the next in-place host step:
``device_put`` is asynchronous and may read the numpy buffer after return
(same aliasing hazard as donated buffers).
"""

import io
import os
import zipfile

import numpy as np

import jax
import jax.numpy as jnp

from ...ops.adam.cpu_adam import DeepSpeedCPUAdam, f32_to_bf16
from ...utils.logging import logger, log_dist

# host<->device copies of different leaves are independent; issuing them from
# a pool keeps multiple DMA streams in flight (4x measured on serialized
# links, still a win on direct PCIe). ONE process-wide pool, owned by the
# shared streaming layer since PR 11 — a second pool here would double the
# I/O threads and contend for the same links
from ...memory.streams import TRANSFER_POOL as _TRANSFER_POOL  # noqa: E402


def _slash_path(path):
    """'/'-joined key path (same format as tensor_fragment accessors)."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _norm_index(index, shape):
    """Normalize a Shard.index (tuple of slices) to ((start, stop), ...)."""
    out = []
    for s, extent in zip(index, shape):
        out.append((int(s.start or 0), int(extent if s.stop is None else s.stop)))
    return tuple(out)


def _index_str(norm):
    return ";".join(f"{a}:{b}" for a, b in norm)


def _parse_index_str(s):
    return tuple(tuple(map(int, part.split(":"))) for part in s.split(";"))


def _slices(norm):
    return tuple(slice(a, b) for a, b in norm)


class _Block:
    """One owned shard of one leaf: its global index + the local devices
    holding it."""

    __slots__ = ("leaf", "index", "shape", "devices")

    def __init__(self, leaf, index, shape, devices):
        self.leaf = leaf  # leaf ordinal in tree order
        self.index = index  # normalized ((start, stop), ...) per dim
        self.shape = shape  # block shape
        self.devices = devices  # local devices holding this block

    @property
    def size(self):
        return int(np.prod(self.shape, dtype=np.int64))


class HostOffloadOptimizer:
    """fp32 master params + Adam moments on the host, partitioned per-block.

    ``master``/``m``/``v`` are lists (aligned with ``blocks``) of flat fp32
    numpy arrays — this host's partition of the global state.
    """

    def __init__(self, optimizer_config, lr_schedule_fn):
        p = dict(optimizer_config.params)
        betas = tuple(p.get("betas", (0.9, 0.999)))
        self.opt = DeepSpeedCPUAdam(lr=p.get("lr", 1e-3), betas=betas,
                                    eps=p.get("eps", 1e-8),
                                    weight_decay=p.get("weight_decay", 0.0),
                                    adamw_mode=p.get("adam_w_mode", True)
                                    if (optimizer_config.type or "").lower() != "adamw" else True)
        self.lr_schedule_fn = lr_schedule_fn
        self.blocks = None  # list[_Block], this host's partition
        self.master = None  # list of flat fp32 arrays aligned with blocks
        self.m = None
        self.v = None
        self.t = 0  # 1-based inside step()
        self._treedef = None
        self._leaf_shapes = None  # global leaf shapes, tree order
        self._leaf_paths = None  # keystr per leaf, tree order
        self._off_shardings = None  # per-leaf NamedSharding (offload layout)

    # -- partition discovery ----------------------------------------------
    def _discover_blocks(self, leaves_off):
        """Read this process's addressable shards of the offload-sharded
        device arrays; one _Block per unique shard index."""
        self.blocks = []
        per_leaf_data = []
        for li, arr in enumerate(leaves_off):
            seen = {}
            for shard in arr.addressable_shards:
                key = _norm_index(shard.index, arr.shape)
                if key in seen:
                    seen[key].devices.append(shard.device)
                else:
                    blk = _Block(li, key, tuple(shard.data.shape), [shard.device])
                    seen[key] = blk
                    self.blocks.append(blk)
                    per_leaf_data.append((blk, shard.data))
        return per_leaf_data

    def init_from_device(self, params_off):
        """Build the host partition from offload-sharded fp32 device params
        (parallel per-block fetches)."""
        self._record_layout(params_off)
        pairs = self._discover_blocks(jax.tree_util.tree_leaves(params_off))
        fetch = lambda bd: np.array(jax.device_get(bd[1]), np.float32, copy=True).reshape(-1)
        self.master = list(_TRANSFER_POOL.map(fetch, pairs))
        self.m = [np.zeros_like(b) for b in self.master]
        self.v = [np.zeros_like(b) for b in self.master]

    def _record_layout(self, params_off):
        leaves, treedef = jax.tree_util.tree_flatten(params_off)
        flat_paths = jax.tree_util.tree_flatten_with_path(params_off)[0]
        self._treedef = treedef
        self._leaf_shapes = [tuple(x.shape) for x in leaves]
        self._leaf_paths = [_slash_path(path) for path, _ in flat_paths]
        self._off_shardings = [x.sharding for x in leaves]
        self._reshard_cache = {}

    def num_params(self):
        """Number of parameters whose optimizer state THIS host owns."""
        return sum(b.size for b in self.blocks)

    # -- hot path ----------------------------------------------------------
    def fetch_grads(self, grads_off):
        """Offload-sharded device grads -> this host's blocks (parallel)."""
        leaves = jax.tree_util.tree_leaves(grads_off)
        by_key = {}
        for li, arr in enumerate(leaves):
            for shard in arr.addressable_shards:
                by_key.setdefault((li, _norm_index(shard.index, arr.shape)), shard.data)
        datas = [by_key[(b.leaf, b.index)] for b in self.blocks]
        fetch = lambda d: np.asarray(jax.device_get(d)).reshape(-1)
        return list(_TRANSFER_POOL.map(fetch, datas))

    def step(self, grad_blocks, grad_coef, lr):
        """Fused host AdamW over every owned block. ``grad_blocks``: flat
        host arrays aligned with ``self.blocks``; ``grad_coef`` folds
        loss-scale unscale, grad-accum averaging and clipping."""
        self.t += 1
        for g, p, m, v in zip(grad_blocks, self.master, self.m, self.v):
            self.opt.step(p, m, v, g, self.t, lr=lr, grad_coef=grad_coef)

    def _cast(self, flat, compute_dtype):
        if np.dtype(compute_dtype) == np.dtype(jnp.bfloat16):
            return f32_to_bf16(flat)
        return flat.astype(np.dtype(compute_dtype))

    def _block_out(self, i, compute_dtype):
        """Updated master for block i as a compute-dtype host array."""
        return self._cast(self.master[i], compute_dtype).reshape(self.blocks[i].shape)

    def compute_params(self, compute_dtype, shardings):
        """Push this host's updated shards; XLA reshards to the compute
        layout (the stage-1/2 'allgather updated partitions' step tail,
        reference ``stage_1_and_2.py``)."""
        blocks_by_leaf = {}
        for i, blk in enumerate(self.blocks):
            blocks_by_leaf.setdefault(blk.leaf, []).append(i)

        def assemble(li):
            arrays = []
            for i in blocks_by_leaf[li]:
                blk = self.blocks[i]
                host = self._block_out(i, compute_dtype)
                for d in blk.devices:
                    arrays.append(jax.device_put(host, d))
            return jax.make_array_from_single_device_arrays(
                self._leaf_shapes[li], self._off_shardings[li], arrays)

        off_leaves = list(_TRANSFER_POOL.map(assemble, range(len(self._leaf_shapes))))
        off_tree = jax.tree_util.tree_unflatten(self._treedef, off_leaves)
        # cache the jitted reshard per (dtype, out layout): a fresh jit wrapper
        # each step would retrace the full param tree every train step
        key = (np.dtype(compute_dtype).str,
               tuple(jax.tree_util.tree_leaves(shardings)))
        reshard = self._reshard_cache.get(key)
        if reshard is None:
            reshard = jax.jit(lambda t: t, donate_argnums=(0, ), out_shardings=shardings)
            self._reshard_cache[key] = reshard
        out = reshard(off_tree)
        # the host buffers are mutated in place next step; the async transfer
        # must have consumed them by then
        jax.block_until_ready(out)
        return out

    # -- checkpoint ---------------------------------------------------------
    # Every process writes its partition to host_optimizer.rank{r}.npz; the
    # loader reassembles full leaves from all rank files and re-slices into
    # the current partition, so resume works across process/mesh layouts
    # (the universal-checkpoint property, reference checkpoint/ reshape).
    def _iter_state_blocks(self):
        """Yield (kind, block_ordinal, flat fp32 array) for this partition."""
        for kind, store in (("master", self.master), ("m", self.m), ("v", self.v)):
            for i, flat in enumerate(store):
                yield kind, i, flat

    def _block_key(self, kind, i):
        blk = self.blocks[i]
        return f"{kind}/{self._leaf_paths[blk.leaf]}|{_index_str(blk.index)}"

    def save_to(self, tag_dir):
        """Persist this host's partition next to the device checkpoint
        (streamed into the npz one block at a time — bounded DRAM)."""
        path = os.path.join(tag_dir, f"host_optimizer.rank{jax.process_index():05d}.npz")
        with zipfile.ZipFile(path, "w", zipfile.ZIP_STORED) as zf:
            buf = io.BytesIO()
            np.lib.format.write_array(buf, np.asarray(self.t, np.int64))
            zf.writestr("__step__.npy", buf.getvalue())
            for kind, i, flat in self._iter_state_blocks():
                buf = io.BytesIO()
                np.lib.format.write_array(buf, flat)
                zf.writestr(self._block_key(kind, i) + ".npy", buf.getvalue())

    def _saved_piece_index(self, tag_dir):
        """Scan rank files (+ legacy formats) -> {(kind, leaf_path):
        [(norm_index or None, load_fn), ...]}, plus the saved step."""
        import glob
        import json
        files = sorted(glob.glob(os.path.join(tag_dir, "host_optimizer.rank*.npz")))
        legacy = os.path.join(tag_dir, "host_optimizer.npz")
        if os.path.isfile(legacy):
            files.append(legacy)
        index, step = {}, 0
        self._open_npzs = [np.load(f) for f in files]
        for nz in self._open_npzs:
            for key in nz.files:
                if key == "__step__":
                    step = int(nz[key])
                    continue
                kind, rest = key.split("/", 1)
                if "|" in rest:
                    leaf_path, idxstr = rest.rsplit("|", 1)
                    norm = _parse_index_str(idxstr)
                else:
                    leaf_path, norm = rest, None  # legacy full-leaf entry
                index.setdefault((kind, leaf_path), []).append(
                    (norm, lambda nz=nz, key=key: np.asarray(nz[key], np.float32)))
        # legacy NVMe-tier dir: per-leaf flat files in tree order
        nv = os.path.join(tag_dir, "nvme_optimizer")
        if os.path.isdir(nv):
            with open(os.path.join(nv, "meta.json")) as f:
                meta = json.load(f)
            step = step or int(meta.get("step", 0))
            for li, shape in enumerate(meta.get("leaves", [])):
                if li >= len(self._leaf_paths):
                    break
                for kind in ("master", "m", "v"):
                    path = os.path.join(nv, f"leaf{li:05d}.{kind}")
                    if os.path.isfile(path):
                        index.setdefault((kind, self._leaf_paths[li]), []).append(
                            (None, lambda path=path: np.fromfile(path, np.float32)))
        if not index:
            return None, 0
        return index, step

    def _set_block(self, kind, i, data):
        {"master": self.master, "m": self.m, "v": self.v}[kind][i][...] = data.reshape(-1)

    def load_from(self, tag_dir):
        """Restore this partition from ``save_to`` output (any rank/mesh
        layout whose pieces cover our blocks); False when the checkpoint
        carries no offloaded optimizer state."""
        index, step = self._saved_piece_index(tag_dir)
        if index is None:
            return False
        try:
            blocks_by_leaf = {}
            for i, blk in enumerate(self.blocks):
                blocks_by_leaf.setdefault(blk.leaf, []).append(i)
            for li, block_ids in blocks_by_leaf.items():
                shape = self._leaf_shapes[li]
                leaf_path = self._leaf_paths[li]
                for kind in ("master", "m", "v"):
                    pieces = index.get((kind, leaf_path))
                    if not pieces:
                        raise ValueError(f"offload checkpoint misses {kind} for {leaf_path}")
                    full = np.empty(shape, np.float32)
                    covered = np.zeros(shape, bool)
                    for norm, load in pieces:
                        data = load()
                        if norm is None:
                            if data.size != int(np.prod(shape, dtype=np.int64)):
                                raise ValueError(f"{kind}/{leaf_path}: full-leaf entry size "
                                                 f"{data.size} != leaf {shape}")
                            full[...] = data.reshape(shape)
                            covered[...] = True
                        else:
                            sl = _slices(norm)
                            full[sl] = data.reshape(full[sl].shape)
                            covered[sl] = True
                    if not covered.all():
                        raise ValueError(f"offload checkpoint pieces do not cover "
                                         f"{kind}/{leaf_path} (partial copy, or mesh-resize "
                                         f"with mismatched partition boundaries?)")
                    for i in block_ids:
                        self._set_block(kind, i, full[_slices(self.blocks[i].index)])
            self.t = step
            return True
        finally:
            for nz in getattr(self, "_open_npzs", []):
                nz.close()
            self._open_npzs = []

    def reset_from_params(self, params, step):
        """Rebuild fp32 master from (already-loaded) device params with
        fresh moments — the fallback when a checkpoint was saved without
        offload. ``params`` may be in any sharding; resharded on device."""
        reshard = jax.jit(lambda t: jax.tree_util.tree_map(lambda x: x.astype(jnp.float32), t),
                          out_shardings=jax.tree_util.tree_unflatten(self._treedef,
                                                                     self._off_shardings))
        leaves = jax.tree_util.tree_leaves(reshard(params))
        by_key = {}
        for li, arr in enumerate(leaves):
            for shard in arr.addressable_shards:
                by_key.setdefault((li, _norm_index(shard.index, arr.shape)), shard.data)
        for i, blk in enumerate(self.blocks):
            self._set_block("master", i, np.asarray(jax.device_get(by_key[(blk.leaf, blk.index)]),
                                                    np.float32))
            self.m[i][...] = 0
            self.v[i][...] = 0
        self.t = step

    def state_dict_arrays(self):
        """Flat {key: np.ndarray} of this partition (tests/debug aid)."""
        out = {"__step__": np.asarray(self.t, np.int64)}
        for kind, i, flat in self._iter_state_blocks():
            out[self._block_key(kind, i)] = flat
        return out

    # -- full-leaf accessors (tensor_fragment debug API) --------------------
    def _leaf_index(self, path):
        try:
            return self._leaf_paths.index(path)
        except ValueError:
            raise KeyError(f"path {path!r}: no such parameter; known leaves include "
                           f"{self._leaf_paths[:5]}...") from None

    def _block_data(self, kind, i):
        """Flat fp32 data of owned block i (host tier: in-memory)."""
        return {"master": self.master, "m": self.m, "v": self.v}[kind][i]

    def get_full(self, kind, path):
        """Assemble the full leaf at ``path`` from this host's blocks.
        Raises if this host owns only part of it (multi-host partition)."""
        li = self._leaf_index(path)
        shape = self._leaf_shapes[li]
        full = np.empty(shape, np.float32)
        covered = np.zeros(shape, bool)
        for i, blk in enumerate(self.blocks):
            if blk.leaf != li:
                continue
            sl = _slices(blk.index)
            full[sl] = self._block_data(kind, i).reshape(blk.shape)
            covered[sl] = True
        if not covered.all():
            raise ValueError(f"{path}: this host owns only part of the leaf (offload state "
                             f"is partitioned across hosts); gather via checkpoint instead")
        return full

    def set_full(self, kind, path, value):
        """Write this host's blocks of the full leaf value at ``path``."""
        li = self._leaf_index(path)
        shape = self._leaf_shapes[li]
        src = np.asarray(value, np.float32)
        if src.shape != shape:
            raise ValueError(f"value shape {src.shape} != param shape {shape}")
        for i, blk in enumerate(self.blocks):
            if blk.leaf == li:
                self._set_block(kind, i, src[_slices(blk.index)])
