"""ZeRO-Offload: optimizer state in host DRAM, stepped by the native CPU
optimizer.

TPU-native analogue of the reference's ZeRO-Offload tier (optimizer-state
CPU offload: ``runtime/zero/stage_1_and_2.py:1031`` async CPU accumulation +
``csrc/adam/cpu_adam.cpp``; config surface ``zero/offload_config.py:94``).
Design translation (SURVEY §7): instead of hook-driven swap of partitioned
torch tensors, the engine keeps only the compute-dtype (bf16) parameters and
activations in HBM; fp32 master parameters and Adam moments live in host
numpy buffers owned by this class. One training step is:

  device: fwd+bwd (one pjit) -> compute-dtype grads, loss, grad-norm
  host:   fetch grads -> fused C AdamW over (master, m, v) -> cast bf16
  device: push updated compute params back into their sharded layout

HBM cost drops from 16 bytes/param (fp32 master + 2 moments + bf16 copy)
to ~4 (bf16 params + transient grads) — how a 1.5B-param model trains on a
single 16 GB chip (the reference's "10x bigger models" ZeRO-Offload pitch).

The push uses ``jax.block_until_ready`` before the next in-place host step:
``device_put`` is asynchronous and may read the numpy buffer after return
(same aliasing hazard as donated buffers).
"""

from concurrent.futures import ThreadPoolExecutor

import numpy as np

import jax
import jax.numpy as jnp

from ...ops.adam.cpu_adam import DeepSpeedCPUAdam, f32_to_bf16
from ...utils.logging import logger, log_dist

# host<->device copies of different leaves are independent; issuing them from
# a pool keeps multiple DMA streams in flight (4x measured on serialized
# links, still a win on direct PCIe)
_TRANSFER_POOL = ThreadPoolExecutor(max_workers=8, thread_name_prefix="offload-io")


class HostOffloadOptimizer:
    """fp32 master params + Adam moments on the host, per-leaf.

    Each feeding process owns the state for the parameters it pushes —
    with a single controller that is the full model; under multi-host DP
    each host steps the same global state redundantly (grads are already
    reduced on-device), trading host FLOPs for zero extra communication.
    """

    def __init__(self, optimizer_config, lr_schedule_fn):
        p = dict(optimizer_config.params)
        betas = tuple(p.get("betas", (0.9, 0.999)))
        self.opt = DeepSpeedCPUAdam(lr=p.get("lr", 1e-3), betas=betas,
                                    eps=p.get("eps", 1e-8),
                                    weight_decay=p.get("weight_decay", 0.0),
                                    adamw_mode=p.get("adam_w_mode", True)
                                    if (optimizer_config.type or "").lower() != "adamw" else True)
        self.lr_schedule_fn = lr_schedule_fn
        self.master = None  # pytree of fp32 np arrays
        self.m = None
        self.v = None
        self.t = 0  # 1-based inside step()

    def init_from_device(self, params_f32):
        """Pull fp32 master copies (parallel per-leaf fetches)."""
        leaves, treedef = jax.tree_util.tree_flatten(params_f32)
        fetch = lambda leaf: np.array(jax.device_get(leaf), dtype=np.float32, copy=True)
        host = list(_TRANSFER_POOL.map(fetch, leaves))
        self.master = jax.tree_util.tree_unflatten(treedef, host)
        self.m = jax.tree_util.tree_map(np.zeros_like, self.master)
        self.v = jax.tree_util.tree_map(np.zeros_like, self.master)

    def num_params(self):
        return sum(x.size for x in jax.tree_util.tree_leaves(self.master))

    def step(self, grads, grad_coef, lr):
        """Fused host AdamW over every leaf. ``grads``: pytree of host numpy
        arrays (fp32 or bfloat16); ``grad_coef`` folds loss-scale unscale,
        grad-accum averaging and clipping."""
        self.t += 1
        for g, p, m, v in zip(jax.tree_util.tree_leaves(grads),
                              jax.tree_util.tree_leaves(self.master),
                              jax.tree_util.tree_leaves(self.m),
                              jax.tree_util.tree_leaves(self.v)):
            self.opt.step(p.reshape(-1), m.reshape(-1), v.reshape(-1), g.reshape(-1),
                          self.t, lr=lr, grad_coef=grad_coef)

    def fetch_grads(self, grads):
        """Device grads -> host numpy, parallel per-leaf."""
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        host = list(_TRANSFER_POOL.map(lambda a: np.asarray(jax.device_get(a)), leaves))
        return jax.tree_util.tree_unflatten(treedef, host)

    def compute_params(self, compute_dtype, shardings):
        """Push the updated master as compute-dtype device arrays in their
        sharded layout (parallel per-leaf)."""
        cast = (lambda x: f32_to_bf16(x)) if compute_dtype == jnp.bfloat16 else \
            (lambda x: x.astype(np.dtype(compute_dtype)))

        m_leaves, treedef = jax.tree_util.tree_flatten(self.master)
        s_leaves = jax.tree_util.tree_flatten(shardings)[0]
        out_leaves = list(_TRANSFER_POOL.map(lambda ms: jax.device_put(cast(ms[0]), ms[1]),
                                             zip(m_leaves, s_leaves)))
        out = jax.tree_util.tree_unflatten(treedef, out_leaves)
        # the host buffers are mutated in place next step; the async transfer
        # must have consumed them by then
        jax.block_until_ready(out)
        return out

    # ---- checkpoint ------------------------------------------------------
    def save_to(self, tag_dir):
        """Persist master/m/v next to the device checkpoint."""
        import os
        np.savez(os.path.join(tag_dir, "host_optimizer.npz"), **self.state_dict_arrays())

    def load_from(self, tag_dir):
        """Restore from ``save_to`` output — this tier's npz, or an NVMe-tier
        ``nvme_optimizer/`` directory (cross-tier resume works both ways);
        False when the checkpoint carries no offloaded optimizer state."""
        import json
        import os
        p = os.path.join(tag_dir, "host_optimizer.npz")
        if os.path.isfile(p):
            with np.load(p) as arrays:
                self.load_state_dict_arrays(arrays)
            return True
        nv = os.path.join(tag_dir, "nvme_optimizer")
        if os.path.isdir(nv):
            with open(os.path.join(nv, "meta.json")) as f:
                meta = json.load(f)
            trees = {"master": self.master, "m": self.m, "v": self.v}
            for kind, tree in trees.items():
                leaves = jax.tree_util.tree_leaves(tree)
                if len(leaves) != len(meta["leaves"]):
                    raise ValueError(f"nvme optimizer checkpoint has {len(meta['leaves'])} "
                                     f"leaves; the model expects {len(leaves)}")
                for i, leaf in enumerate(leaves):
                    path = os.path.join(nv, f"leaf{i:05d}.{kind}")
                    data = np.fromfile(path, dtype=np.float32)
                    if data.size != leaf.size:
                        raise ValueError(f"{path}: {data.size} values != leaf size {leaf.size}")
                    leaf[...] = data.reshape(leaf.shape)
            self.t = int(meta["step"])
            return True
        return False

    def reset_from_params(self, params, step):
        """Rebuild fp32 master from (already-loaded) device params with
        fresh moments — the fallback when a checkpoint was saved without
        offload."""
        for dst, src in zip(jax.tree_util.tree_leaves(self.master),
                            jax.tree_util.tree_leaves(params)):
            dst[...] = np.asarray(jax.device_get(src), dtype=np.float32)
        for t in (self.m, self.v):
            for leaf in jax.tree_util.tree_leaves(t):
                leaf[...] = 0
        self.t = step

    def state_dict_arrays(self):
        """Flat {path: np.ndarray} for np.savez (checkpoint sidecar)."""
        out = {"__step__": np.asarray(self.t, np.int64)}
        for prefix, tree in (("master", self.master), ("m", self.m), ("v", self.v)):
            flat = jax.tree_util.tree_flatten_with_path(tree)[0]
            for path, leaf in flat:
                out[prefix + "/" + jax.tree_util.keystr(path)] = leaf
        return out

    def load_state_dict_arrays(self, arrays):
        self.t = int(arrays["__step__"])
        for prefix, tree in (("master", self.master), ("m", self.m), ("v", self.v)):
            flat, _ = jax.tree_util.tree_flatten_with_path(tree)
            for path, leaf in flat:
                key = prefix + "/" + jax.tree_util.keystr(path)
                src = arrays[key]
                if src.shape != leaf.shape:
                    raise ValueError(f"offload state {key}: shape {src.shape} != {leaf.shape}")
                leaf[...] = src
