"""ZeRO-Infinity parameter offload: params live on host (or NVMe), streamed
through the step one layer block at a time.

TPU-native counterpart of the reference's partitioned-parameter swap tier
(``runtime/swap_tensor/partitioned_param_swapper.py:36``,
``runtime/zero/stage3.py:463 _configure_tensor_swapping``, ZeRO-Inference
``docs/_posts/2022-09-10-zero-inference.md``). The reference streams fp16
params CPU/NVMe->GPU via module hooks + a prefetch coordinator; here the
model exposes explicit block functions (``stream_embed`` / ``stream_layer``
/ ``stream_tail_loss`` — ``models/transformer.py``) and this runner drives
them:

  forward   embed -> [device_put(l+1) overlaps layer l] x L -> tail loss
  backward  tail vjp -> [layer vjp, re-streaming params, grads -> host] x L
            -> embed vjp
  update    fused C AdamW (``ops/csrc/cpu_adam.c``) over each block's
            host-resident fp32 master + moments; bf16 compute copies
            refreshed in place

HBM high-water mark is O(embed block + one layer block + L saved
activations + tail CE) — independent of total parameter count, which is how
a model whose *parameters* exceed one chip's HBM still trains (the
reference's "10x bigger models" pitch). Optimizer state is host/NVMe
resident by construction, so ``offload_param`` subsumes
``offload_optimizer`` here (the reference requires the same pairing for the
NVMe tier, ``zero/offload_config.py``).

Backward rematerializes each block's forward inside its vjp (the
``jax.checkpoint``-everything policy): saved state per layer is one
(B, T, H) activation, not the block's internals.

The NVMe tier (``NVMeParamStore``) keeps master/m/v in flat per-block files
under ``nvme_path`` via the AIO pool (``ops/csrc/aio.c``) and bounds DRAM to
the bf16 compute copies plus a rotating read/compute/write window, the
pipelined-swapper scheme of ``swap_tensor/optimizer_swapper.py``.

All four host<->device/NVMe flows of the step are pipelined by
:class:`LayerStreamExecutor` (the prefetch-coordinator role of the
reference's ``PartitionedParameterCoordinator``): depth-``k`` parameter
prefetch in both traversal directions, a bounded-window async gradient
fetch queue, persistent staging buffers, and NVMe optimizer-state reads
scheduled ``k`` blocks ahead of ``apply_block``. Knobs:
``zero_optimization.offload_optimizer.prefetch_depth`` / ``fetch_window``
(``zero/config.py``); ``prefetch_depth=0`` degenerates to the synchronous
point-of-use put — bit-identical numerics by construction, the executor
moves bytes, never math.
"""

import os
import json
import threading
import time

import numpy as np

import jax
import jax.numpy as jnp
import ml_dtypes
from jax.sharding import NamedSharding, PartitionSpec as P

from ...comm import comm as dist
# the streaming transfer core lives in the shared memory subsystem now
# (PR 11 extraction); re-exported here so existing imports keep working
from ...memory.streams import LayerStreamExecutor  # noqa: F401
from ...ops.adam.cpu_adam import DeepSpeedCPUAdam, f32_to_bf16
from ...ops.aio import aligned_empty
from ...utils.logging import log_dist, logger
from .offload import _slash_path


def _tree_f32(tree):
    # force writable owned copies: device_get / asarray views are read-only
    return jax.tree_util.tree_map(
        lambda x: np.array(x, np.float32, copy=True), tree)


def _tree_zeros(tree, dtype=np.float32):
    return jax.tree_util.tree_map(lambda x: np.zeros(x.shape, dtype), tree)


def _tree_bf16(tree, out=None):
    if out is None:
        return jax.tree_util.tree_map(lambda x: f32_to_bf16(np.ascontiguousarray(x)), tree)
    jax.tree_util.tree_map(lambda x, o: f32_to_bf16(np.ascontiguousarray(x), o), tree, out)
    return out


def _tree_cast(tree, dtype, out=None):
    """fp32 master -> compute-dtype copies. bf16 takes the native fast path
    (cpu_adam's f32_to_bf16); fp16 (reference fp16 param swap,
    ``partitioned_param_swapper.py:36``) goes through numpy."""
    if np.dtype(dtype) == np.dtype(ml_dtypes.bfloat16):
        return _tree_bf16(tree, out)
    if out is None:
        return jax.tree_util.tree_map(lambda x: np.ascontiguousarray(x).astype(dtype), tree)
    jax.tree_util.tree_map(lambda x, o: np.copyto(o, x.astype(dtype)), tree, out)
    return out


def _leaf_cast(src_f32, out):
    """Refresh one compute-copy leaf from flat fp32 (dtype-dispatching)."""
    if out.dtype == np.dtype(ml_dtypes.bfloat16):
        f32_to_bf16(np.ascontiguousarray(src_f32), out)
    else:
        np.copyto(out, src_f32.astype(out.dtype))


def _nbytes(tree):
    return sum(x.nbytes for x in jax.tree_util.tree_leaves(tree))


def _num_params(tree):
    return sum(int(np.prod(x.shape, dtype=np.int64))
               for x in jax.tree_util.tree_leaves(tree))


class HostParamStore:
    """cpu tier: every block's fp32 master + Adam moments + bf16 compute copy
    in host DRAM. A block is a param pytree (one layer's slice of the stacked
    stack, or the embed/tail subtrees)."""

    def __init__(self, optimizer_config, grad_dtype=np.float32, compute_dtype=None):
        p = dict(optimizer_config.params)
        self.opt = DeepSpeedCPUAdam(lr=p.get("lr", 1e-3),
                                    betas=tuple(p.get("betas", (0.9, 0.999))),
                                    eps=p.get("eps", 1e-8),
                                    weight_decay=p.get("weight_decay", 0.0),
                                    adamw_mode=p.get("adam_w_mode", True))
        self.grad_dtype = grad_dtype
        # "bf16" names the COMPUTE COPY slot for continuity; fp16 serving of
        # the reference's fp16 param swap stores fp16 copies in it
        self.compute_dtype = np.dtype(compute_dtype) if compute_dtype is not None \
            else np.dtype(ml_dtypes.bfloat16)
        self.blocks = {}  # name -> dict(master/m/v/bf16 pytrees)
        self.t = 0

    def add_block(self, name, master_tree):
        master = _tree_f32(master_tree)
        self.blocks[name] = {
            "master": master,
            "m": _tree_zeros(master),
            "v": _tree_zeros(master),
            "bf16": _tree_cast(master, self.compute_dtype),
        }

    def block_names(self):
        return list(self.blocks.keys())

    def bf16(self, name):
        """Host bf16 compute pytree for ``name`` (zero-copy view of DRAM)."""
        return self.blocks[name]["bf16"]

    def num_params(self):
        return sum(_num_params(b["master"]) for b in self.blocks.values())

    def master_paths(self, name):
        """Slash paths of the block's master leaves, flatten order."""
        flat = jax.tree_util.tree_flatten_with_path(self.blocks[name]["master"])[0]
        return [_slash_path(p) for p, _ in flat]

    def schedule_state_prefetch(self, names):
        """Optimizer-state look-ahead hook (flow 4): host-tier master/m/v
        are already DRAM-resident, so there is nothing to prefetch."""

    # -- update -----------------------------------------------------------
    def begin_step(self):
        self.t += 1

    def apply_block(self, name, grad_leaves, grad_coef, lr):
        """Fused AdamW over one block + refresh its bf16 copy in place.
        ``grad_leaves``: flat arrays ALIGNED with the master's flatten order
        (the runner aligns by path — zip over two differently-shaped trees
        would silently mispair leaves)."""
        b = self.blocks[name]
        masters = jax.tree_util.tree_leaves(b["master"])
        assert len(grad_leaves) == len(masters), (name, len(grad_leaves), len(masters))
        for g, p, m, v in zip(grad_leaves, masters,
                              jax.tree_util.tree_leaves(b["m"]),
                              jax.tree_util.tree_leaves(b["v"])):
            assert g.size == p.size, (name, g.shape, p.shape)
            self.opt.step(p.ravel(), m.ravel(), v.ravel(),
                          np.ascontiguousarray(g).ravel(), self.t,
                          lr=lr, grad_coef=grad_coef)
        _tree_cast(b["master"], self.compute_dtype, b["bf16"])

    # -- checkpoint --------------------------------------------------------
    def save_to(self, tag_dir):
        d = os.path.join(tag_dir, "param_offload")
        os.makedirs(d, exist_ok=True)
        meta = {"step": self.t, "blocks": {}}
        for name, b in self.blocks.items():
            flat = jax.tree_util.tree_flatten_with_path(b["master"])[0]
            paths = [_slash_path(p) for p, _ in flat]
            meta["blocks"][name] = paths
            arrays = {}
            for kind in ("master", "m", "v"):
                leaves = jax.tree_util.tree_leaves(b[kind])
                for path, leaf in zip(paths, leaves):
                    arrays[f"{kind}|{path}"] = leaf
            np.savez(os.path.join(d, f"{name.replace('/', '_')}.npz"), **arrays)
        with open(os.path.join(d, "meta.json"), "w") as f:
            json.dump(meta, f)

    def load_from(self, tag_dir, load_optimizer_states=True):
        d = os.path.join(tag_dir, "param_offload")
        meta_path = os.path.join(d, "meta.json")
        if not os.path.isfile(meta_path):
            return False
        with open(meta_path) as f:
            meta = json.load(f)
        kinds = ("master", "m", "v") if load_optimizer_states else ("master", )
        for name, b in self.blocks.items():
            nz = np.load(os.path.join(d, f"{name.replace('/', '_')}.npz"))
            flat = jax.tree_util.tree_flatten_with_path(b["master"])[0]
            paths = [_slash_path(p) for p, _ in flat]
            for kind in kinds:
                for path, leaf in zip(paths, jax.tree_util.tree_leaves(b[kind])):
                    leaf[...] = nz[f"{kind}|{path}"]
            if not load_optimizer_states:  # fresh moments (reference
                for kind in ("m", "v"):    # load_optimizer_states=False)
                    for leaf in jax.tree_util.tree_leaves(b[kind]):
                        leaf[...] = 0
            _tree_cast(b["master"], self.compute_dtype, b["bf16"])
            nz.close()
        self.t = int(meta["step"]) if load_optimizer_states else 0
        return True


class NVMeParamStore(HostParamStore):
    """nvme tier: master/m/v in flat per-block files; DRAM holds only the
    bf16 compute copies plus a rotating (read | adam | write) window —
    the pipelined swapper scheme of ``swap_tensor/optimizer_swapper.py``."""

    def __init__(self, optimizer_config, nvme_path, aio_config=None, grad_dtype=np.float32,
                 compute_dtype=None, state_window=2):
        super().__init__(optimizer_config, grad_dtype, compute_dtype)
        from ...ops.aio import AsyncIOHandle
        from ..swap_tensor.aio_config import get_aio_config
        from ..swap_tensor.read_window import AioReadWindow
        aio = aio_config if aio_config is not None else get_aio_config({})
        kw = dict(block_size=aio["block_size"], queue_depth=aio["queue_depth"],
                  single_submit=aio["single_submit"], overlap_events=aio["overlap_events"],
                  thread_count=max(1, aio["thread_count"]))
        self._read_h = AsyncIOHandle(**kw)
        self._write_h = AsyncIOHandle(**kw)
        self.swap_dir = os.path.join(nvme_path,
                                     f"zero_param_swap_rank{jax.process_index():05d}")
        os.makedirs(self.swap_dir, exist_ok=True)
        self._meta = {}  # name -> list[(path, shape)] flat leaf layout
        # state-read look-ahead: one slot per in-flight block, each with a
        # private AIO handle (a shared handle's wait() would fence the
        # look-ahead reads too) + persistent buffers. DRAM bound:
        # slots x 3 x largest block x 4 bytes.
        self._window = AioReadWindow(max(2, int(state_window)), kw)
        self._prefetched = {}   # name -> _Slot with (master, m, v) in flight
        self._writing_slot = None  # slot whose buffers ride the current write
        self._applied_step = set()  # blocks already applied this step: a
        # late look-ahead for one of these would pread files whose
        # write-back may still be in flight, and park a window slot
        self._grad_stage = {}   # flat size -> persistent fp32 grad staging
        # streaming applies arrive from transfer-pool threads; the shared
        # read/write AIO handles and prefetch window are single-consumer.
        # RLock: prefetch_state is public and also called under apply_block.
        self._apply_lock = threading.RLock()

    def _file(self, name, kind):
        return os.path.join(self.swap_dir, f"{name.replace('/', '_')}.{kind}")

    def add_block(self, name, master_tree):
        master = _tree_f32(master_tree)
        flat = jax.tree_util.tree_flatten_with_path(master)[0]
        self._meta[name] = [(_slash_path(p), tuple(x.shape)) for p, x in flat]
        cat = np.concatenate([x.ravel() for _, x in flat]) if flat else np.empty(0, np.float32)
        self._write_h.async_pwrite(cat, self._file(name, "master"))
        zeros = np.zeros_like(cat)
        self._write_h.async_pwrite(zeros, self._file(name, "m"))
        self._write_h.async_pwrite(zeros, self._file(name, "v"))
        self._write_h.wait()
        self.blocks[name] = {"bf16": _tree_cast(master, self.compute_dtype)}

    def num_params(self):
        return sum(sum(int(np.prod(s, dtype=np.int64)) for _, s in leaves)
                   for leaves in self._meta.values())

    def _block_size(self, name):
        return sum(int(np.prod(s, dtype=np.int64)) for _, s in self._meta[name])

    def begin_step(self):
        super().begin_step()
        with self._apply_lock:
            self._applied_step.clear()

    def prefetch_state(self, name):
        """Issue async reads of (master, m, v) for ``name`` into a free
        read-window slot. No-op when already in flight, already applied
        this step (a late look-ahead racing its own write-back), or the
        window is saturated (``apply_block`` then falls back to a
        synchronous read)."""
        with self._apply_lock:
            if name in self._prefetched or name in self._applied_step:
                return
            slot = self._window.acquire()
            if slot is None:
                return
            for buf, kind in zip(slot.buffers(self._block_size(name), 3),
                                 ("master", "m", "v")):
                slot.handle.async_pread(buf, self._file(name, kind))
            self._prefetched[name] = slot

    def schedule_state_prefetch(self, names):
        """Flow-4 hook: issue look-ahead state reads for the next blocks of
        the apply order (stops silently when the window saturates)."""
        for name in names:
            if name in self.blocks:
                self.prefetch_state(name)

    def master_paths(self, name):
        return [p for p, _ in self._meta[name]]

    def _stage_grads(self, name, grad_leaves):
        """Flatten grad leaves into a persistent per-size staging buffer
        (applies serialize on the apply lock, so one buffer per distinct
        block size suffices — no per-apply reallocation)."""
        n = self._block_size(name)
        g = self._grad_stage.get(n)
        if g is None:
            g = aligned_empty((n, ), np.float32)
            self._grad_stage[n] = g
        off = 0
        for x in grad_leaves:
            x = np.ascontiguousarray(x)
            g[off:off + x.size] = x.reshape(-1)  # numpy casts to fp32 in place
            off += x.size
        return g

    def apply_block(self, name, grad_leaves, grad_coef, lr):
        assert len(grad_leaves) == len(self._meta[name])
        with self._apply_lock:
            slot = self._prefetched.pop(name, None)
            if slot is None:
                slot = self._window.acquire()
                if slot is not None:  # cold read through a window slot
                    for buf, kind in zip(slot.buffers(self._block_size(name), 3),
                                         ("master", "m", "v")):
                        slot.handle.async_pread(buf, self._file(name, kind))
            if slot is not None:
                slot.handle.wait()
                master, m, v = slot.buffers(self._block_size(name), 3)
            else:  # window fully busy: one-off buffers via the shared handle
                bufs = tuple(aligned_empty((self._block_size(name), ), np.float32)
                             for _ in range(3))
                for buf, kind in zip(bufs, ("master", "m", "v")):
                    self._read_h.async_pread(buf, self._file(name, kind))
                self._read_h.wait()
                master, m, v = bufs
            self._applied_step.add(name)
            g = self._stage_grads(name, grad_leaves)
            self.opt.step(master, m, v, g, self.t, lr=lr, grad_coef=grad_coef)
            # write-back overlaps the next block's read + compute; the slot
            # (and its buffers) rejoins the free window only after the NEXT
            # wait() proves the write consumed them
            self._write_h.wait()
            if self._writing_slot is not None:
                self._window.release(self._writing_slot)
            self._writing_slot = slot  # None for the one-off path (GC'd)
            for buf, kind in zip((master, m, v), ("master", "m", "v")):
                self._write_h.async_pwrite(buf, self._file(name, kind))
            # refresh bf16 views from the updated flat master
            off = 0
            for (path, shape), leaf in zip(self._meta[name],
                                           jax.tree_util.tree_leaves(self.blocks[name]["bf16"])):
                n = int(np.prod(shape, dtype=np.int64))
                _leaf_cast(master[off:off + n].reshape(shape), leaf)
                off += n

    def flush(self):
        with self._apply_lock:
            self._write_h.wait()
            if self._writing_slot is not None:
                self._window.release(self._writing_slot)
                self._writing_slot = None
            # stale look-aheads (e.g. a skipped non-finite block): fence and
            # reclaim their slots so the window never leaks
            for name, slot in list(self._prefetched.items()):
                slot.handle.wait()
                self._window.release(slot)
            self._prefetched.clear()

    def save_to(self, tag_dir):
        self.flush()
        d = os.path.join(tag_dir, "param_offload")
        os.makedirs(d, exist_ok=True)
        meta = {"step": self.t,
                "blocks": {n: [p for p, _ in leaves] for n, leaves in self._meta.items()}}
        for name in self.blocks:
            arrays = {}
            n = self._block_size(name)
            for kind in ("master", "m", "v"):
                buf = aligned_empty((n, ), np.float32)
                self._read_h.async_pread(buf, self._file(name, kind))
                self._read_h.wait()
                off = 0
                for path, shape in self._meta[name]:
                    k = int(np.prod(shape, dtype=np.int64))
                    arrays[f"{kind}|{path}"] = buf[off:off + k].reshape(shape)
                    off += k
            np.savez(os.path.join(d, f"{name.replace('/', '_')}.npz"), **arrays)
        with open(os.path.join(d, "meta.json"), "w") as f:
            json.dump(meta, f)

    def load_from(self, tag_dir, load_optimizer_states=True):
        d = os.path.join(tag_dir, "param_offload")
        meta_path = os.path.join(d, "meta.json")
        if not os.path.isfile(meta_path):
            return False
        with open(meta_path) as f:
            meta = json.load(f)
        for name in self.blocks:
            nz = np.load(os.path.join(d, f"{name.replace('/', '_')}.npz"))
            for kind in ("master", "m", "v"):
                if kind != "master" and not load_optimizer_states:
                    cat = np.zeros(self._block_size(name), np.float32)  # fresh moments
                else:
                    cat = np.concatenate([np.asarray(nz[f"{kind}|{p}"], np.float32).ravel()
                                          for p, _ in self._meta[name]])
                self._write_h.async_pwrite(cat, self._file(name, kind))
                self._write_h.wait()
                if kind == "master":
                    off = 0
                    for (path, shape), leaf in zip(
                            self._meta[name],
                            jax.tree_util.tree_leaves(self.blocks[name]["bf16"])):
                        k = int(np.prod(shape, dtype=np.int64))
                        _leaf_cast(cat[off:off + k].reshape(shape), leaf)
                        off += k
            nz.close()
        self.t = int(meta["step"]) if load_optimizer_states else 0
        return True


class ParamStreamRunner:
    """Owns the host param store and the layer-streamed train/eval/generate
    loops. Built by the engine when ``zero_optimization.offload_param.device``
    is 'cpu' or 'nvme' (stage 3)."""

    def __init__(self, model, config, mesh, planner, compute_dtype, lr_schedule_fn,
                 rng_seed=0):
        cfg = config
        self.model = model
        self.mesh = mesh
        self.planner = planner
        self.compute_dtype = compute_dtype
        self.lr_schedule_fn = lr_schedule_fn
        self.gas = cfg.gradient_accumulation_steps
        self.micro_bs = cfg.train_micro_batch_size_per_gpu
        self.clip = cfg.gradient_clipping
        self._seed_int = int(rng_seed)
        self._rng = jax.random.key(rng_seed)

        # MoE composes: expert kernels ride each layer block (the stacked
        # (E, ...) leaves stream like any other); the gating aux loss flows
        # through the per-layer vjp (see _build_fns)
        self._moe = getattr(getattr(model, "cfg", None), "num_experts", 0) > 0
        self._aux_coef = float(getattr(getattr(model, "cfg", None), "moe_aux_loss_coef", 0.0))
        # fp16 loss-scaled streaming (reference fp16 param swap,
        # partitioned_param_swapper.py:36): fp16 compute copies + a host-side
        # dynamic loss scaler — the tail vjp is seeded with the scale, every
        # streamed grad is scale-scaled, and applies divide it back out
        self._fp16 = jnp.dtype(compute_dtype) == jnp.float16
        fp16_cfg = cfg.fp16
        if self._fp16:
            if fp16_cfg.loss_scale:  # static scale
                self._scale = float(fp16_cfg.loss_scale)
                self._scale_dynamic = False
            else:
                self._scale = float(2.0 ** fp16_cfg.initial_scale_power)
                self._scale_dynamic = True
            self._scale_window = int(fp16_cfg.loss_scale_window)
            self._min_scale = float(fp16_cfg.min_loss_scale)
            self._good_steps = 0
        else:
            self._scale = 1.0
            self._scale_dynamic = False

        abstract = jax.eval_shape(model.init_params, self._rng)
        self.plan = model.stream_plan(abstract)
        lk = self.plan["layer_key"]
        self.L = jax.tree_util.tree_leaves(abstract[lk])[0].shape[0]
        self._abs_embed = {k: abstract[k] for k in self.plan["embed"]}
        self._abs_tail = {k: abstract[k] for k in self.plan["tail"]}
        self._abs_layer = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape[1:], x.dtype), abstract[lk])

        # per-block compute shardings (TP/replication rules; the planner sees
        # the same "layers/..." paths the full tree would produce). Layer
        # blocks are PER-LAYER slices — their kernels have no leading stack
        # dim, so they take the model's unscanned TP rules.
        self._shard_embed = planner.shardings(planner.param_specs(self._abs_embed))
        self._shard_tail = planner.shardings(planner.param_specs(self._abs_tail))
        import dataclasses
        from .sharding import ShardingPlanner
        flat_model = type(model)(dataclasses.replace(model.cfg, scan_layers=False))
        layer_planner = ShardingPlanner(mesh, cfg.zero_optimization,
                                        tp_rules=flat_model.tp_rules(),
                                        expert_pattern=planner.expert_pattern and
                                        planner.expert_pattern.pattern)
        self._shard_layer = layer_planner.shardings(
            layer_planner.param_specs({lk: self._abs_layer}))[lk]

        off = cfg.zero_optimization.offload_param
        opt_cfg = cfg.optimizer
        # streaming-pipeline knobs live on offload_optimizer (offload_param
        # subsumes it here — the streamed step keeps optimizer state host/
        # NVMe-resident by construction, so its tuning section is the one
        # that configures the transfer executor)
        opt_off = cfg.zero_optimization.offload_optimizer
        self.prefetch_depth = max(0, int(getattr(opt_off, "prefetch_depth", 2)))
        self.fetch_window = max(1, int(getattr(opt_off, "fetch_window", 4)))
        store_dtype = np.dtype(jnp.dtype(compute_dtype).name)  # bf16 or fp16 copies
        grad_dtype = store_dtype if self.gas == 1 else np.float32
        if off.device == "nvme":
            if not off.nvme_path:
                raise ValueError("offload_param.device='nvme' requires nvme_path")
            from ..swap_tensor.aio_config import get_aio_config
            self.store = NVMeParamStore(opt_cfg, nvme_path=off.nvme_path,
                                        aio_config=get_aio_config(cfg.raw_config),
                                        grad_dtype=grad_dtype, compute_dtype=store_dtype,
                                        state_window=min(4, self.prefetch_depth + 1))
        else:
            self.store = HostParamStore(opt_cfg, grad_dtype=grad_dtype,
                                        compute_dtype=store_dtype)
        self._grad_dtype = grad_dtype

        self._init_store()
        self._layer_names = [f"layer{l:05d}" for l in range(self.L)]
        self.executor = LayerStreamExecutor(self._dispatch_block, self.store,
                                            self.prefetch_depth, self.fetch_window)
        self._fns = {}
        self.global_steps = 0
        self._last_gnorm = 0.0
        self.last_phase_times = None
        tier = "NVMe" if off.device == "nvme" else "host DRAM"
        log_dist(f"ZeRO-Infinity param offload: {self.store.num_params():,} params resident "
                 f"on {tier} ({_nbytes_blocks(self.store):,} DRAM bytes), streamed per layer "
                 f"block; HBM holds one block + activations", [0])

    # -- init ---------------------------------------------------------------
    def _init_store(self):
        """Initialize blocks HOST-side from the abstract shapes — the
        streaming analogue of ``zero.Init`` (reference
        ``partition_parameters.py:601``): no device (and no host buffer)
        ever holds the full model, and nothing crosses the host<->HBM link
        at init. Initializers follow the zoo's conventions (normal(0.02)
        kernels/embeddings, ones scales, zeros biases); random-init parity
        with the fused path is not a goal — real runs restore checkpoints
        (``set_params_from_tree`` / ``load_checkpoint``)."""

        def init_tree(abs_tree, seed):
            rng = np.random.default_rng(seed)
            flat = jax.tree_util.tree_flatten_with_path(abs_tree)
            out = []
            for path, sds in flat[0]:
                name = _slash_path(path).rsplit("/", 1)[-1]
                if name == "scale":
                    out.append(np.ones(sds.shape, np.float32))
                elif name == "bias":
                    out.append(np.zeros(sds.shape, np.float32))
                else:  # kernel / embedding / pos_embed
                    out.append(rng.normal(0.0, 0.02, sds.shape).astype(np.float32))
            return jax.tree_util.tree_unflatten(flat[1], out)

        self.store.add_block("embed", init_tree(self._abs_embed, self._seed_int))
        self.store.add_block("tail", init_tree(
            {k: v for k, v in self._abs_tail.items() if k not in self.plan["embed"]},
            self._seed_int + 1))
        for l in range(self.L):
            self.store.add_block(f"layer{l:05d}",
                                 init_tree(self._abs_layer, self._seed_int + 2 + l))

    # -- device feed --------------------------------------------------------
    def _shard_batch_arr(self, x):
        """Batch arrays scatter over the ZeRO dp axes (activations inherit
        the layout through the jitted block fns)."""
        x = np.asarray(x)
        axes = [a for a in (dist.EXPERT_AXIS, dist.DATA_AXIS) if self.mesh.shape[a] > 1]
        size = int(np.prod([self.mesh.shape[a] for a in axes])) if axes else 1
        if axes and x.shape[0] % size == 0:
            entries = [tuple(axes) if len(axes) > 1 else axes[0]] + [None] * (x.ndim - 1)
            return jax.device_put(x, NamedSharding(self.mesh, P(*entries)))
        return jnp.asarray(x)

    def _tail_store_tree(self):
        """Device-feed pytree for the tail block (tied embeddings pull the
        shared 'embed' entry from the embed block's store)."""
        t = dict(self.store.bf16("tail"))
        if "embed" in self.plan["tail"] and "embed" not in t:
            t["embed"] = self.store.bf16("embed")["embed"]
        return t

    def _dispatch_block(self, name):
        """Raw host->device put of one block (the executor owns timing: it
        separates dispatch from realized transfer via completion fencing)."""
        if name == "embed":
            return jax.device_put(self.store.bf16("embed"), self._shard_embed)
        if name == "tail":
            return jax.device_put(self._tail_store_tree(), self._shard_tail)
        return jax.device_put(self.store.bf16(name), self._shard_layer)

    # -- compiled pieces ----------------------------------------------------
    def _get(self, name, builder):
        fn = self._fns.get(name)
        if fn is None:
            fn = builder()
            self._fns[name] = fn
        return fn

    def _build_fns(self, T, shift, has_mask):
        model = self.model
        cd = self.compute_dtype
        moe, aux_coef = self._moe, self._aux_coef

        def embed_fwd(ep, ids):
            return model.stream_embed(ep, ids).astype(cd)

        if moe:
            # forward carries this layer's gating aux loss; backward seeds
            # its cotangent with the aux coefficient so the gate/expert
            # grads include load balancing (the fused path adds
            # coef*sum(aux) to the scalar loss — same math, per layer)
            def layer_fwd(lp, h, mask):
                y, aux = model.stream_layer(lp, h, mask, return_aux=True)
                return y.astype(cd), aux

            def layer_bwd(lp, h, mask, g, scale):
                _, vjp = jax.vjp(lambda lp_, h_: layer_fwd(lp_, h_, mask), lp, h)
                # the aux cotangent carries the same loss scale as g
                dlp, dh = vjp((g, jnp.asarray(aux_coef, jnp.float32) * scale))
                return dlp, dh
        else:
            def layer_fwd(lp, h, mask):
                return model.stream_layer(lp, h, mask).astype(cd)

            def layer_bwd(lp, h, mask, g):
                _, vjp = jax.vjp(lambda lp_, h_: layer_fwd(lp_, h_, mask), lp, h)
                dlp, dh = vjp(g)
                return dlp, dh

        def tail_grad(tp, h, labels, valid, scale):
            def f(tp_, h_):
                return model.stream_tail_loss(tp_, h_, labels, valid, shift=shift)
            loss, vjp = jax.vjp(f, tp, h)
            # fp16: seed the backward with the loss scale so small grads
            # survive the fp16 stream; applies divide it back out
            dtp, dh = vjp(jnp.asarray(scale, loss.dtype))
            return loss, dtp, dh

        def embed_bwd(ep, ids, g):
            _, vjp = jax.vjp(lambda ep_: embed_fwd(ep_, ids), ep)
            return vjp(g)[0]

        j = lambda f, **kw: jax.jit(f, **kw)
        return {
            "embed_fwd": j(embed_fwd),
            # h is NOT donated in layer_fwd: the input activation is the
            # saved residual for this layer's backward vjp
            "layer_fwd": j(layer_fwd),
            "layer_bwd": j(layer_bwd, donate_argnums=(3, )),
            "tail_grad": j(tail_grad),
            "embed_bwd": j(embed_bwd, donate_argnums=(2, )),
        }

    # -- hot loop -----------------------------------------------------------
    def _micro_grads(self, fns, ids, mask, labels, valid, grad_sink, scale=1.0):
        """One microbatch: streamed forward + backward; per-block grads are
        handed to ``grad_sink(name, grad_tree)`` as device arrays the moment
        they exist (their host fetch overlaps the next block's compute).
        ``scale``: fp16 loss scale seeded into the tail vjp (1.0 for bf16).

        Both traversal directions stream through the executor with the same
        depth-``k`` look-ahead: the forward walk prefetches
        ``embed -> layers -> tail``; the backward walk re-streams the layer
        blocks in REVERSED order (``ep`` is still live from the forward, so
        only layers re-fetch)."""
        ex = self.executor
        names = self._layer_names
        fwd = ["embed"] + names + ["tail"]
        bwd = names[::-1]
        with self.mesh:
            ep = ex.take("embed", ahead=fwd[1:])
            h = fns["embed_fwd"](ep, ids)
            acts = []
            aux_total = 0.0
            for l in range(self.L):
                lp = ex.take(names[l], ahead=fwd[l + 2:])  # prefetch overlaps compute
                acts.append(h)
                if self._moe:
                    h, aux = fns["layer_fwd"](lp, h, mask)
                    aux_total = aux_total + aux
                else:
                    h = fns["layer_fwd"](lp, h, mask)
                del lp
            # taking the tail seeds the backward direction's look-ahead
            tp = ex.take("tail", ahead=bwd)
            loss, dtp, dh = fns["tail_grad"](tp, h, labels, valid,
                                             jnp.asarray(scale, jnp.float32))
            if self._moe:  # report CE + coef*aux like the fused engine
                loss = loss + self._aux_coef * aux_total
            del tp, h
            grad_sink("tail", dtp)
            for i, l in enumerate(reversed(range(self.L))):
                lp = ex.take(bwd[i], ahead=bwd[i + 1:])
                if self._moe:
                    dlp, dh = fns["layer_bwd"](lp, acts.pop(), mask, dh,
                                               jnp.asarray(scale, jnp.float32))
                else:
                    dlp, dh = fns["layer_bwd"](lp, acts.pop(), mask, dh)
                del lp
                grad_sink(names[l], dlp)
            dep = fns["embed_bwd"](ep, ids, dh)
            del ep, dh
            grad_sink("embed", dep)
        return loss

    def train_batch(self, batch):
        ids = np.asarray(batch["input_ids"])
        if ids.ndim == 2:
            ids = ids.reshape((self.gas, -1) + ids.shape[1:])
        mask = batch.get("attention_mask")
        if mask is not None:
            mask = np.asarray(mask).reshape(ids.shape)
        if "labels" in batch:
            labels = np.asarray(batch["labels"]).reshape(ids.shape)
            shift = False
        else:
            labels = ids[:, :, 1:]
            shift = True
        valid = labels >= 0
        labels_c = np.maximum(labels, 0)

        fns = self._get(("train", ids.shape[2], shift, mask is not None),
                        lambda: self._build_fns(ids.shape[2], shift, mask is not None))

        # host grad accumulators KEYED BY (block, leaf path): alignment with
        # each block's master flatten order is re-established at apply time,
        # and a tied embedding's two contributions (embed fwd + tail CE) sum
        # into the same slot regardless of which block's vjp produced them
        grads = {}  # name -> {path: np.ndarray} (persistent staging buffers)
        acc_dtype = self._grad_dtype if self.gas == 1 else np.float32
        tied_shared = [k for k in self.plan["tail"] if k in self.plan["embed"]]
        acc_lock = threading.Lock()  # tail + embed fetches can target the
        # same tied-embedding slot from different pool threads

        # STREAMING APPLY (capacity mode): with gas=1 each LAYER block's
        # AdamW applies the moment its grad lands — host DRAM never holds a
        # full model's gradients (the difference between 6.7B fitting this
        # host's 125 GB or OOMing). Gradient clipping uses the RUNNING
        # global norm (step N-1's measured norm; the reference's pragmatic
        # trade for hook-time clipping) since the true norm isn't known
        # until every grad has landed — step 1 applies unclipped. NVMe-tier
        # applies serialize on the store's apply lock (shared AIO handles);
        # fetches still overlap. gas>1 falls through to the buffered path:
        # cross-microbatch accumulation inherently holds every block's
        # accumulator at once, so streaming wins nothing there.
        #
        # Overflow semantics (intentionally weaker than the fused path's
        # atomic skip): a non-finite block is skipped INDIVIDUALLY — other
        # blocks keep their updates and Adam's step count still advances,
        # reported via the returned overflow flag. The buffered path below
        # keeps the reference's atomic whole-step skip.
        stream_apply = self.gas == 1 and isinstance(self.store, HostParamStore)
        lr = float(self.lr_schedule_fn(jnp.asarray(self.global_steps, jnp.float32)))
        scale = self._scale  # fp16 loss scale (1.0 for bf16)
        stream_coef = 1.0 / scale
        if stream_apply and self.clip and self.clip > 0:
            prev = getattr(self, "_last_gnorm", None)
            if prev is not None and np.isfinite(prev) and prev > 0:
                stream_coef = min(1.0, float(self.clip) / (prev + 1e-6)) / scale
        sq_by_block = {}  # name -> grad sum-of-squares; summed in SORTED key
        # order below so the global norm is independent of fetch-thread
        # completion order (float addition is not associative — an
        # arrival-order sum would make clipped streaming runs
        # timing-dependent and break depth/window parity)
        skipped_blocks = []
        if stream_apply:
            self.store.begin_step()
        ex = self.executor
        # streaming-apply order (grads land backward; embed/tail buffer to
        # the main thread at the end): the NVMe state look-ahead walks this
        # list k blocks ahead of each apply
        apply_order = self._layer_names[::-1] + ["embed", "tail"]
        apply_pos = {n: i for i, n in enumerate(apply_order)}

        def accumulate(name, path, host, src):
            """Stage one contribution. Multi-SOURCE slots (the tied
            embedding receives both the embed vjp and the tail CE vjp) are
            staged PER SOURCE and combined in sorted-source order by
            ``_finalize_grads`` — adding them in fetch-thread arrival order
            would make the sum's bit pattern scheduler-dependent (3+ float
            adds are order-sensitive; per-source accumulation is not,
            because microbatch drains serialize each source's stream)."""
            with acc_lock:
                # fp32 whenever a slot can receive >1 contribution (gas>1,
                # or the tied embedding's two vjp sources)
                dt = np.float32 if (name == "embed" and tied_shared) else acc_dtype
                slot = grads.setdefault(name, {}).setdefault(path, {})
                slot[src] = ex.stage_grad((name, src), path, host, dt)

        def _finalize_grads():
            """Collapse per-source staging into one array per (block, leaf)
            in sorted-source order (deterministic); runs on the main thread
            after the final drain."""
            for name in grads:
                for path, slot in grads[name].items():
                    srcs = sorted(slot)
                    if len(srcs) == 1:
                        grads[name][path] = slot[srcs[0]]
                        continue
                    out = ex.stage_grad((name, "__combined__"), path,
                                        slot[srcs[0]], np.float32)
                    for s in srcs[1:]:
                        np.add(out, np.asarray(slot[s], np.float32), out=out)
                    grads[name][path] = out

        def sink(name, dev_tree):
            # flow 4: the NEXT applies' state reads go out from the fetch
            # thread, just before this block's own fetch/apply — issuing
            # them from the hot loop would block it on the NVMe apply lock
            # whenever an apply is mid-flight (no-op on the host tier)
            nxt = 0 if name == "tail" else apply_pos.get(name, len(apply_order) - 1) + 1
            look_ahead = apply_order[nxt:] if stream_apply else ()

            def fetch(dev_tree=dev_tree, name=name, look_ahead=look_ahead):
                if look_ahead:
                    ex.schedule_state_prefetch(look_ahead)
                flat = jax.tree_util.tree_flatten_with_path(dev_tree)[0]
                if stream_apply and name.startswith("layer"):
                    with ex.timed_fetch():  # transfer only — not the apply
                        by_path = {_slash_path(p): np.asarray(jax.device_get(leaf))
                                   for p, leaf in flat}
                    aligned = [by_path[p] for p in self.store.master_paths(name)]
                    sq = sum(float(np.sum(np.square(np.asarray(g, np.float32))))
                             for g in aligned)
                    with acc_lock:
                        sq_by_block[name] = sq_by_block.get(name, 0.0) + sq
                    if not np.isfinite(sq):
                        skipped_blocks.append(name)
                        return
                    self.store.apply_block(name, aligned, stream_coef, lr)
                    return
                with ex.timed_fetch():
                    fetched = [(_slash_path(p), np.asarray(jax.device_get(leaf)))
                               for p, leaf in flat]
                for path, host in fetched:
                    if name == "tail" and path.split("/", 1)[0] in tied_shared:
                        # tied embedding: this is the EMBED block's param
                        accumulate("embed", path, host, src="tail")
                    else:
                        accumulate(name, path, host, src=name)
            ex.submit_fetch(fetch)

        t_step0 = time.perf_counter()
        ex.begin_step()  # step-scoped stats: eval/generate puts must not leak in
        loss_sum = 0.0
        for i in range(self.gas):
            m = None if mask is None else self._shard_batch_arr(mask[i])
            loss = self._micro_grads(fns, self._shard_batch_arr(ids[i]), m,
                                     self._shard_batch_arr(labels_c[i]),
                                     self._shard_batch_arr(valid[i]), sink, scale=scale)
            loss_sum += float(loss)
            # drain before the next microbatch: fetches for the SAME slot
            # accumulate in place and must not race
            ex.drain_fetches()
        _finalize_grads()
        # per-phase breakdown (capacity-run evidence: how much of the step
        # hid behind compute vs blocked on the host link). 'put_s'/'drain_s'
        # are CRITICAL-PATH exposure (main-thread blocked time) — prefetched
        # puts no longer count against them; 'put_dispatch_s' is issue time
        # wherever it ran, 'put_realized_s'/'fetch_realized_s' are fenced
        # transfer completions, and 'overlap_efficiency' is the realized
        # fraction the pipeline hid: 1 - exposed / realized.
        st = ex.collect_stats()
        realized = st["put_realized_s"] + st["fetch_realized_s"]
        exposed = st["put_wait_s"] + st["fetch_wait_s"]
        self.last_phase_times = {
            "step_s": time.perf_counter() - t_step0,
            "drain_s": st["fetch_wait_s"],
            "put_s": st["put_wait_s"],
            "put_dispatch_s": st["put_dispatch_s"],
            "put_realized_s": st["put_realized_s"],
            "fetch_realized_s": st["fetch_realized_s"],
            "overlap_efficiency": (max(0.0, min(1.0, 1.0 - exposed / realized))
                                   if realized > 0 else 0.0),
        }

        sq_sum = sum(sq_by_block[k] for k in sorted(sq_by_block))
        for name in sorted(grads):
            for path in sorted(grads[name]):
                sq_sum += float(np.sum(np.square(np.asarray(grads[name][path], np.float32))))
        gnorm_raw = float(np.sqrt(sq_sum)) if np.isfinite(sq_sum) else float("inf")
        overflow = not np.isfinite(gnorm_raw)
        gnorm = gnorm_raw / self.gas / scale  # true-norm units

        if stream_apply:
            # layer blocks already applied in the sink; finish embed/tail
            # (their own finiteness guard) — a wholly non-finite step only
            # skipped the offending blocks, reported via overflow
            for name in ("embed", "tail"):
                slot = grads.get(name)
                if not slot:
                    continue
                aligned = [slot[p] for p in self.store.master_paths(name)]
                if all(np.isfinite(np.sum(np.square(np.asarray(g, np.float32))))
                       for g in aligned):
                    self.store.apply_block(name, aligned, stream_coef, lr)
                else:
                    skipped_blocks.append(name)
            if hasattr(self.store, "flush"):
                self.store.flush()
            if skipped_blocks:
                logger.warning(f"param offload: skipped non-finite grad blocks "
                               f"{skipped_blocks[:4]}{'...' if len(skipped_blocks) > 4 else ''}")
            self.global_steps += 1
            self._last_gnorm = gnorm
            self._update_scaler(bool(skipped_blocks))
            # clip_coef: the coefficient ACTUALLY applied this step. The
            # streaming path clips by the PREVIOUS step's norm (the true
            # norm isn't known until every grad lands), so this surfaces
            # the approximation — runs comparing stream vs buffered
            # clipping can account for the one-step lag (step 1 applies
            # unclipped: coef 1.0)
            return {"loss": loss_sum / self.gas, "grad_norm": gnorm, "lr": lr,
                    "overflow": bool(skipped_blocks), "loss_scale": scale,
                    "clip_coef": stream_coef * scale}

        clip_coef = 1.0
        if not overflow:
            coef = 1.0 / self.gas / scale
            if self.clip and self.clip > 0:
                clip_coef = min(1.0, self.clip / (gnorm + 1e-6))
                coef *= clip_coef
            self.store.begin_step()
            for name in self.store.block_names():
                slot = grads.get(name)
                if not slot:
                    continue
                aligned = []
                for path in self.store.master_paths(name):
                    g = slot.get(path)
                    if g is None:
                        raise RuntimeError(f"param offload: no gradient fetched for "
                                           f"{name}/{path} (backward incomplete?)")
                    aligned.append(g)
                self.store.apply_block(name, aligned, coef, lr)
            if hasattr(self.store, "flush"):
                self.store.flush()
            self.global_steps += 1
        self._last_gnorm = gnorm
        self._update_scaler(overflow)
        # buffered path: clip_coef is exact (computed from THIS step's norm)
        return {"loss": loss_sum / self.gas, "grad_norm": gnorm, "lr": lr,
                "overflow": overflow, "loss_scale": scale,
                "clip_coef": clip_coef}

    def _update_scaler(self, overflow):
        """Host-side dynamic loss scaler (reference DynamicLossScaler
        semantics: halve on overflow, double after a clean window)."""
        if not self._scale_dynamic:
            return
        if overflow:
            self._scale = max(self._scale / 2.0, self._min_scale)
            self._good_steps = 0
            logger.warning(f"param offload fp16: overflow, loss scale -> {self._scale:g}")
        else:
            self._good_steps += 1
            if self._good_steps >= self._scale_window:
                self._scale *= 2.0
                self._good_steps = 0

    def eval_batch(self, batch):
        ids = np.asarray(batch["input_ids"])
        mask = batch.get("attention_mask")
        if "labels" in batch:
            labels = np.asarray(batch["labels"])
            shift = False
        else:
            labels = ids[:, 1:]
            shift = True
        valid = labels >= 0
        labels_c = np.maximum(labels, 0)
        model = self.model
        cd = self.compute_dtype

        def build():
            ef = jax.jit(lambda ep, i: model.stream_embed(ep, i).astype(cd))
            lf = jax.jit(lambda lp, h, m: model.stream_layer(lp, h, m).astype(cd),
                         donate_argnums=(1, ))
            tf = jax.jit(lambda tp, h, l, v: model.stream_tail_loss(tp, h, l, v, shift=shift))
            return ef, lf, tf

        ef, lf, tf = self._get(("eval", ids.shape[1], shift, mask is not None), build)
        # same streaming executor as the train loop: depth-k forward-order
        # parameter prefetch (ZeRO-Inference eval rides the pipeline too)
        ex = self.executor
        ex.invalidate()
        names = self._layer_names
        fwd = ["embed"] + names + ["tail"]
        with self.mesh:
            ep = ex.take("embed", ahead=fwd[1:])
            h = ef(ep, jnp.asarray(ids))
            del ep
            for l in range(self.L):
                lp = ex.take(names[l], ahead=fwd[l + 2:])
                h = lf(lp, h, None if mask is None else jnp.asarray(mask))
                del lp
            tp = ex.take("tail")
            loss = tf(tp, h, jnp.asarray(labels_c), jnp.asarray(valid))
        return {"loss": float(loss)}

    # -- ZeRO-Inference: generate from streamed weights ---------------------
    def generate(self, input_ids, max_new_tokens=16):
        """Greedy decode with layer-streamed weights: every decode step
        re-streams the L blocks host->HBM (bandwidth-bound by design — the
        ZeRO-Inference trade, reference docs/_posts/2022-09-10-zero-inference:
        HBM holds the KV cache + one block; weights live on the host)."""
        model = self.model
        cd = self.compute_dtype
        ids = np.asarray(input_ids)
        B, T0 = ids.shape
        S = T0 + max_new_tokens
        cfg = model.cfg
        cache = [(jnp.zeros((B, cfg.kv_heads, S, cfg.head_size), cd),
                  jnp.zeros((B, cfg.kv_heads, S, cfg.head_size), cd))
                 for _ in range(self.L)]

        def build():
            ef = jax.jit(lambda ep, i, ci: model.stream_embed(ep, i, ci).astype(cd))
            lf = jax.jit(lambda lp, h, kv, ci, cm: model.stream_layer_cached(lp, h, kv, ci, cm),
                         donate_argnums=(2, ))
            lg = jax.jit(lambda tp, h: model.stream_logits(tp, h[:, -1:, :]))
            return ef, lf, lg

        ef, lf, lg = self._get(("gen", ), build)
        out = list(ids.T)  # per-position columns
        pos = np.arange(S)
        # decode re-streams every weight block per token (bandwidth-bound by
        # design); the executor's forward-order look-ahead is what hides the
        # host link behind the per-layer compute here too
        ex = self.executor
        ex.invalidate()
        names = self._layer_names
        fwd = ["embed"] + names + ["tail"]
        with self.mesh:
            cur = jnp.asarray(ids)
            index = 0
            # step 0 streams the prompt and emits the first new token; each
            # later step streams one token — the LAST emitted token needs no
            # further forward (each full pass re-streams every weight block,
            # so an extra pass would cost 1/max_new_tokens of the decode)
            for step in range(max_new_tokens):
                # cache_index rides as a DEVICE scalar: a python int would be
                # baked static and retrace every decode step
                ci = jnp.asarray(index, jnp.int32)
                cm = jnp.asarray((pos < index + cur.shape[1]).astype(np.int32))[None].repeat(B, 0)
                ep = ex.take("embed", ahead=fwd[1:])
                h = ef(ep, cur, ci)
                del ep
                for l in range(self.L):
                    lp = ex.take(names[l], ahead=fwd[l + 2:])
                    h, cache[l] = lf(lp, h, cache[l], ci, cm)
                    del lp
                tp = ex.take("tail")
                logits = lg(tp, h)
                del tp, h
                index += cur.shape[1]
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                out.append(np.asarray(nxt))
                cur = nxt[:, None]
        return np.stack(out, axis=1)

    # -- host param import/export -------------------------------------------
    def set_params_from_tree(self, tree):
        """Overwrite the host master blocks from a full param pytree of host
        arrays (checkpoint import / HF weights / test parity); moments reset."""
        lk = self.plan["layer_key"]
        host = jax.tree_util.tree_map(lambda x: np.asarray(x, np.float32), tree)
        self.store.add_block("embed", {k: host[k] for k in self.plan["embed"]})
        self.store.add_block("tail", {k: host[k] for k in self.plan["tail"]
                                      if k not in self.plan["embed"]})
        for l in range(self.L):
            self.store.add_block(f"layer{l:05d}",
                                 jax.tree_util.tree_map(lambda x: np.ascontiguousarray(x[l]),
                                                        host[lk]))

    def get_params_tree(self, dtype=np.float32):
        """Assemble the full param pytree on host (export / tests). DRAM cost
        is one full model copy — never materialized on device. Leaves are
        OWNED copies: a same-dtype ``np.asarray`` would alias the live
        masters and silently mutate the caller's tree as training steps."""
        out = {}
        for k in self.plan["embed"]:
            out[k] = jax.tree_util.tree_map(lambda x: np.array(x, dtype, copy=True),
                                            self._host_master("embed")[k])
        tail = self._host_master("tail")
        for k in self.plan["tail"]:
            if k not in out:
                out[k] = jax.tree_util.tree_map(lambda x: np.array(x, dtype, copy=True),
                                                tail[k])
        layers = [self._host_master(f"layer{l:05d}") for l in range(self.L)]
        out[self.plan["layer_key"]] = jax.tree_util.tree_map(
            lambda *xs: np.stack([np.asarray(x, dtype) for x in xs]), *layers)
        return out

    def _host_master(self, name):
        b = self.store.blocks[name]
        if "master" in b:
            return b["master"]
        # nvme tier: masters live on disk; reassemble from the flat file
        n = self.store._block_size(name)
        buf = aligned_empty((n, ), np.float32)
        self.store._read_h.async_pread(buf, self.store._file(name, "master"))
        self.store._read_h.wait()
        out, off = {}, 0
        flat = []
        for path, shape in self.store._meta[name]:
            k = int(np.prod(shape, dtype=np.int64))
            flat.append((path, buf[off:off + k].reshape(shape)))
            off += k
        return _unflatten_slash(flat)

    # -- checkpoint ---------------------------------------------------------
    def save_checkpoint(self, tag_dir):
        os.makedirs(tag_dir, exist_ok=True)
        self.store.save_to(tag_dir)
        with open(os.path.join(tag_dir, "param_stream.json"), "w") as f:
            json.dump({"global_steps": self.global_steps}, f)

    def load_checkpoint(self, tag_dir, load_optimizer_states=True):
        if not self.store.load_from(tag_dir, load_optimizer_states=load_optimizer_states):
            return False
        if not load_optimizer_states:
            self.global_steps = 0
            return True
        p = os.path.join(tag_dir, "param_stream.json")
        if os.path.isfile(p):
            with open(p) as f:
                self.global_steps = int(json.load(f).get("global_steps", self.store.t))
        else:
            self.global_steps = self.store.t
        return True


def _nbytes_blocks(store):
    return sum(_nbytes(b.get("bf16", {})) for b in store.blocks.values())


def _unflatten_slash(flat):
    """[("a/b/c", arr), ...] -> nested dict."""
    out = {}
    for path, arr in flat:
        node = out
        parts = path.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return out
