"""ZeRO as sharding rules.

This module is the TPU-native replacement for the reference's hook-driven
ZeRO machinery (``runtime/zero/partition_parameters.py`` :601/:874/:940,
``partitioned_param_coordinator.py`` :43, ``parameter_offload.py`` :201 —
~2.6k LoC of monkey-patching and prefetch scheduling). Here the same
semantics are *declared* as ``jax.sharding`` placements and XLA's SPMD
partitioner + latency-hiding scheduler perform the all-gather/reduce-scatter
scheduling that DeepSpeed drives by hand (SURVEY §7 design translation):

- stage 0: params, grads, optimizer state replicated over DP.
- stage 1: optimizer state (and fp32 master params) sharded over DP.
- stage 2: + gradients reduce-scattered into the same sharding.
- stage 3: + model params sharded over DP; XLA all-gathers just-in-time
  per layer and frees after use (the fetch/release/prefetch coordinator
  becomes the compiler's scheduling problem).

DeepSpeed concepts that survive as rules:
- ``stage3_param_persistence_threshold`` → small params stay replicated.
- MoE-aware groups (``moe/utils.py``) → expert params shard over the
  ``data`` axis only; dense params over ``('expert','data')``.
- TP (Megatron-style, reference delegates to user mpu) → per-param
  PartitionSpec rules matched by path regex, applied before DP sharding.
"""

import re

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...comm import comm as dist
from ...utils.logging import logger
from .config import ZeroStageEnum


def _path_str(path):
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _spec_axes(spec):
    """Flatten axis names used in a PartitionSpec."""
    used = []
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.extend(entry)
        else:
            used.append(entry)
    return used


class TensorParallelRules:
    """Ordered (regex, PartitionSpec) rules; first match wins.

    The TPU-native form of inference AutoTP's row/col parser
    (``module_inject/auto_tp.py:84``) generalized to training: rules name
    which dims of which params split over the ``tensor`` (and ``expert``)
    axes.
    """

    def __init__(self, rules=()):
        self.rules = [(re.compile(pat), P(*spec) if not isinstance(spec, P) else spec) for pat, spec in rules]

    def match(self, path_str, ndim):
        for pat, spec in self.rules:
            if pat.search(path_str):
                if len(spec) > ndim:
                    raise ValueError(f"TP rule {pat.pattern} spec {spec} has more dims than param "
                                     f"{path_str} (ndim={ndim})")
                return P(*(tuple(spec) + (None, ) * (ndim - len(spec))))
        return None

    def __bool__(self):
        return bool(self.rules)


def best_shardable_dim(shape, size, taken):
    """Largest dim divisible by ``size`` and not already sharded; None if none.

    Replaces DeepSpeed's flat-buffer padding (``partition_parameters.py:1091``
    pads 1-D partitions): XLA shards a real tensor dim instead, so no padding
    or flattening is needed.
    """
    best = None
    for d, extent in enumerate(shape):
        if d in taken:
            continue
        if extent % size == 0 and extent >= size:
            if best is None or extent > shape[best]:
                best = d
    return best


class ShardingPlanner:
    """Plans NamedShardings for params / grads / optimizer state.

    ``fsdp_axes``: mesh axes forming the ZeRO data-parallel group
    (``('expert','data')`` for dense params; expert params drop ``'expert'``).
    """

    def __init__(self, mesh, zero_config=None, tp_rules=None, expert_pattern=None,
                 pipe_pattern=None):
        self.mesh = mesh
        self.zero = zero_config
        self.stage = zero_config.stage if zero_config is not None else 0
        self.tp_rules = tp_rules if isinstance(tp_rules, TensorParallelRules) else TensorParallelRules(tp_rules or ())
        self.expert_pattern = re.compile(expert_pattern) if expert_pattern else None
        self.pipe_pattern = re.compile(pipe_pattern) if pipe_pattern else None
        self.persistence_threshold = (zero_config.stage3_param_persistence_threshold
                                      if zero_config is not None else int(1e5))

    # -- single-leaf planning ------------------------------------------------
    def _validate(self, spec, shape, path_str):
        """Drop sharding entries whose dim extent isn't divisible by the axis
        size (e.g. 2 kv-heads under tensor=4 fall back to replication)."""
        entries = list(spec)
        changed = False
        for d, entry in enumerate(entries):
            if entry is None:
                continue
            axes = entry if isinstance(entry, (tuple, list)) else (entry, )
            size = int(np.prod([self.mesh.shape[a] for a in axes]))
            if d >= len(shape) or shape[d] % size != 0:
                entries[d] = None
                changed = True
        if changed:
            logger.debug(f"{path_str}: shape {shape} not divisible by rule {spec}; "
                         f"relaxed to {P(*entries)}")
        return P(*entries)

    def _apply_pipe(self, spec, shape, path_str):
        """Stage-partition layer-stacked params: leading (layer) dim over
        ``pipe`` (the sharding form of reference ``PipelineModule``'s layer
        assignment, ``pipe/module.py:353``)."""
        pipe = self.mesh.shape[dist.PIPE_AXIS]
        if pipe == 1 or self.pipe_pattern is None or not self.pipe_pattern.search(path_str):
            return spec
        if not shape or shape[0] % pipe != 0:
            logger.warning(f"{path_str}: leading dim {shape and shape[0]} not divisible by "
                           f"pipe={pipe}; layer stack left unsharded over pipe")
            return spec
        entries = list(spec)
        if entries[0] is None:
            entries[0] = dist.PIPE_AXIS
        return P(*entries)

    def _dp_axes_for(self, path_str):
        if self.expert_pattern is not None and self.expert_pattern.search(path_str):
            return (dist.DATA_AXIS, )
        return (dist.EXPERT_AXIS, dist.DATA_AXIS)

    def _dp_size(self, axes):
        return int(np.prod([self.mesh.shape[a] for a in axes]))

    def _apply_dp(self, spec, shape, path_str):
        """Append the ZeRO dp axes to the largest free divisible dim."""
        axes = [a for a in self._dp_axes_for(path_str) if self.mesh.shape[a] > 1]
        if not axes:
            return spec
        size = self._dp_size(axes)
        taken = {d for d, e in enumerate(spec) if e is not None}
        dim = best_shardable_dim(shape, size, taken)
        if dim is None:
            logger.debug(f"param {path_str} shape {shape} not divisible by dp={size}; replicating")
            return spec
        entries = list(spec)
        entries[dim] = tuple(axes) if len(axes) > 1 else axes[0]
        return P(*entries)

    def param_spec(self, path_str, shape):
        """PartitionSpec for a *model* (compute) parameter."""
        ndim = len(shape)
        spec = self.tp_rules.match(path_str, ndim) or P(*([None] * ndim))
        spec = self._validate(spec, shape, path_str)
        spec = self._apply_pipe(spec, shape, path_str)
        if self.stage >= ZeroStageEnum.weights:
            n_elem = int(np.prod(shape)) if shape else 1
            if n_elem > self.persistence_threshold:
                spec = self._apply_dp(spec, shape, path_str)
        return spec

    def master_spec(self, path_str, shape):
        """PartitionSpec for fp32 master params + optimizer moments."""
        ndim = len(shape)
        spec = self.tp_rules.match(path_str, ndim) or P(*([None] * ndim))
        spec = self._validate(spec, shape, path_str)
        spec = self._apply_pipe(spec, shape, path_str)
        if self.stage >= ZeroStageEnum.optimizer_states:
            spec = self._apply_dp(spec, shape, path_str)
        return spec

    def grad_spec(self, path_str, shape):
        """PartitionSpec for gradients/accumulators: stage >= 2 scatters."""
        ndim = len(shape)
        spec = self.tp_rules.match(path_str, ndim) or P(*([None] * ndim))
        spec = self._validate(spec, shape, path_str)
        spec = self._apply_pipe(spec, shape, path_str)
        if self.stage >= ZeroStageEnum.gradients:
            spec = self._apply_dp(spec, shape, path_str)
        return spec

    def offload_spec(self, path_str, shape):
        """PartitionSpec for *offloaded* optimizer state and the gradients
        feeding it: always scattered over the ZeRO dp axes regardless of
        stage. ZeRO-Offload partitions optimizer state per DP rank so each
        host steps only its shard (reference ``stage_1_and_2.py:1031`` CPU
        accumulation of this rank's partition; ``stage3.py:463``)."""
        ndim = len(shape)
        spec = self.tp_rules.match(path_str, ndim) or P(*([None] * ndim))
        spec = self._validate(spec, shape, path_str)
        spec = self._apply_pipe(spec, shape, path_str)
        return self._apply_dp(spec, shape, path_str)

    # -- pytree planning -----------------------------------------------------
    def _tree_specs(self, params, leaf_fn):
        def plan(path, leaf):
            shape = np.shape(leaf) if not hasattr(leaf, "shape") else tuple(leaf.shape)
            return leaf_fn(_path_str(path), shape)

        return jax.tree_util.tree_map_with_path(plan, params)

    def param_specs(self, params):
        return self._tree_specs(params, self.param_spec)

    def master_specs(self, params):
        return self._tree_specs(params, self.master_spec)

    def grad_specs(self, params):
        return self._tree_specs(params, self.grad_spec)

    def offload_specs(self, params):
        return self._tree_specs(params, self.offload_spec)

    def shardings(self, specs):
        return jax.tree_util.tree_map(lambda s: NamedSharding(self.mesh, s),
                                      specs,
                                      is_leaf=lambda x: isinstance(x, P))

    def param_shardings(self, params):
        return self.shardings(self.param_specs(params))

    def master_shardings(self, params):
        return self.shardings(self.master_specs(params))

    def opt_state_shardings(self, opt_state, params):
        """Optimizer state leaves that mirror a param get the master sharding;
        scalars (step counts) replicate."""
        master = self.master_specs(params)
        flat_master, _ = jax.tree_util.tree_flatten(master)
        by_shape = {}
        for p_leaf, spec in zip(jax.tree_util.tree_leaves(params), flat_master):
            by_shape.setdefault(tuple(p_leaf.shape), spec)

        def plan(leaf):
            shape = tuple(np.shape(leaf))
            spec = by_shape.get(shape)
            if spec is None:
                spec = P()
            return NamedSharding(self.mesh, spec)

        return jax.tree_util.tree_map(plan, opt_state)

    def replicated(self):
        return NamedSharding(self.mesh, P())

    def describe(self, params):
        """Human-readable plan dump (ds_report-style aid)."""
        lines = []

        def show(path, leaf):
            ps = _path_str(path)
            lines.append(f"{ps:60s} {str(tuple(leaf.shape)):20s} param={self.param_spec(ps, tuple(leaf.shape))} "
                         f"master={self.master_spec(ps, tuple(leaf.shape))}")
            return leaf

        jax.tree_util.tree_map_with_path(show, params)
        return "\n".join(lines)
