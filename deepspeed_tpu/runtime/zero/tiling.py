"""TiledLinear: memory-bounded large linear layers.

Counterpart of reference ``runtime/zero/tiling.py:32`` (``TiledLinear``):
split a huge linear into input/output tiles so no single matmul (or its
saved residuals) materializes the full weight or activation at once. Under
XLA much of the reference's motivation is subsumed by ZeRO-3 sharding +
rematerialization, but the explicit tiling remains useful when one logical
weight exceeds a comfortable HBM working set (vocab projections, wide MLPs)
— each tile's compute is wrapped in ``jax.checkpoint`` so backward re-runs
one tile at a time instead of saving every tile's residuals.

Semantics match the reference: ``in_splits`` cut the contraction dim (tiles
accumulate), ``out_splits`` cut the feature dim (tiles concatenate); the
kernel is stored UNSPLIT so checkpoints and sharding rules see one logical
(in, out) parameter.
"""

import jax
import jax.numpy as jnp

import flax.linen as nn


def tiled_linear(x, kernel, bias=None, in_splits=1, out_splits=1):
    """y = x @ kernel (+ bias), computed tile-by-tile.

    x: (..., in); kernel: (in, out). ``in`` % in_splits == 0 and
    ``out`` % out_splits == 0 (reference requires the same divisibility).
    """
    n_in, n_out = kernel.shape
    if n_in % in_splits or n_out % out_splits:
        raise ValueError(f"kernel {kernel.shape} not divisible by splits "
                         f"({in_splits}, {out_splits})")
    ti, to = n_in // in_splits, n_out // out_splits

    @jax.checkpoint
    def one_tile(i, j):
        xs = jax.lax.dynamic_slice_in_dim(x, i * ti, ti, axis=-1)
        ks = jax.lax.dynamic_slice_in_dim(
            jax.lax.dynamic_slice_in_dim(kernel, i * ti, ti, axis=0), j * to, to, axis=1)
        # fp32 partials: the MXU accumulates fp32 anyway; rounding each
        # tile's output to bf16 would add one rounding per in-split vs dense
        return jnp.matmul(xs, ks.astype(xs.dtype),
                          preferred_element_type=jnp.float32)

    def out_tile(j):
        acc = one_tile(0, j)
        for i in range(1, in_splits):
            acc = acc + one_tile(i, j)
        return acc.astype(x.dtype)

    y = jnp.concatenate([out_tile(j) for j in range(out_splits)], axis=-1) \
        if out_splits > 1 else out_tile(0)
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


class TiledLinear(nn.Module):
    """Flax module with the reference's constructor surface (``tiling.py:32``
    in_features/out_features/in_splits/out_splits)."""

    features: int
    in_splits: int = 1
    out_splits: int = 1
    use_bias: bool = True
    dtype: object = jnp.float32

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", nn.initializers.normal(0.02),
                            (x.shape[-1], self.features), jnp.float32)
        bias = (self.param("bias", nn.initializers.zeros, (self.features, ), jnp.float32)
                if self.use_bias else None)
        return tiled_linear(x.astype(self.dtype), kernel, bias,
                            self.in_splits, self.out_splits)
