"""Serving gateway: streaming HTTP frontend over the continuous-batching
scheduler — admission control, per-tenant fair queuing, graceful lifecycle.

Quickstart (see ``benchmarks/SERVING.md`` "Gateway" for the full protocol)::

    python -m deepspeed_tpu.serving --model gpt2-large --port 8000

    curl -N localhost:8000/v1/completions -d \\
      '{"prompt": [5, 6, 7], "max_tokens": 16, "stream": true}'
"""

from ..inference.config import GatewayConfig  # noqa: F401
from .controller import FleetController, FleetSignals  # noqa: F401
from .fair_queue import FairQueue, QueueFull  # noqa: F401
from .replica import Replica, ReplicaSet  # noqa: F401
from .gateway import Gateway  # noqa: F401
from .router import Router, WorkerAgent  # noqa: F401
