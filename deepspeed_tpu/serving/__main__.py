"""``python -m deepspeed_tpu.serving``: run the serving gateway.

Builds an :class:`InferenceEngine` (continuous batching on), binds the HTTP
gateway, and serves until SIGTERM/SIGINT — which trigger a graceful drain:
readiness flips to 503, admitted requests finish, telemetry flushes, and
the process exits 0. Prints one ``GATEWAY_READY`` JSON line (with the bound
port — ``--port 0`` binds an ephemeral one) once accepting traffic.
"""

import argparse
import json
import signal
import sys


def build_parser():
    p = argparse.ArgumentParser(prog="python -m deepspeed_tpu.serving",
                                description=__doc__.splitlines()[0])
    p.add_argument("--model", default="gpt2-large",
                   help="zoo model preset name (see deepspeed_tpu.models)")
    p.add_argument("--config", default=None,
                   help="path to a DeepSpeedInferenceConfig JSON (flags below "
                        "override its gateway/serving sections)")
    p.add_argument("--checkpoint", default=None, help="weights to load")
    p.add_argument("--dtype", default=None, help="serving dtype (bf16/int8/...)")
    p.add_argument("--host", default=None)
    p.add_argument("--port", type=int, default=None,
                   help="0 binds an ephemeral port (printed in GATEWAY_READY)")
    p.add_argument("--num-slots", type=int, default=None,
                   help="decode batch slots (continuous_batching.num_slots)")
    p.add_argument("--replicas", type=int, default=None,
                   help="scheduler replicas behind the gateway "
                        "(continuous_batching.replicas): independent slot "
                        "pools, one weight tree, one compiled program set")
    p.add_argument("--disagg-roles", default=None,
                   help="comma-separated per-replica phase roles "
                        "(prefill/decode/mixed), e.g. 'prefill,decode' — "
                        "enables continuous_batching.disaggregation: new "
                        "prompts place on prefill-capable replicas and "
                        "finished prefills migrate their KV to decode "
                        "replicas (runtime override: POST "
                        "/v1/replicas/<i>/role)")
    p.add_argument("--autoscale", action="store_true",
                   help="enable the elastic fleet controller "
                        "(continuous_batching.autoscaler.enabled): SLO-driven "
                        "replica scaling, phase re-balancing, and brownout "
                        "shedding, ticked from the serving pump (runtime "
                        "toggle + dry-run: POST /v1/autoscaler)")
    p.add_argument("--max-queue-depth", type=int, default=None)
    p.add_argument("--default-max-tokens", type=int, default=None)
    p.add_argument("--request-timeout-s", type=float, default=None)
    p.add_argument("--drain-timeout-s", type=float, default=None)
    p.add_argument("--kernel-inject", action="store_true",
                   help="enable the Pallas kernel-injected decode path")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    cfg = {}
    if args.config:
        with open(args.config) as f:
            cfg = json.load(f)
    cfg.setdefault("continuous_batching", {})["enabled"] = True
    if args.num_slots is not None:
        cfg["continuous_batching"]["num_slots"] = args.num_slots
    if args.replicas is not None:
        cfg["continuous_batching"]["replicas"] = args.replicas
    if args.autoscale:
        # merge: keep any tuned autoscaler thresholds from the config file
        cfg["continuous_batching"].setdefault("autoscaler", {})["enabled"] = True
    if args.disagg_roles is not None:
        # merge, don't replace: a config file's migrate_min_tokens (etc.)
        # must survive the CLI setting the roles
        dg = cfg["continuous_batching"].setdefault("disaggregation", {})
        dg["enabled"] = True
        dg["roles"] = [r.strip() for r in args.disagg_roles.split(",") if r.strip()]
    if args.dtype is not None:
        cfg["dtype"] = args.dtype
    if args.checkpoint is not None:
        cfg["checkpoint"] = args.checkpoint
    if args.kernel_inject:
        cfg["kernel_inject"] = True
    gw_cfg = cfg.setdefault("gateway", {})
    for flag, key in (("host", "host"), ("port", "port"),
                      ("max_queue_depth", "max_queue_depth"),
                      ("default_max_tokens", "default_max_tokens"),
                      ("request_timeout_s", "request_timeout_s"),
                      ("drain_timeout_s", "drain_timeout_s")):
        val = getattr(args, flag)
        if val is not None:
            gw_cfg[key] = val

    import deepspeed_tpu
    from deepspeed_tpu.serving import Gateway

    engine = deepspeed_tpu.init_inference(args.model, config=cfg)
    gateway = Gateway(engine)
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: gateway.begin_drain())
    if hasattr(signal, "SIGUSR1"):
        # operator-forced flight-recorder dump (kill -USR1 <pid>): the
        # handler only flags the request — the pump thread performs the
        # dump (taking sink locks in signal context can self-deadlock)
        signal.signal(signal.SIGUSR1,
                      lambda *_: gateway.request_flight_dump("sigusr1"))
    return gateway.run()


if __name__ == "__main__":
    sys.exit(main())
