"""``python -m deepspeed_tpu.serving``: run the serving gateway.

Builds an :class:`InferenceEngine` (continuous batching on), binds the HTTP
gateway, and serves until SIGTERM/SIGINT — which trigger a graceful drain:
readiness flips to 503, admitted requests finish, telemetry flushes, and
the process exits 0. Prints one ``GATEWAY_READY`` JSON line (with the bound
port — ``--port 0`` binds an ephemeral one) once accepting traffic.

Multi-host modes (``serving/router.py``):

- ``--worker --router-url http://HOST:PORT``: same gateway, but the process
  joins a cross-process fleet — it registers with the router, heartbeats
  capacity signals, and serves its slice of the networked prefix/handoff
  store. ``--worker-role prefill`` additionally hands finished prefills off
  to decode workers through that store.
- ``--router``: no model at all — run the router tier (placement + proxy +
  store directory). Prints one ``ROUTER_READY`` JSON line; optionally
  spawns a local worker fleet (``--spawn-workers N``) for smoke tests.
"""

import argparse
import json
import os
import signal
import subprocess
import sys


def build_parser():
    p = argparse.ArgumentParser(prog="python -m deepspeed_tpu.serving",
                                description=__doc__.splitlines()[0])
    p.add_argument("--model", default="gpt2-large",
                   help="zoo model preset name (see deepspeed_tpu.models)")
    p.add_argument("--config", default=None,
                   help="path to a DeepSpeedInferenceConfig JSON (flags below "
                        "override its gateway/serving sections)")
    p.add_argument("--checkpoint", default=None, help="weights to load")
    p.add_argument("--dtype", default=None, help="serving dtype (bf16/int8/...)")
    p.add_argument("--host", default=None)
    p.add_argument("--port", type=int, default=None,
                   help="0 binds an ephemeral port (printed in GATEWAY_READY)")
    p.add_argument("--num-slots", type=int, default=None,
                   help="decode batch slots (continuous_batching.num_slots)")
    p.add_argument("--replicas", type=int, default=None,
                   help="scheduler replicas behind the gateway "
                        "(continuous_batching.replicas): independent slot "
                        "pools, one weight tree, one compiled program set")
    p.add_argument("--disagg-roles", default=None,
                   help="comma-separated per-replica phase roles "
                        "(prefill/decode/mixed), e.g. 'prefill,decode' — "
                        "enables continuous_batching.disaggregation: new "
                        "prompts place on prefill-capable replicas and "
                        "finished prefills migrate their KV to decode "
                        "replicas (runtime override: POST "
                        "/v1/replicas/<i>/role)")
    p.add_argument("--autoscale", action="store_true",
                   help="enable the elastic fleet controller "
                        "(continuous_batching.autoscaler.enabled): SLO-driven "
                        "replica scaling, phase re-balancing, and brownout "
                        "shedding, ticked from the serving pump (runtime "
                        "toggle + dry-run: POST /v1/autoscaler)")
    p.add_argument("--max-queue-depth", type=int, default=None)
    p.add_argument("--default-max-tokens", type=int, default=None)
    p.add_argument("--request-timeout-s", type=float, default=None)
    p.add_argument("--drain-timeout-s", type=float, default=None)
    p.add_argument("--kernel-inject", action="store_true",
                   help="enable the Pallas kernel-injected decode path")
    p.add_argument("--hierarchical-kv", action="store_true",
                   help="enable the hierarchical KV tier "
                        "(continuous_batching.hierarchical_kv.enabled) — the "
                        "networked prefix/handoff store rides on it, so "
                        "prefill-role workers require it")
    mh = p.add_argument_group("multi-host serving (serving/router.py)")
    mh.add_argument("--worker", action="store_true",
                    help="join a cross-process worker fleet: register with "
                         "--router-url, heartbeat capacity signals, serve "
                         "this process's slice of the networked "
                         "prefix/handoff store")
    mh.add_argument("--router-url", default=None,
                    help="router base URL the worker registers with")
    mh.add_argument("--worker-id", default=None,
                    help="fleet-unique worker id (default w<pid>)")
    mh.add_argument("--worker-role", default=None,
                    choices=("prefill", "decode", "mixed"),
                    help="process-level phase role (default mixed); "
                         "'prefill' hands finished prefills to decode "
                         "workers over the networked store")
    mh.add_argument("--heartbeat-s", type=float, default=None,
                    help="heartbeat cadence (multihost.heartbeat_interval_s)")
    mh.add_argument("--lease-s", type=float, default=None,
                    help="handoff claim deadline (multihost.lease_s)")
    mh.add_argument("--advertise-host", default=None,
                    help="host other processes dial to reach this worker")
    mh.add_argument("--migrate-min-tokens", type=int, default=None,
                    help="colocate threshold for cross-process handoff")
    mh.add_argument("--router", action="store_true",
                    help="run the ROUTER tier instead of a gateway (no "
                         "model): placement + proxy + store directory")
    mh.add_argument("--heartbeat-timeout-s", type=float, default=None,
                    help="router: a worker silent this long stops getting "
                         "placements")
    mh.add_argument("--spawn-workers", type=int, default=0,
                    help="router: also spawn N local worker processes "
                         "(inheriting --model/--dtype/... flags); smoke "
                         "tests and single-host fleets")
    mh.add_argument("--spawn-roles", default=None,
                    help="router: comma-separated roles for spawned workers "
                         "(e.g. 'prefill,decode'); default all mixed")
    return p


def run_router(args):
    """``--router``: the placement/proxy/directory tier. No engine, no JAX —
    the router is pure stdlib networking and can front any worker fleet."""
    from deepspeed_tpu.serving.router import Router

    router = Router(host=args.host or "127.0.0.1",
                    port=args.port if args.port is not None else 0,
                    heartbeat_timeout_s=args.heartbeat_timeout_s or 10.0)
    procs = []

    def on_ready():
        print(json.dumps({"event": "ROUTER_READY", "host": router.host,
                          "port": router.port}), flush=True)
        roles = ([r.strip() for r in args.spawn_roles.split(",") if r.strip()]
                 if args.spawn_roles else [])
        for i in range(args.spawn_workers):
            cmd = [sys.executable, "-m", "deepspeed_tpu.serving",
                   "--worker", "--router-url",
                   f"http://{router.host}:{router.port}",
                   "--worker-id", f"w{i}", "--model", args.model,
                   "--host", router.host, "--port", "0"]
            role = roles[i] if i < len(roles) else "mixed"
            cmd += ["--worker-role", role]
            if role == "prefill" or args.hierarchical_kv:
                cmd.append("--hierarchical-kv")
            for flag, name in (("dtype", "--dtype"),
                               ("checkpoint", "--checkpoint"),
                               ("config", "--config"),
                               ("num_slots", "--num-slots"),
                               ("replicas", "--replicas")):
                val = getattr(args, flag)
                if val is not None:
                    cmd += [name, str(val)]
            procs.append(subprocess.Popen(cmd))

    def shutdown(*_):
        for proc in procs:
            proc.terminate()
        router.close()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, shutdown)
    try:
        router.run(on_ready)
    finally:
        for proc in procs:
            proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
    return 0


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.router:
        return run_router(args)
    if args.worker and not args.router_url:
        build_parser().error("--worker requires --router-url")
    cfg = {}
    if args.config:
        with open(args.config) as f:
            cfg = json.load(f)
    cfg.setdefault("continuous_batching", {})["enabled"] = True
    if args.num_slots is not None:
        cfg["continuous_batching"]["num_slots"] = args.num_slots
    if args.replicas is not None:
        cfg["continuous_batching"]["replicas"] = args.replicas
    if args.autoscale:
        # merge: keep any tuned autoscaler thresholds from the config file
        cfg["continuous_batching"].setdefault("autoscaler", {})["enabled"] = True
    if args.disagg_roles is not None:
        # merge, don't replace: a config file's migrate_min_tokens (etc.)
        # must survive the CLI setting the roles
        dg = cfg["continuous_batching"].setdefault("disaggregation", {})
        dg["enabled"] = True
        dg["roles"] = [r.strip() for r in args.disagg_roles.split(",") if r.strip()]
    if args.dtype is not None:
        cfg["dtype"] = args.dtype
    if args.checkpoint is not None:
        cfg["checkpoint"] = args.checkpoint
    if args.kernel_inject:
        cfg["kernel_inject"] = True
    if args.hierarchical_kv:
        cfg["continuous_batching"].setdefault("hierarchical_kv",
                                              {})["enabled"] = True
    mh_cfg = cfg["continuous_batching"].setdefault("multihost", {})
    for flag, key in (("router_url", "router_url"),
                      ("worker_id", "worker_id"),
                      ("worker_role", "worker_role"),
                      ("heartbeat_s", "heartbeat_interval_s"),
                      ("lease_s", "lease_s"),
                      ("advertise_host", "advertise_host"),
                      ("migrate_min_tokens", "migrate_min_tokens")):
        val = getattr(args, flag)
        if val is not None:
            mh_cfg[key] = val
    gw_cfg = cfg.setdefault("gateway", {})
    for flag, key in (("host", "host"), ("port", "port"),
                      ("max_queue_depth", "max_queue_depth"),
                      ("default_max_tokens", "default_max_tokens"),
                      ("request_timeout_s", "request_timeout_s"),
                      ("drain_timeout_s", "drain_timeout_s")):
        val = getattr(args, flag)
        if val is not None:
            gw_cfg[key] = val

    import deepspeed_tpu
    from deepspeed_tpu.serving import Gateway

    engine = deepspeed_tpu.init_inference(args.model, config=cfg)
    gateway = Gateway(engine)
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: gateway.begin_drain())
    if hasattr(signal, "SIGUSR1"):
        # operator-forced flight-recorder dump (kill -USR1 <pid>): the
        # handler only flags the request — the pump thread performs the
        # dump (taking sink locks in signal context can self-deadlock)
        signal.signal(signal.SIGUSR1,
                      lambda *_: gateway.request_flight_dump("sigusr1"))
    if args.worker:
        from deepspeed_tpu.serving.router import WorkerAgent

        gateway.start_background()
        agent = WorkerAgent(
            gateway, args.router_url,
            mh_cfg.get("worker_id") or f"w{os.getpid()}",
            role=mh_cfg.get("worker_role", "mixed"),
            heartbeat_s=mh_cfg.get("heartbeat_interval_s", 2.0),
            lease_s=mh_cfg.get("lease_s", 30.0),
            advertise_host=mh_cfg.get("advertise_host"),
            migrate_min_tokens=mh_cfg.get("migrate_min_tokens", 0))
        agent.attach()
        agent.start()
        print(json.dumps({"event": "GATEWAY_READY", "host": gateway.host,
                          "port": gateway.port, "worker_id": agent.wid,
                          "role": agent.role}), flush=True)
        while not gateway.wait_drained(0.2):
            pass
        agent.stop()
        return 0
    return gateway.run()


if __name__ == "__main__":
    sys.exit(main())
