"""Shared capacity/backoff math for the gateway and the multi-host router.

The gateway's ``Retry-After`` estimate and the router's fleet-wide backoff
must agree — both answer "how long until the current backlog drains through
the available slot pools at the measured per-request service time". Before
this module the math lived inline in ``Gateway._retry_after`` and silently
assumed every slot it divided by was local AND available, while the backlog
sums it divided iterated ALL replicas (including drained / pending-drain
ones) — a fleet mid-scale-down double-counted retiring backlogs against a
capacity surface that had already stopped advertising them.

Everything here works on a plain **capacity-signals dict** so the router can
merge per-worker signals it received over the wire without holding any
scheduler objects:

    {"queued":            fresh requests not yet placed (gateway fair queue),
     "inflight":          admitted requests not yet finished,
     "sched_backlog":     per-scheduler queue depths, AVAILABLE replicas only,
     "prefill_backlog":   same, prefill-capable AND available replicas only,
     "total_slots":       slots across available replicas,
     "prefill_slots":     slots across available prefill-capable replicas,
     "decode_slots":      slots across available decode-capable replicas,
     "ema_service_s":     per-request service-time EMA or None,
     "disaggregated":     phase-split fleet (True routes the phase-aware
                          estimate: a request needs a prefill slot first and
                          a decode slot after, and the pools are disjoint)}

``Gateway.capacity_signals()`` builds this dict locally; workers ship it in
heartbeats; the router merges the fleet's dicts with :func:`merge_signals`
and runs the SAME :func:`estimate_retry_after` the single-process gateway
runs. One formula, every surface.
"""


def estimate_retry_after(sig, cap_s):
    """Integer Retry-After seconds (RFC 9110) from a capacity-signals dict.

    Identical math to the pre-refactor ``Gateway._retry_after``: with no
    service EMA yet, a conservative ``1 + depth // slots``; with an EMA,
    ``(depth + 1) * ema / slots``. Phase-aware when ``disaggregated`` — the
    estimate is the WORSE of (queued work / prefill capacity) and
    (in-flight work / decode capacity), not the blended depth over the
    blended fleet (which under-advertises exactly when one phase is the
    bottleneck). Floor 1s, capped, rounded up.
    """
    ema = sig.get("ema_service_s")

    def est(depth, slots):
        if ema is None:
            return 1 + depth // max(1, slots)
        return (depth + 1) * ema / max(1, slots)

    if sig.get("disaggregated"):
        pre_depth = int(sig.get("queued", 0)) + int(sig.get("prefill_backlog", 0))
        # inflight already covers parked handoffs (their handles are not
        # done) and soon-to-decode prefills — adding a migration count on
        # top would double-count each parked request
        dec_depth = int(sig.get("inflight", 0))
        val = max(est(pre_depth, int(sig.get("prefill_slots", 0))),
                  est(dec_depth, int(sig.get("decode_slots", 0))))
    else:
        depth = (int(sig.get("queued", 0)) + int(sig.get("inflight", 0))
                 + int(sig.get("sched_backlog", 0)))
        val = est(depth, int(sig.get("total_slots", 1)))
    return max(1, min(int(cap_s), int(val + 0.999)))


def merge_signals(signals):
    """Fold per-worker capacity-signals dicts into one fleet-wide dict.

    ``signals`` is an iterable of dicts as produced by
    ``Gateway.capacity_signals()`` — the caller filters to LIVE,
    non-draining workers first (a drained or dead worker contributes
    neither backlog nor slots; including either side alone would skew the
    estimate). Depths and slots sum; the EMA averages over workers that
    have one (None when none do); the fleet is disaggregated when any
    worker is phase-split — or when the workers themselves form the split
    (some prefill-only, some decode-only processes).
    """
    out = {"queued": 0, "inflight": 0, "sched_backlog": 0,
           "prefill_backlog": 0, "total_slots": 0, "prefill_slots": 0,
           "decode_slots": 0, "ema_service_s": None, "disaggregated": False}
    emas = []
    for sig in signals:
        if not sig:
            continue
        for key in ("queued", "inflight", "sched_backlog", "prefill_backlog",
                    "total_slots", "prefill_slots", "decode_slots"):
            out[key] += int(sig.get(key, 0))
        if sig.get("disaggregated"):
            out["disaggregated"] = True
        ema = sig.get("ema_service_s")
        if ema is not None:
            emas.append(float(ema))
    if emas:
        out["ema_service_s"] = sum(emas) / len(emas)
    # process-level phase split: a fleet of one prefill-role worker and one
    # decode-role worker is disaggregated even though each worker's local
    # fleet reports mixed math over its own (single-phase) pool
    if (not out["disaggregated"] and out["total_slots"]
            and (out["prefill_slots"] < out["total_slots"]
                 or out["decode_slots"] < out["total_slots"])):
        out["disaggregated"] = True
    return out
