"""Elastic fleet control plane: SLO-driven autoscaling, phase re-balancing,
and brownout preemption.

The loop-closer over signals and actuators the serving stack already had:
PR 8's multi-window SLO burn rates and PR 15's capacity gauges (MFU /
HBM-bandwidth / host-gap / goodput) *observe* saturation; PR 10's
:class:`~deepspeed_tpu.serving.replica.ReplicaSet` over a shared
compiled-program set, PR 13's runtime role flips + parked handoffs, PR 12's
tiered :class:`~deepspeed_tpu.serving.fair_queue.FairQueue`, and
``handle.cancel()`` are the *actuators* — but nothing connected them, so
sustained overload shed 429s until a human intervened. Runtime instance
re-scheduling and priority preemption are what Llumnix (OSDI '24) shows
recovers tail-latency SLOs; DistServe's phase-split provisioning argument
implies prefill/decode capacity must be RE-BALANCED as the traffic mix
drifts, not sized once.

Design:

- **One snapshot per tick**: the gateway consolidates every signal into a
  :class:`FleetSignals` value (SLO fast/slow burn, queue depth +
  ``oldest_wait_s``, phase-aware saturation split, ``serving/mfu`` /
  ``serving/hbm_bw_util`` / host-gap fraction / ``serving/
  goodput_fraction``, occupancy, fleet size) so a decision reads one
  coherent view, not N racing gauges.

- **Pure decisions**: :meth:`FleetController.decide` consumes only the
  snapshot and the controller's own cooldown stamps — all in the
  snapshot's ``now`` timebase, never the wall clock — so scripted signal
  traces drive grow/shrink/flip/brownout deterministically under test.

- **Ticked from the replica-0 pump**: no new thread owns scheduler state.
  The pump already runs the fleet-wide side duties (SLO evaluation,
  recompile watch) once per turn; the controller joins that slate.

- **Three actuators, cooldown-guarded**:
  (a) *scale* — ``ReplicaSet.add_replica()`` spawns a scheduler over the
  SHARED weight tree + compiled-program dict (zero new XLA programs, so
  warmup is pool allocation); scale-down is two-phase pending-drain →
  retire, freeing the pool's HBM. The host-gap signal VETOES scale-up
  when the host, not the device, is the bottleneck — another replica
  would only add host work.
  (b) *re-balance* — prefill- vs decode-side saturation skew flips one
  replica's role through the existing ``set_role`` protocol (which
  enforces both-phases-coverable).
  (c) *brownout* — a load-shedding ladder: each configured tier yields
  two levels — first EVICT that tier's queued flows from the FairQueue
  (503 + brownout Retry-After), then PREEMPT in-flight work below it
  (``handle.cancel()``, or park-for-resume through the PR 13 migrate-out
  transport). ``serving/goodput_fraction`` prices preemption: a fleet
  mostly doing wasted work (spec-rejected/replayed tokens) escalates
  without waiting out the step cooldown — the preempted work was free.

- **Fully observable**: every decision is an ``autoscale/decision``
  telemetry event carrying the signal vector that justified it, plus
  per-action counters and gauges on ``/v1/metrics`` + Prometheus; ``GET/
  POST /v1/autoscaler`` exposes live state and runtime enable/disable/
  dry-run. ``dry_run`` records decisions without actuating — the rollout
  mode.
"""

import collections
import threading


class FleetSignals:
    """One consolidated, per-tick snapshot of everything a fleet decision
    reads. Plain data; every field has a neutral default so tests can
    construct partial snapshots. ``now`` is the DECISION timebase — the
    gateway stamps ``time.monotonic()``, tests stamp whatever they like,
    and the controller never consults a clock of its own."""

    __slots__ = ("now", "burn_fast", "burn_slow", "queue_depth",
                 "oldest_wait_s", "prefill_sat", "decode_sat", "mfu",
                 "hbm_bw_util", "host_gap_frac", "goodput_fraction",
                 "occupancy", "replicas", "replicas_active", "inflight",
                 "disaggregated")

    def __init__(self, now=0.0, burn_fast=0.0, burn_slow=0.0, queue_depth=0,
                 oldest_wait_s=0.0, prefill_sat=0.0, decode_sat=0.0, mfu=0.0,
                 hbm_bw_util=0.0, host_gap_frac=0.0, goodput_fraction=1.0,
                 occupancy=0.0, replicas=1, replicas_active=1, inflight=0,
                 disaggregated=False):
        self.now = float(now)
        self.burn_fast = float(burn_fast)          # max fast-window SLO burn
        self.burn_slow = float(burn_slow)          # max slow-window SLO burn
        self.queue_depth = int(queue_depth)        # fair-queue depth
        self.oldest_wait_s = float(oldest_wait_s)  # head-of-line queue wait
        self.prefill_sat = float(prefill_sat)      # queued work / prefill slots
        self.decode_sat = float(decode_sat)        # in-flight work / decode slots
        self.mfu = float(mfu)                      # serving/mfu gauge
        self.hbm_bw_util = float(hbm_bw_util)      # serving/hbm_bw_util gauge
        self.host_gap_frac = float(host_gap_frac)  # device-idle s per wall s
        self.goodput_fraction = float(goodput_fraction)
        self.occupancy = float(occupancy)          # busy slots / total slots
        self.replicas = int(replicas)              # non-retired fleet size
        self.replicas_active = int(replicas_active)  # placement-eligible
        self.inflight = int(inflight)              # admitted, unfinished
        self.disaggregated = bool(disaggregated)

    def vector(self):
        """The signal vector a decision event records (plain floats/ints —
        json-serializable for telemetry and /v1/autoscaler)."""
        return {name: getattr(self, name) for name in self.__slots__}


class FleetController:
    """SLO-driven fleet controller. The gateway constructs it with the
    ``continuous_batching.autoscaler`` config section and binds the four
    actuator callables; :meth:`tick` runs once per replica-0 pump turn
    with a fresh :class:`FleetSignals` snapshot.

    Actuators (bound by the gateway; any may stay None — the decision is
    still recorded, marked unapplied):

    - ``scale_up_fn()`` -> bool — add one replica.
    - ``scale_down_fn()`` -> bool — begin retiring one replica.
    - ``rebalance_fn(phase)`` -> bool — flip one replica's role toward
      ``phase`` (``"prefill"``/``"decode"``).
    - ``brownout_fn(level)`` -> bool — move the shedding ladder to
      ``level`` (0 = off; odd = evict queued below tier, even = preempt
      in-flight below tier, tiers advancing per config).

    The decision ladder returns AT MOST ONE action per tick — legibility
    and testability over reaction latency (the tick interval is seconds;
    compound emergencies resolve over a few ticks).
    """

    def __init__(self, config, telemetry=None):
        self.config = config
        self.telemetry = telemetry
        self.enabled = bool(config.enabled)
        self.dry_run = bool(config.dry_run)
        self.scale_up_fn = None
        self.scale_down_fn = None
        self.rebalance_fn = None
        self.brownout_fn = None
        # brownout ladder position: 0 = off; level (2i+1, 2i+2) = (evict
        # queued, preempt in-flight) below tier config.brownout_tiers[i]
        self.brownout_level = 0
        self.max_brownout = 2 * len(list(config.brownout_tiers or []))
        # cooldown stamps, all in the SNAPSHOT timebase (sig.now): None =
        # never. No wall clock anywhere in the decision path.
        self._last_tick = None
        self._last_scale_up = None
        self._last_scale = None      # either direction (down-cooldown basis)
        self._last_flip = None
        self._last_brownout_step = None
        self._last_overload = None
        self.counters = collections.Counter()
        self.decisions = collections.deque(maxlen=64)  # /v1/autoscaler ring
        self._lock = threading.Lock()  # admin (event loop) vs pump tick

    # ------------------------------------------------------------------ policy
    def brownout_tier(self, level=None):
        """The tier name a ladder level sheds below (None at level 0)."""
        level = self.brownout_level if level is None else level
        tiers = list(self.config.brownout_tiers or [])
        if level <= 0 or not tiers:
            return None
        return tiers[min((level - 1) // 2, len(tiers) - 1)]

    def _overloaded(self, sig):
        cfg = self.config
        burn_hot = (sig.burn_fast >= cfg.scale_up_burn
                    and sig.burn_slow >= cfg.slow_burn_floor)
        return burn_hot or sig.oldest_wait_s >= cfg.queue_wait_up_s

    def _elapsed(self, stamp, now, hold):
        return stamp is None or (now - stamp) >= hold

    def decide(self, sig):
        """The pure decision function: one :class:`FleetSignals` snapshot
        (+ the controller's cooldown stamps) -> at most one action dict,
        or None. Never touches a clock, an actuator, or the telemetry
        sink — :meth:`tick` owns side effects."""
        cfg = self.config
        now = sig.now
        overloaded = self._overloaded(sig)
        if overloaded:
            self._last_overload = now
            # (a) grow: device-bound overload with headroom and a cold
            # cooldown. Host-bound overload (host_gap_frac at/above the
            # veto) must NOT grow — the bottleneck is the pump/host side,
            # and another replica only adds host work.
            host_bound = sig.host_gap_frac >= cfg.host_gap_veto
            if (sig.replicas < int(cfg.max_replicas) and not host_bound
                    and self._elapsed(self._last_scale_up, now,
                                      float(cfg.cooldown_up_s))):
                return {"action": "scale_up",
                        "reason": ("slo_burn" if sig.burn_fast >= cfg.scale_up_burn
                                   else "queue_wait")}
            # (c) shed: can't (or shouldn't) grow — escalate the ladder.
            # goodput below the free threshold waives the step cooldown:
            # preempting mostly-wasted work costs nothing.
            if self.brownout_level < self.max_brownout:
                free = sig.goodput_fraction < float(cfg.goodput_free_threshold)
                if free or self._elapsed(self._last_brownout_step, now,
                                         float(cfg.brownout_step_s)):
                    return {"action": "brownout",
                            "level": self.brownout_level + 1,
                            "reason": ("host_bound" if host_bound else
                                       "at_max_replicas" if sig.replicas >= int(cfg.max_replicas)
                                       else "scale_cooldown")
                                      + ("+goodput_free" if free else "")}
            return None  # overloaded but every move is cooldown-blocked
        # calm path ----------------------------------------------------
        if self.brownout_level > 0:
            # de-escalate one level after a sustained calm window (and a
            # step cooldown so the ladder doesn't slam open)
            if (self._elapsed(self._last_overload, now,
                              float(cfg.brownout_cooldown_s))
                    and self._elapsed(self._last_brownout_step, now,
                                      float(cfg.brownout_step_s))):
                return {"action": "brownout",
                        "level": self.brownout_level - 1,
                        "reason": "calm"}
            return None  # ladder engaged: hold before considering scale
        # (b) re-balance: phase saturation skew on a disaggregated fleet
        if sig.disaggregated and self._elapsed(self._last_flip, now,
                                               float(cfg.cooldown_flip_s)):
            ratio = float(cfg.rebalance_ratio)
            hi, lo = max(sig.prefill_sat, sig.decode_sat), \
                min(sig.prefill_sat, sig.decode_sat)
            # the busy side must be meaningfully loaded (>= 0.5 of its
            # capacity) — flipping an idle fleet's roles is churn
            if hi >= 0.5 and hi >= ratio * max(lo, 1e-9):
                phase = ("prefill" if sig.prefill_sat > sig.decode_sat
                         else "decode")
                return {"action": "rebalance", "phase": phase,
                        "reason": f"{phase}_saturated"}
        # shrink: both windows cold, queue empty, fleet mostly idle
        if (sig.replicas > max(1, int(cfg.min_replicas))
                and sig.burn_fast <= float(cfg.scale_down_burn)
                and sig.burn_slow <= float(cfg.scale_down_burn)
                and sig.queue_depth == 0
                and sig.occupancy <= float(cfg.scale_down_occupancy)
                and self._elapsed(self._last_scale, now,
                                  float(cfg.cooldown_down_s))):
            return {"action": "scale_down", "reason": "idle"}
        return None

    # ------------------------------------------------------------------ tick
    def tick(self, sig):
        """One control interval: rate-limit by ``interval_s`` (in the
        snapshot timebase), decide, actuate (unless dry_run), record.
        Returns the decision record, or None when idle/rate-limited."""
        if not self.enabled:
            return None
        now = sig.now
        if (self._last_tick is not None
                and now - self._last_tick < float(self.config.interval_s)):
            return None
        self._last_tick = now
        decision = self.decide(sig)
        if decision is None:
            return None
        decision["signals"] = sig.vector()
        decision["dry_run"] = self.dry_run
        applied = False
        if not self.dry_run:
            applied = self._apply(decision, now)
        else:
            # dry-run still advances the cooldown stamps: without this a
            # sustained overload re-decides the SAME action on every tick
            # (interval_s of scale_up spam), and the recorded stream no
            # longer resembles what a live controller would do — which is
            # the whole point of the dry-run rollout step. Actuators and
            # the brownout level stay untouched: dry-run proposes, never
            # moves.
            self._stamp(decision["action"], now)
        decision["applied"] = applied
        with self._lock:
            self.decisions.append(decision)
        self.counters[decision["action"]] += 1
        tel = self.telemetry
        if tel is not None and tel.enabled:
            tel.event("autoscale/decision",
                      {k: v for k, v in decision.items()})
            tel.counter(f"autoscale/{decision['action']}")
            if not applied and not self.dry_run:
                tel.counter("autoscale/actuator_noop")
        return decision

    def _stamp(self, action, now):
        """Advance the cooldown stamp(s) an ``action`` paces on."""
        if action == "scale_up":
            self._last_scale_up = self._last_scale = now
        elif action == "scale_down":
            self._last_scale = now
        elif action == "rebalance":
            self._last_flip = now
        elif action == "brownout":
            self._last_brownout_step = now

    def _apply(self, decision, now):
        """Drive the bound actuator; update cooldown stamps only on
        SUCCESS (a failed actuation should retry next tick, not burn the
        cooldown)."""
        action = decision["action"]
        try:
            if action == "scale_up" and self.scale_up_fn is not None:
                if self.scale_up_fn():
                    self._stamp(action, now)
                    return True
            elif action == "scale_down" and self.scale_down_fn is not None:
                if self.scale_down_fn():
                    self._stamp(action, now)
                    return True
            elif action == "rebalance" and self.rebalance_fn is not None:
                if self.rebalance_fn(decision["phase"]):
                    self._stamp(action, now)
                    return True
            elif action == "brownout" and self.brownout_fn is not None:
                level = int(decision["level"])
                if self.brownout_fn(level):
                    self.brownout_level = level
                    self._stamp(action, now)
                    return True
        except Exception:  # noqa: BLE001 — a failing actuator must not
            # kill the pump; the decision records applied=False and the
            # gateway's own error handling covers the actuator's side
            pass
        return False

    # ------------------------------------------------------------------ surface
    def state(self):
        """GET /v1/autoscaler payload (and the /v1/metrics rollup)."""
        with self._lock:
            recent = list(self.decisions)[-16:]
        return {
            "enabled": self.enabled,
            "dry_run": self.dry_run,
            "brownout_level": self.brownout_level,
            "brownout_tier": self.brownout_tier(),
            "max_brownout_level": self.max_brownout,
            "counters": dict(self.counters),
            "config": {
                "min_replicas": int(self.config.min_replicas),
                "max_replicas": int(self.config.max_replicas),
                "interval_s": float(self.config.interval_s),
                "scale_up_burn": float(self.config.scale_up_burn),
                "scale_down_burn": float(self.config.scale_down_burn),
                "queue_wait_up_s": float(self.config.queue_wait_up_s),
                "cooldown_up_s": float(self.config.cooldown_up_s),
                "cooldown_down_s": float(self.config.cooldown_down_s),
                "host_gap_veto": float(self.config.host_gap_veto),
                "brownout_tiers": list(self.config.brownout_tiers or []),
                "brownout_park": bool(self.config.brownout_park),
                "rebalance_ratio": float(self.config.rebalance_ratio),
            },
            "recent_decisions": recent,
        }

    def admin(self, body):
        """POST /v1/autoscaler: runtime enable/disable/dry-run toggles
        (``{"enabled": bool, "dry_run": bool}``; unknown keys 400 at the
        gateway). Returns the fields that changed."""
        changed = {}
        if "enabled" in body:
            self.enabled = bool(body["enabled"])
            changed["enabled"] = self.enabled
        if "dry_run" in body:
            self.dry_run = bool(body["dry_run"])
            changed["dry_run"] = self.dry_run
        return changed
