"""Per-tenant weighted fair queue: deficit round-robin with priority classes.

The admission layer between the HTTP frontend and the scheduler. Every
queued request belongs to a *flow* — the ``(tenant, priority)`` pair — and
flows are served deficit-round-robin (Shreedhar & Varghese, SIGCOMM '95):
each visit in the rotation credits the flow ``quantum x weight`` deficit,
and the flow's head request pops once its deficit covers the request's
*cost* (estimated work: prompt tokens + max_tokens). Service converges to
weight-proportional token bandwidth per flow, so a tenant flooding the
queue cannot starve a light tenant: the light flow is visited every round
and its small backlog clears at its weighted share, keeping its time-to-
admission bounded by rounds, not by the heavy tenant's backlog depth.

Weights compose multiplicatively: ``tenant_weights[tenant] (default 1.0)
x priority_weights[priority]``, so "interactive" traffic from an ordinary
tenant can outrank "batch" traffic from a heavy one without a separate
strict-priority tier (which would reintroduce starvation).

Thread-safe: the HTTP side pushes from the event loop, the engine pump
thread pops; a single lock guards the rotation. Depth is bounded —
``push`` raises :class:`QueueFull` past ``max_depth``, which the gateway
maps to 429 + Retry-After (shed at the door, never an unbounded queue).
"""

import collections
import threading
import time


class QueueFull(Exception):
    """The bounded fair queue is at ``max_depth``; shed the request."""


class _Flow:
    __slots__ = ("key", "tp", "weight", "deficit", "queue")

    def __init__(self, key, tp, weight):
        self.key = key
        self.tp = tp  # (tenant, priority) — the WEIGHT-bearing identity
        self.weight = weight
        self.deficit = 0.0
        self.queue = collections.deque()  # (cost, item, enq_monotonic_ts)


class FairQueue:
    """Bounded deficit-round-robin queue over ``(tenant, priority)`` flows.

    ``quantum``: deficit credited per rotation visit (cost units).
    ``tenant_weights``: tenant -> weight (default 1.0).
    ``priority_weights``: priority class -> weight multiplier; unknown
    classes fall back to the lowest configured weight (a client cannot
    invent a fast lane by sending a novel header value).
    """

    def __init__(self, max_depth=64, quantum=256, tenant_weights=None,
                 priority_weights=None):
        self.max_depth = int(max_depth)
        self.quantum = max(1.0, float(quantum))
        self.tenant_weights = dict(tenant_weights or {})
        self.priority_weights = dict(priority_weights or {}) or {"standard": 1.0}
        self._floor = min(self.priority_weights.values())
        self._lock = threading.Lock()
        self._flows = {}                        # key -> _Flow
        self._siblings = {}                     # (tenant, priority) -> live flow count
        self._rotation = collections.deque()    # _Flow service order
        self._fresh_turn = True                 # rotation head not yet credited
        self._depth = 0

    def _weight(self, tenant, priority):
        return (float(self.tenant_weights.get(tenant, 1.0))
                * float(self.priority_weights.get(priority, self._floor)))

    def push(self, item, tenant, priority, cost=1, adapter=None):
        """Enqueue ``item``; raises :class:`QueueFull` at the depth bound.

        ``adapter``: optional model-variant key (multi-LoRA serving) — it
        extends the FLOW key, so a tenant's traffic against different
        adapters forms separate DRR flows: one adapter's backlog cannot
        starve the same tenant's other variants. The WEIGHT still belongs
        to the ``(tenant, priority)`` pair: each turn's credit is divided
        by that pair's live flow count, so spreading a backlog across N
        adapters round-robins among them WITHOUT multiplying the tenant's
        bandwidth (a tenant cannot mint share by spraying adapter ids)."""
        cost = max(1, int(cost))
        with self._lock:
            if self._depth >= self.max_depth:
                raise QueueFull(f"fair queue at max_depth={self.max_depth}")
            tp = (str(tenant), str(priority))
            key = tp + ((str(adapter), ) if adapter is not None else ())
            flow = self._flows.get(key)
            if flow is None:
                flow = self._flows[key] = _Flow(key, tp,
                                                self._weight(tenant, priority))
                self._siblings[tp] = self._siblings.get(tp, 0) + 1
                self._rotation.append(flow)
            flow.queue.append((cost, item, time.monotonic()))
            self._depth += 1

    def pop(self):
        """Next request by DRR order, or None when empty.

        Turn semantics (the part naive implementations get wrong): the flow
        at the head of the rotation is credited ``quantum x weight`` ONCE
        per turn, serves heads while its deficit lasts, then rotates to the
        back — still holding any residual deficit. Crediting on every visit
        instead would let a backlogged flow re-earn its quantum after each
        pop and never yield the head: exactly the starvation DRR exists to
        prevent. Every turn either serves or rotates past a credited flow,
        and deficits grow monotonically until one covers its head's cost —
        the loop always terminates."""
        with self._lock:
            if self._depth == 0:
                return None
            while True:
                flow = self._rotation[0]
                if not flow.queue:
                    # emptied flows leave the rotation and forfeit deficit
                    # (standard DRR: idle flows must not bank credit)
                    self._rotation.popleft()
                    self._drop_flow(flow)
                    self._fresh_turn = True
                    continue
                if self._fresh_turn:
                    # the WEIGHT is per (tenant, priority): with k sibling
                    # flows (adapter variants) each turn earns 1/k of the
                    # pair's quantum, so the pair's total service stays
                    # weight-proportional no matter how many adapters its
                    # backlog spans (still >0: the loop terminates)
                    k = max(1, self._siblings.get(flow.tp, 1))
                    flow.deficit += self.quantum * flow.weight / k
                    self._fresh_turn = False
                cost = flow.queue[0][0]
                if flow.deficit < cost:
                    # turn over: next flow's turn begins, residual kept
                    self._rotation.rotate(-1)
                    self._fresh_turn = True
                    continue
                cost, item, _enq = flow.queue.popleft()
                flow.deficit -= cost
                self._depth -= 1
                if not flow.queue:
                    self._rotation.popleft()
                    self._drop_flow(flow)
                    self._fresh_turn = True
                return item

    def requeue(self, item, tenant, priority, cost=1, adapter=None):
        """Put a just-popped request BACK at the head of its flow, undoing
        the pop's accounting (depth and deficit restored, no fresh
        timestamp-based reordering: the tuple goes to the flow's FRONT).

        The gateway uses this when placement transiently fails AFTER a pop
        (a replica drained/sicked/changed phase role between the capacity
        check and the route): shedding an already-accepted request with a
        503 over a momentary eligibility blip would punish the client for
        fleet-internal churn. Depth may transiently exceed ``max_depth`` by
        the requeued item — it was already admitted once."""
        cost = max(1, int(cost))
        with self._lock:
            tp = (str(tenant), str(priority))
            key = tp + ((str(adapter), ) if adapter is not None else ())
            flow = self._flows.get(key)
            if flow is None:
                flow = self._flows[key] = _Flow(key, tp,
                                                self._weight(tenant, priority))
                self._siblings[tp] = self._siblings.get(tp, 0) + 1
                self._rotation.appendleft(flow)
            flow.queue.appendleft((cost, item, time.monotonic()))
            flow.deficit += cost
            self._depth += 1

    def _drop_flow(self, flow):
        del self._flows[flow.key]
        n = self._siblings.get(flow.tp, 1) - 1
        if n <= 0:
            self._siblings.pop(flow.tp, None)
        else:
            self._siblings[flow.tp] = n

    def __len__(self):
        return self._depth

    def depths(self):
        """{(tenant, priority): queued count} — introspection/metrics."""
        with self._lock:
            return {flow.key: len(flow.queue) for flow in self._flows.values()}

    def flow_stats(self):
        """Per-flow queue state for the fleet controller / metrics surface:
        ``{flow key: {tenant, priority, depth, oldest_wait_s, weight}}``.
        ``oldest_wait_s`` is the age of the flow's HEAD request — the
        per-flow head-of-line-wait the brownout ladder prices eviction by."""
        now = time.monotonic()
        with self._lock:
            return {
                flow.key: {
                    "tenant": flow.tp[0],
                    "priority": flow.tp[1],
                    "depth": len(flow.queue),
                    "oldest_wait_s": (round(now - flow.queue[0][2], 6)
                                      if flow.queue else 0.0),
                    "weight": flow.weight,
                }
                for flow in self._flows.values()}

    def tier_weight(self, priority):
        """The configured weight multiplier of a priority class (unknown
        classes resolve to the floor, same rule as admission)."""
        return float(self.priority_weights.get(str(priority), self._floor))

    def evict_flows(self, below_tier):
        """Brownout load shedding: remove every queued request whose flow's
        PRIORITY class weighs strictly less than ``below_tier``'s weight —
        tenant weights don't shield a low class (the ladder sheds by tier,
        not by tenant generosity). Returns the evicted ``(item, tenant,
        priority)`` rows, oldest-first within each flow; the caller owes
        each a 503 with a brownout ``Retry-After``. An unknown tier name
        resolves to the floor weight, so (strict comparison) it evicts
        nothing rather than everything."""
        bar = self.tier_weight(below_tier)
        evicted = []
        with self._lock:
            for flow in list(self._flows.values()):
                if self.tier_weight(flow.tp[1]) >= bar:
                    continue
                while flow.queue:
                    _cost, item, _enq = flow.queue.popleft()
                    evicted.append((item, flow.tp[0], flow.tp[1]))
                    self._depth -= 1
                # evicted flows leave the rotation like emptied ones (and
                # forfeit deficit); removing the rotation HEAD hands the
                # turn to the next flow with a fresh credit
                if self._rotation and self._rotation[0] is flow:
                    self._fresh_turn = True
                try:
                    self._rotation.remove(flow)
                except ValueError:
                    pass
                self._drop_flow(flow)
        return evicted

    def oldest_wait_s(self):
        """Age (seconds) of the longest-queued request across every flow —
        the head-of-line-wait signal the SLO/metrics surface reads; 0.0
        when empty."""
        now = time.monotonic()
        with self._lock:
            oldest = min((flow.queue[0][2] for flow in self._flows.values()
                          if flow.queue), default=None)
        return round(now - oldest, 6) if oldest is not None else 0.0
