"""Production serving gateway: streaming HTTP frontend over the scheduler.

The network layer the continuous-batching stack was missing: after PR 2/3
the :class:`~deepspeed_tpu.inference.scheduler.DecodeScheduler` could only
be driven in-process. This module is the DeepSpeed-MII/vLLM-serving-class
frontend, built on **stdlib only** (``asyncio`` + hand-rolled HTTP/1.1 —
no aiohttp/fastapi in the image, and none needed):

- **HTTP surface** (OpenAI-compatible where it can be, given the engine
  speaks token ids, not text): ``POST /v1/completions`` with ``"stream":
  true`` SSE token streaming (``data: {chunk}\\n\\n`` ... ``data: [DONE]``),
  ``GET /healthz`` (process liveness), ``GET /readyz`` (serving readiness —
  flips 503 during drain), ``GET /v1/metrics`` (JSON gateway stats + the
  telemetry sink's :meth:`snapshot`; Prometheus text exposition under
  ``Accept: text/plain``/``openmetrics`` or ``?format=prometheus``, so
  standard scrapers work), ``GET /v1/slo`` (the SLO engine's objective/
  burn-rate state), ``GET /v1/debug/flight`` (force a flight-recorder
  dump). Prompts are token-id lists (or whitespace-separated decimal ids in
  a string); completions carry both ``token_ids`` and a space-joined
  decimal ``text``.

- **Request tracing**: an inbound W3C ``traceparent`` or ``x-request-id``
  header names the request (minted otherwise, echoed back as
  ``x-request-id``); with telemetry + request tracing on, every request
  records a span tree (queued -> admitted -> prefix probe -> prefill
  chunks -> decode -> complete/cancel/expire) on its own Perfetto track,
  flow-linked to the scheduler's shared per-iteration spans
  (``telemetry/tracing.py``).

- **SLOs + flight recorder**: the ``telemetry.slo`` config section (or the
  default serving slate — TTFT/queue-wait/ITL p95, shed+expiry rate) is
  evaluated from the pump loop with multi-window burn rates; a burn-rate
  trip, a backend step failure, or an unexpected post-warmup XLA recompile
  dumps the telemetry flight recorder's ring of surrounding iterations to
  a timestamped file.

- **Admission control**: a bounded per-tenant fair queue
  (:class:`~deepspeed_tpu.serving.fair_queue.FairQueue`). Past
  ``max_queue_depth`` requests shed with **429** and a ``Retry-After``
  derived from live state (queue depth x EMA service time / slots) instead
  of queueing unboundedly; during drain/not-ready they shed with **503**.
  Every request carries a deadline (``request_timeout_s``, body
  ``timeout_s`` override): expiry — and client disconnect, observed as EOF
  on the connection — propagates ``handle.cancel()`` into the scheduler so
  the KV slot frees mid-decode instead of finishing a dead request.

- **Per-tenant weighted fair queuing**: deficit round-robin over
  ``(tenant, priority)`` flows sits BETWEEN the HTTP layer and scheduler
  admission — the scheduler's own FIFO is kept nearly empty so the DRR
  order decides who gets the next free slot, and one heavy tenant cannot
  starve the pool (see ``fair_queue.py``).

- **Graceful lifecycle**: ``begin_drain()`` (wired to SIGTERM by the
  ``python -m deepspeed_tpu.serving`` entrypoint) flips readiness, stops
  admitting (503 + Retry-After), finishes every already-admitted request,
  flushes telemetry, and exits; ``drain_timeout_s`` bounds the grace.

- **Replica fleet** (``continuous_batching.replicas`` > 1): N scheduler
  replicas — independent slot pools, ONE weight tree and ONE compiled
  program set — behind this one gateway (``serving/replica.py``). The DRR
  pop is placed prefix-sticky (prompts sharing a cached prefix follow the
  replica that owns it) or least-loaded (occupancy x per-replica service
  EMA); ``POST /v1/replicas/<i>/drain|resume`` and per-replica health keep
  one sick replica from sinking the fleet. ``GET /v1/replicas`` lists
  states.

- **Elastic fleet control plane** (``continuous_batching.autoscaler``):
  a :class:`~deepspeed_tpu.serving.controller.FleetController` ticked from
  the replica-0 pump reads one consolidated signal snapshot per interval
  (SLO burn rates, queue wait, phase saturation, MFU/HBM/host-gap/goodput)
  and drives three cooldown-guarded actuators — grow/shrink the replica
  fleet over the SHARED compiled-program set, flip prefill/decode roles as
  the traffic mix drifts, and a brownout ladder that evicts then preempts
  low-tier work (503 + brownout Retry-After; optionally parking decode
  state for resume through the migration transport). ``GET/POST
  /v1/autoscaler`` exposes decisions and runtime enable/dry-run.

Threading model: the asyncio event loop owns sockets and parsing; one
**pump thread per replica** owns ALL of that replica's scheduler
interaction (submit/step/cancel — each scheduler stays single-threaded).
Admission (fair-queue pop + placement) and terminal accounting serialize on
the dispatch/finish locks. Tokens cross from a pump to a response's
``asyncio.Queue`` via ``loop.call_soon_threadsafe`` from the scheduler's
``on_token`` hook, so SSE events flush as each host sync lands (TTFB =
queue wait + prefill + first sync, not request completion).

Telemetry (PR-1 sink): histograms ``gateway/queue_wait_ms``,
``gateway/ttfb_ms``; gauges ``gateway/queue_depth``,
``gateway/active_requests``; counters ``gateway/requests``,
``gateway/completed``, ``gateway/tokens``, ``gateway/shed_429``,
``gateway/shed_503``, ``gateway/deadline_expired``,
``gateway/disconnects``, ``gateway/tenant/<tenant>/tokens``.
"""

import asyncio
import copy
import json
import threading
import time

import numpy as np

from ..inference.config import GatewayConfig
from ..telemetry import (DEFAULT_SERVING_OBJECTIVES, RequestTrace, SLOEngine,
                         extract_trace_context)
from ..telemetry import prometheus as prom
from ..utils.logging import logger
from . import capacity_math
from .controller import FleetController, FleetSignals
from .fair_queue import FairQueue, QueueFull
from .replica import ReplicaSet

_JSON = "application/json"


def _round_up(x, m):
    return (x + m - 1) // m * m


class _GatewayRequest:
    """One admitted-or-queued completion request: the handoff record between
    the HTTP handler (event loop) and the scheduler pump thread."""

    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_token_id", "do_sample",
                 "temperature", "top_k", "top_p", "seed", "tenant", "priority",
                 "cost", "deadline", "stream", "loop", "events", "handle",
                 "cancel_requested", "cancel_reason", "finished", "enq_ts",
                 "admit_ts", "n_tokens", "trace", "trace_id", "replica",
                 "adapter_id", "return_logits", "resume")

    def __init__(self, rid, prompt, *, max_new_tokens, eos_token_id, do_sample,
                 temperature, top_k, top_p, seed, tenant, priority, deadline,
                 stream, loop, trace=None, trace_id=None, adapter_id=None,
                 return_logits=False, resume=None):
        self.rid = rid
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.eos_token_id = eos_token_id
        self.do_sample = do_sample
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.seed = seed
        self.tenant = tenant
        self.priority = priority
        self.cost = len(prompt) + max_new_tokens  # DRR work estimate
        self.deadline = deadline
        self.stream = stream
        self.loop = loop
        self.events = asyncio.Queue()
        self.handle = None
        self.cancel_requested = False
        self.cancel_reason = None
        self.finished = False
        self.enq_ts = time.monotonic()
        self.admit_ts = None
        self.n_tokens = 0
        self.trace = trace          # RequestTrace (None when tracing is off)
        self.trace_id = trace_id    # request identity echoed as x-request-id
        self.replica = None         # serving replica this request landed on
        self.adapter_id = adapter_id  # model variant (multi-LoRA serving)
        # unary responses can carry per-step logits (the multihost
        # bit-identity surface: logits must round-trip process boundaries)
        self.return_logits = return_logits
        # cross-process migration resume: the handoff descriptor a router
        # POSTed after a prefill worker handed this request off (None for
        # ordinary arrivals — resume requests bypass the fair queue and go
        # straight to the fleet's migration admission)
        self.resume = resume


class Gateway:
    """Serving gateway over one :class:`InferenceEngine`'s scheduler.

    ``Gateway(engine).start_background()`` binds the HTTP server (port 0 =
    ephemeral; the bound port lands on :attr:`port`) and starts the pump
    thread; ``begin_drain()`` initiates graceful shutdown and
    ``wait_drained()`` blocks until every admitted request finished and the
    server closed. ``run()`` is the blocking form the module entrypoint
    uses. ``config`` defaults to the engine config's ``gateway`` section;
    keyword overrides replace individual fields.
    """

    def __init__(self, engine, config=None, **overrides):
        if config is None:
            config = getattr(engine._config, "gateway", None)
        if not isinstance(config, GatewayConfig):
            config = GatewayConfig(dict(config or {}))
        if overrides:
            # never mutate the caller's (usually the ENGINE's) config object
            # in place: a later Gateway(engine) would silently inherit this
            # instance's overrides
            config = copy.deepcopy(config)
        for key, val in overrides.items():
            if not hasattr(config, key):
                raise ValueError(f"unknown GatewayConfig override {key!r}")
            setattr(config, key, val)
        self.engine = engine
        self.config = config
        self.telemetry = engine.telemetry
        # multi-replica serving (continuous_batching.replicas): N scheduler
        # replicas behind one dispatch policy (serving/replica.py), sharing
        # one weight tree and ONE compiled program set. Replica 0 is the
        # engine's singleton scheduler, so `self.scheduler` keeps meaning
        # what it always did for the single-replica gateway.
        self.replicas = ReplicaSet.build(engine)
        self.scheduler = self.replicas.primary
        # disaggregated serving: a finished handoff wakes parked decode
        # pumps immediately instead of waiting out the poll interval
        self.replicas.on_migration_ready = self._wake_all
        self._fair = FairQueue(max_depth=config.max_queue_depth,
                               quantum=config.quantum_tokens,
                               tenant_weights=config.tenant_weights,
                               priority_weights=config.priority_weights)
        self.stats = {"requests": 0, "completed": 0, "tokens": 0, "shed_429": 0,
                      "shed_503": 0, "deadline_expired": 0, "disconnects": 0,
                      "rejected": 0, "brownout_shed": 0, "brownout_evicted": 0,
                      "brownout_preempted": 0, "brownout_parked": 0,
                      "replicas_added": 0, "replicas_retired": 0,
                      # multi-host serving: requests handed off to another
                      # process (prefill side) / adopted from one (decode)
                      "handoffs_out": 0, "resumed_in": 0}
        self.host = config.host
        self.port = None  # bound port (after start)
        self.ready = False
        self.draining = False
        self._rid = 0
        self._rid_lock = threading.Lock()
        self._tenant_labels = set()          # tenants with their own counter
        self._wake = threading.Event()       # pump wakeup
        self._active = set()                 # admitted, unfinished _GatewayRequests
        self._ema_service_s = None           # EMA of request wall time
        # pump-side locks: dispatch (fair-queue pop + replica placement must
        # be one atomic decision across the per-replica pump threads) and
        # finish (terminal accounting is exactly-once even when a cancel
        # settling on one pump races the final token on another)
        self._dispatch_lock = threading.Lock()
        self._finish_lock = threading.Lock()
        self._loop = None
        self._server = None
        self._open_streams = 0               # responses still being written
        self._pump_thread = None
        self._pump_threads = []
        self._loop_thread = None
        self._done_evt = threading.Event()   # fully drained + server closed
        self._force_stop = False
        # SLO engine over the shared sink: the telemetry config's 'slo'
        # section (or the default serving objective slate) evaluated from
        # the pump loop; burn-rate trips dump the flight recorder
        self.slo = None
        if self.telemetry.enabled:
            self.slo = SLOEngine(self.telemetry,
                                 getattr(self.telemetry, "slo_config", None),
                                 defaults=DEFAULT_SERVING_OBJECTIVES)
            if not self.slo.enabled:
                self.slo = None
            else:
                self.slo.on_alert.append(
                    lambda state: self.telemetry.dump_flight(
                        f"slo_burn_{state['name']}", state))
        # unexpected-recompile watch: once the gateway has completed a
        # request the scheduler's program set is considered warm; later
        # growth is an anomaly worth a flight dump
        self._compile_baseline = None
        # operator flight-dump request (SIGUSR1): the signal handler only
        # stores the reason — dump_flight takes sink locks and a handler
        # interrupting a flush on the same thread would self-deadlock on
        # the non-reentrant io lock; the pump thread performs the dump
        self._flight_request = None
        # on-demand XLA profiling (POST /v1/debug/profile): duration-bounded
        # captures written next to the flight dumps; one per process — a
        # second request while one is in flight gets 409
        self.profiler = None
        if self.telemetry.enabled:
            from ..telemetry.profiler import XlaProfiler
            self.profiler = XlaProfiler(self.telemetry.output_path)
        # elastic fleet control plane (serving/controller.py): the replica-0
        # pump ticks it with one consolidated FleetSignals snapshot per
        # interval; the four actuators below close the loop onto the
        # ReplicaSet / FairQueue / cancel machinery the stack already has.
        # Constructed even when disabled so POST /v1/autoscaler can turn it
        # on at runtime (rollout: start dry_run, watch decisions, enable).
        cb_cfg = getattr(engine._config, "continuous_batching", None)
        as_cfg = getattr(cb_cfg, "autoscaler", None)
        self.autoscaler = None
        if as_cfg is not None:
            self.autoscaler = FleetController(as_cfg, telemetry=self.telemetry)
            self.autoscaler.scale_up_fn = self._scale_up
            self.autoscaler.scale_down_fn = self._scale_down
            self.autoscaler.rebalance_fn = self._rebalance
            self.autoscaler.brownout_fn = self._set_brownout
        # a replica added at runtime needs its own pump thread: the set
        # fires this from whichever thread ran add_replica
        self.replicas.on_replica_added = self._spawn_pump
        self._brownout_bar = None   # weight bar arrivals shed under (None=off)
        self._park_pending = set()  # greqs awaiting park-out on their owning pump
        self._gap_mark = None       # (now, fleet host-gap total) delta basis
        # multi-host serving (serving/router.py): the WorkerAgent attaches a
        # NetPrefixStore here so /v1/store/fetch can serve this shard's KV
        # bytes to remote restores; None on single-process gateways
        self.net_store = None
        # POST /v1/debug/flush_radix: replica idxs whose pump must evict the
        # whole radix trie through the tier next turn (multihost tests force
        # cross-host demotion with it)
        self._flush_radix_pending = set()

    # ------------------------------------------------------------------ lifecycle
    def start_background(self, timeout=120.0):
        """Start the server + pump on background threads; returns once the
        port is bound and the gateway is ready (raises on startup failure)."""
        ready = threading.Event()
        fail = []

        def runner():
            try:
                asyncio.run(self._serve(ready.set))
            except Exception as e:  # noqa: BLE001 — surface to the caller
                fail.append(e)
                ready.set()
            finally:
                self._done_evt.set()

        self._loop_thread = threading.Thread(target=runner, daemon=True,
                                             name="gateway-server")
        self._loop_thread.start()
        if not ready.wait(timeout):
            raise TimeoutError("gateway failed to bind within startup timeout")
        if fail:
            raise fail[0]
        return self

    def run(self):
        """Blocking serve-until-drained (the ``python -m`` entrypoint path).
        Returns 0 after a clean drain. Interruptible: signal handlers run on
        the main thread while this waits."""
        self.start_background()
        logger.info(f"gateway listening on {self.host}:{self.port}")
        print(json.dumps({"event": "GATEWAY_READY", "host": self.host,
                          "port": self.port}), flush=True)
        while not self._done_evt.wait(0.2):
            pass
        return 0

    def begin_drain(self):
        """Graceful shutdown trigger (SIGTERM handler / test hook; any
        thread): flip readiness, stop admitting, let the pump finish every
        admitted request, then close the server and flush telemetry."""
        if self.draining:
            return
        self.draining = True
        self.ready = False
        logger.info("gateway: drain initiated (no new admissions)")
        # lift any brownout: parked decode state must resume (and finish)
        # for the drain to complete, and the door is closed anyway
        self._brownout_bar = None
        self._park_pending.clear()
        self.replicas.release_parked()
        # drain grace bound: past it, in-flight requests fail fast instead
        # of holding the process open forever
        timer = threading.Timer(float(self.config.drain_timeout_s), self._force)
        timer.daemon = True
        timer.start()
        self._wake.set()

    def _force(self):
        if not self._done_evt.is_set():
            logger.warning("gateway: drain timeout exceeded; forcing stop")
            self._force_stop = True
            self._wake.set()

    def request_flight_dump(self, reason):
        """Async-signal-safe flight-dump request (a plain attribute store):
        the pump thread performs the actual dump on its next turn. This is
        what the ``SIGUSR1`` handler calls — a handler that invoked
        ``dump_flight`` directly could interrupt a flush on its own thread
        and deadlock on the sink's io lock."""
        self._flight_request = str(reason)
        self._wake.set()

    def wait_drained(self, timeout=None):
        """Block until drain completes (all admitted requests finished, the
        server closed). Returns False on timeout."""
        return self._done_evt.wait(timeout)

    def close(self, timeout=None):
        """begin_drain + wait_drained, for tests/benches."""
        self.begin_drain()
        done = self.wait_drained(timeout if timeout is not None
                                 else self.config.drain_timeout_s + 30)
        if self.profiler is not None:
            self.profiler.stop()  # a capture must not outlive the gateway
        return done

    async def _serve(self, ready_cb):
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(self._handle_conn, self.host,
                                                  self.config.port)
        self.port = self._server.sockets[0].getsockname()[1]
        # one pump thread PER REPLICA: each owns all calls into its own
        # scheduler (the single-threaded-scheduler contract, N times over);
        # admission and terminal accounting serialize on the dispatch/finish
        # locks. On a pod each pump drives its own device group; on one host
        # the threads interleave through the shared backend.
        self._pump_threads = []
        for rep in self.replicas:
            self._spawn_pump(rep)
        self._pump_thread = self._pump_threads[0]  # single-replica back-compat
        self.ready = True
        ready_cb()
        # pump exit == fully drained (each pump only returns when draining
        # and all admitted work finished, or on force-stop)
        while any(t.is_alive() for t in self._pump_threads):
            await asyncio.sleep(0.05)
        # let in-flight response writers flush their final events
        deadline = time.monotonic() + 10.0
        while self._open_streams > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.01)
        self._server.close()
        await self._server.wait_closed()
        try:
            self.telemetry.flush()
        except Exception:  # noqa: BLE001 — a sink failure must not fail drain
            pass
        logger.info("gateway: drained and closed")

    # ------------------------------------------------------------------ pump threads
    def _wake_all(self):
        """Transfer-thread-safe pump wakeup (migration-ready callback)."""
        self._wake.set()

    def _spawn_pump(self, rep):
        """Start (or restart) the pump thread that owns ``rep``'s scheduler.
        Called at startup for the initial fleet and from ``add_replica`` —
        on the on_replica_added hook — for elastic growth; a retired index
        being re-used gets a FRESH thread (the old one exited at retire)."""
        t = threading.Thread(target=self._pump, args=(rep, ), daemon=True,
                             name=f"gateway-pump-{rep.idx}")
        self._pump_threads.append(t)
        t.start()
        return t

    def _pump(self, rep):
        """One replica's pump: admit from the fair queue in DRR order
        (dispatch-locked — placement is a fleet-wide decision), step THIS
        replica's decode loop, enforce deadlines and cancellations. Exits
        only when draining and every admitted request has finished.

        Replica 0's pump additionally owns the fleet-wide side duties (SLO
        evaluation, operator flight dumps, recompile watch) so they run
        exactly once per turn regardless of fleet size."""
        sched = rep.scheduler
        primary = rep.idx == 0
        while not self._force_stop:
            with self._dispatch_lock:
                self._enforce_cancellations()
                self._admit()
            try:
                # disaggregated serving: claim parked prefill->decode
                # handoffs for THIS replica (cancelled ones settle on any
                # pump; a decode pump that adopts one becomes non-idle and
                # steps below). Inside the SAME guard as step(): a restore
                # failing on device must degrade to sick-replica shedding,
                # not kill this daemon thread and strand its requests
                self.replicas.admit_migrations(rep)
                if self._park_pending:
                    # brownout park-for-resume: only the owning pump may
                    # call migrate_out on its scheduler
                    self._park_owned(rep)
                if rep.idx in self._flush_radix_pending:
                    # debug-forced demotion: only this pump may touch its
                    # scheduler's radix trie
                    self._flush_radix(rep)
                if not rep.idle() and not rep.sick:
                    rep.step()
            except Exception:  # noqa: BLE001 — fail requests, not the server
                logger.exception(f"gateway: replica {rep.idx} scheduler step failed")
                self.telemetry.dump_flight("backend_error")
                # "other healthy replicas remain BESIDES this one":
                # healthy() still counts this not-yet-marked replica, so
                # > 1 is the real fleet-keeps-serving test — the LAST
                # healthy replica failing must take the fail-and-retry
                # path below, not sick the whole fleet into a state only
                # a manual resume can leave
                if len(self.replicas.healthy()) > 1:
                    # shed the sick replica, keep the fleet serving:
                    # its in-flight requests fail, placement avoids it,
                    # and its pump STOPS stepping it (a persistently-
                    # raising backend must not spin traceback/flight-
                    # dump loops or block drain) until resume()
                    self.replicas.mark_sick(rep.idx, "scheduler step failed")
                    self._fail_replica_in_flight(rep, "replica step failed")
                else:
                    # single replica (or the last healthy one): today's
                    # semantics — fail everything, stay up, retry on the
                    # next admitted request
                    self._fail_in_flight("scheduler step failed")
            self._settle_done()
            if primary:
                # every primary iteration, stepped or not: the program set
                # is SHARED, so another replica's stray shape must trip the
                # recompile watch even while replica 0 idles
                self._watch_recompiles()
                if self.slo is not None:
                    self.slo.maybe_evaluate()
                if self.autoscaler is not None and not self.draining:
                    # elastic fleet control: one consolidated snapshot, at
                    # most one actuation per interval (controller.py)
                    self.autoscaler.tick(self.fleet_signals())
                if self._flight_request is not None:
                    reason, self._flight_request = self._flight_request, None
                    self.telemetry.dump_flight(reason)
                if self.profiler is not None:
                    # belt-and-braces deadline: stops an overdue capture
                    # even if its timer thread was lost
                    self.profiler.poll()
            if rep.pending_drain or rep.retired:
                # elastic scale-down: once THIS pump observes its replica
                # idle it performs the retire itself (frees the slot pool
                # HBM on the thread that owns the scheduler) and exits;
                # add_replica reusing the index spawns a fresh pump
                if rep.retired or self.replicas.finish_scale_down(rep):
                    break
            if rep.idle() or rep.sick:
                if self.draining and not len(self._fair) and not self._active:
                    break
                self._wake.wait(0.02)
                self._wake.clear()
        # force-stop: anything still in flight is failed, not silently
        # dropped (any one pump suffices — _fail_in_flight spans the fleet)
        if self._force_stop and primary:
            self._fail_in_flight("gateway shutdown")

    def _watch_recompiles(self):
        """Flight-dump on unexpected XLA recompiles: after the first
        completed request the scheduler's compiled-program set is warm for
        the serving mix — later growth (a stray shape, a new sampling
        variant slipping past the O(1)-programs design) is exactly the
        anomaly the recorder exists for."""
        count = self.scheduler.compiled_program_count()
        if self._compile_baseline is None:
            if self.stats["completed"] >= 1:
                self._compile_baseline = count
        elif count > self._compile_baseline:
            tel = self.telemetry
            if tel.enabled:
                tel.counter("gateway/unexpected_recompiles",
                            count - self._compile_baseline)
                tel.dump_flight("xla_recompile",
                                {"programs": count,
                                 "baseline": self._compile_baseline})
            self._compile_baseline = count

    def _admit(self):
        """Move requests from the DRR queue into scheduler slots while the
        fleet has capacity (caller holds the dispatch lock). Each pop is
        placed by the replica set — prefix-sticky, else least-loaded — and
        every replica's FIFO is kept empty (admission is 1:1 with free
        capacity) so fair-queue order IS slot order."""
        tel = self.telemetry
        while True:
            if not self.replicas.any_capacity():
                if self.replicas.all_sick():
                    if len(self._fair):
                        self._fail_queue("no healthy serving replica")
                    if self.replicas.pending_migrations():
                        # parked handoffs have no adopter left either
                        self.replicas._fail_handoffs()
                return
            greq = self._fair.pop()
            if greq is None:
                return
            if tel.enabled:
                tel.gauge("gateway/queue_depth", len(self._fair))
            if greq.cancel_requested:
                if greq.trace is not None:
                    greq.trace.instant("cancelled", where="queue")
                self._post(greq, ("cancelled", greq.cancel_reason or "cancelled"))
                continue
            now = time.monotonic()
            if greq.deadline is not None and now >= greq.deadline:
                self.stats["deadline_expired"] += 1
                if tel.enabled:
                    tel.counter("gateway/deadline_expired")
                if greq.trace is not None:
                    greq.trace.phase("queued", status="expired")
                    greq.trace.instant("expired", where="queue")
                self._post(greq, ("failed", 504, "deadline expired in queue"))
                continue
            rep = self.replicas.route(greq.prompt, adapter=greq.adapter_id)
            if rep is None:
                # eligibility changed between the capacity check and the
                # pop (drain/sick/phase-role mutate under the ReplicaSet's
                # own lock): requeue at the flow head — the blip is fleet-
                # internal churn, not client overload, so a 503 here would
                # shed an already-accepted request for nothing. If the
                # fleet stays unplaceable the queue bounds still shed new
                # arrivals with honest Retry-After.
                self._fair.requeue(greq, greq.tenant, greq.priority,
                                   cost=greq.cost, adapter=greq.adapter_id)
                return
            try:
                handle = rep.scheduler.submit(
                    greq.prompt, max_new_tokens=greq.max_new_tokens,
                    eos_token_id=greq.eos_token_id, do_sample=greq.do_sample,
                    temperature=greq.temperature, top_k=greq.top_k,
                    top_p=greq.top_p, seed=greq.seed,
                    collect_logits=True if greq.return_logits else None,
                    on_token=self._make_on_token(greq), trace=greq.trace,
                    adapter_id=greq.adapter_id)
            except ValueError as e:
                self.stats["rejected"] += 1
                if greq.trace is not None:
                    greq.trace.instant("rejected", error=str(e))
                self._post(greq, ("failed", 400, str(e)))
                continue
            greq.handle = handle
            greq.replica = rep
            self.replicas.note_dispatch(rep)
            greq.admit_ts = now
            if greq.trace is not None:
                greq.trace.phase("queued",
                                 wait_ms=round((now - greq.enq_ts) * 1e3, 3))
                greq.trace.instant("admitted", replica=rep.idx)
            if tel.enabled:
                tel.histogram("gateway/queue_wait_ms", (now - greq.enq_ts) * 1e3)
            if handle.done:  # zero-budget edge: finished with no tokens
                self._finish(greq, ("done", "length"))
            else:
                self._active.add(greq)
                if tel.enabled:
                    tel.gauge("gateway/active_requests", len(self._active))

    def _make_on_token(self, greq):
        def on_token(tok, done):
            greq.n_tokens += 1
            reason = None
            if done:
                reason = ("stop" if (greq.eos_token_id is not None
                                     and tok == greq.eos_token_id) else "length")
                # account BEFORE posting the final token: the HTTP side
                # responds the moment the event lands, and a client that
                # reads the response then polls /v1/metrics must see its
                # own completion counted (the reverse order raced)
                self._finish(greq, None)
            self._post(greq, ("token", int(tok), reason))
        return on_token

    def _finish(self, greq, event):
        """Request reached a terminal state on the pump side: account it,
        update the service-time EMA (feeds Retry-After), emit telemetry.

        Only requests that ran to natural completion count toward
        ``completed`` and the EMA: folding cancelled/disconnected/failed
        requests in would collapse the EMA toward the abort latency under
        overload with impatient clients, making ``Retry-After`` advertise
        far-too-small backoffs (a retry-storm amplifier). Token counters
        still accrue — the decode work happened, and the per-tenant counter
        is a billing/fairness audit.

        Exactly-once across pump threads: a cancel settling on one replica's
        pump can race the final token on another — the finish lock plus the
        ``finished`` flag make whichever lands first the terminal event."""
        with self._finish_lock:
            if greq.finished:
                return
            greq.finished = True
            self._active.discard(greq)
            completed = event is None or event[0] == "done"
            if completed:
                service = time.monotonic() - greq.enq_ts
                ema = self._ema_service_s
                self._ema_service_s = (service if ema is None
                                       else 0.9 * ema + 0.1 * service)
                if greq.replica is not None:
                    greq.replica.observe_service(service)
                self.stats["completed"] += 1
            self.stats["tokens"] += greq.n_tokens
        if event is not None:
            self._post(greq, event)
        tel = self.telemetry
        if tel.enabled:
            if completed:
                tel.counter("gateway/completed")
            tel.counter("gateway/tokens", greq.n_tokens)
            # cardinality cap: the tenant id is CLIENT-controlled, and sink
            # counters are never evicted — random ids must not grow the sink
            # (and every /v1/metrics payload) without bound
            tenant = greq.tenant
            if tenant not in self._tenant_labels:
                if len(self._tenant_labels) < 256:
                    self._tenant_labels.add(tenant)
                else:
                    tenant = "__other__"
            tel.counter(f"gateway/tenant/{tenant}/tokens", greq.n_tokens)
            tel.gauge("gateway/active_requests", len(self._active))

    def _enforce_cancellations(self):
        """Deadline expiry and HTTP-side cancellation (disconnect) propagate
        into the scheduler: ``handle.cancel()`` flags the slot, the next
        ``step()`` frees it (the scheduler never mutates mid-dispatch)."""
        now = time.monotonic()
        tel = self.telemetry
        for greq in list(self._active):
            if (not greq.cancel_requested and greq.deadline is not None
                    and now >= greq.deadline):
                greq.cancel_requested = True
                greq.cancel_reason = "deadline"
                self.stats["deadline_expired"] += 1
                if tel.enabled:
                    tel.counter("gateway/deadline_expired")
            if greq.cancel_requested and greq.handle is not None:
                greq.handle.cancel()

    def _settle_done(self):
        """Cancelled/failed requests finish via the scheduler's reap (done
        without a final on_token): confirm the terminal state to the HTTP
        side — a migration failure answers 500 with its reason, not a
        phantom "cancelled" the client never asked for."""
        for greq in list(self._active):
            if greq.handle is not None and greq.handle.done and not greq.finished:
                err = greq.handle._req.error
                if err is not None:
                    self._finish(greq, ("failed", 500, err))
                else:
                    self._finish(greq, ("cancelled", greq.cancel_reason or "cancelled"))

    def _fail_in_flight(self, msg):
        for greq in list(self._active):
            if greq.handle is not None:
                greq.handle.cancel()
            self._finish(greq, ("failed", 500, msg))
        self._fail_queue(msg)

    def _fail_replica_in_flight(self, rep, msg):
        """Fail ONLY the requests ``rep``'s scheduler currently OWNS (a sick
        replica sheds its own work; the rest of the fleet, and the queue,
        keep going). Ownership is asked of the scheduler rather than
        remembered from placement: a request whose prefill ``rep`` ran but
        whose KV already migrated out is owned by NO scheduler (or by its
        decode replica), so the prefill replica failing cannot kill it."""
        for greq in list(self._active):
            if greq.handle is not None and rep.scheduler.owns(greq.handle._req):
                greq.handle.cancel()
                self._finish(greq, ("failed", 500, msg))

    def _fail_queue(self, msg):
        while True:
            greq = self._fair.pop()
            if greq is None:
                break
            self._post(greq, ("failed", 503, msg))

    def _post(self, greq, event):
        """Pump -> HTTP handler handoff; never raises (the response side may
        already be gone — its queue then just collects unread events)."""
        try:
            greq.loop.call_soon_threadsafe(greq.events.put_nowait, event)
        except RuntimeError:
            pass  # event loop closed mid-drain

    # ------------------------------------------------------------------ elastic fleet
    def fleet_signals(self, now=None):
        """One consolidated :class:`FleetSignals` snapshot — the controller
        tick's entire world view, assembled here so the decision function
        never reads live gateway state (deterministic under test: tests
        construct FleetSignals directly)."""
        now = time.monotonic() if now is None else now
        burn_fast = burn_slow = 0.0
        if self.slo is not None:
            for obj in (self.slo._last_state or {}).get("objectives", []):
                burn_fast = max(burn_fast, float(obj.get("burn_fast") or 0.0))
                burn_slow = max(burn_slow, float(obj.get("burn_slow") or 0.0))
        reps = [r for r in self.replicas if not r.retired]
        active = [r for r in reps if r.available()]
        placeable = active or reps  # degenerate all-drained fleet: avoid /0
        pre_depth = (len(self._fair)
                     + sum(len(r.scheduler.queue) for r in active
                           if r.prefill_capable()))
        total_slots = sum(r.scheduler.num_slots for r in placeable) or 1
        busy = sum(r.busy_slots() for r in active)
        mfu = bw = 0.0
        goodput = 1.0
        cap = self.scheduler.capacity
        if cap is not None:
            goodput = float(cap.goodput_fraction)
            # per-program roofline entries hold the LAST sampled dispatch;
            # the max across programs is the "how hot is the device" signal
            for ent in cap.programs.values():
                mfu = max(mfu, float(ent.get("mfu", 0.0)))
                bw = max(bw, float(ent.get("hbm_bw_util", 0.0)))
        # host-gap fraction: device-idle seconds accrued per wall second
        # since the previous snapshot, summed over the fleet's trackers —
        # the "the host is the bottleneck" veto input
        host_gap_frac = 0.0
        gap_total = sum(r.scheduler._gap.total_gap_s for r in reps
                        if r.scheduler._gap is not None)
        mark, self._gap_mark = self._gap_mark, (now, gap_total)
        if mark is not None and now > mark[0]:
            host_gap_frac = max(0.0, min(1.0, (gap_total - mark[1])
                                         / (now - mark[0])))
        return FleetSignals(
            now=now, burn_fast=burn_fast, burn_slow=burn_slow,
            queue_depth=len(self._fair),
            oldest_wait_s=self._fair.oldest_wait_s(),
            prefill_sat=pre_depth / max(1, self.replicas.phase_slots("prefill")),
            decode_sat=len(self._active) / max(1, self.replicas.phase_slots("decode")),
            mfu=mfu, hbm_bw_util=bw, host_gap_frac=host_gap_frac,
            goodput_fraction=goodput, occupancy=busy / total_slots,
            replicas=len(reps), replicas_active=len(active),
            inflight=len(self._active),
            disaggregated=self.replicas.disaggregated())

    def _scale_up(self):
        """Autoscaler actuator: grow the fleet by one replica over the
        SHARED weight tree + compiled-program set (zero new XLA programs —
        warmup is pool allocation; on_replica_added spawns its pump)."""
        if self.replicas.active_count() >= int(self.autoscaler.config.max_replicas):
            return False
        rep = self.replicas.add_replica()
        self.stats["replicas_added"] += 1
        logger.info(f"autoscaler: added replica {rep.idx} "
                    f"(fleet {self.replicas.active_count()})")
        self._wake.set()
        return True

    def _scale_down(self):
        """Autoscaler actuator: begin the two-phase retire of the
        highest-index drainable replica (never 0 — it owns the fleet-wide
        pump duties). Its own pump finishes the retire once idle."""
        victims = [r for r in self.replicas
                   if r.idx != 0 and not r.retired and not r.pending_drain
                   and not r.sick]
        if not victims:
            return False
        victim = max(victims, key=lambda r: r.idx)
        self.replicas.begin_scale_down(victim.idx)
        self.stats["replicas_retired"] += 1
        logger.info(f"autoscaler: draining replica {victim.idx} for retire")
        self._wake.set()
        return True

    def _rebalance(self, phase):
        """Autoscaler actuator: flip ONE replica's role toward the
        saturated ``phase``. Prefers a pure opposite-role replica, then a
        non-primary mixed one; set_role's both-phases-coverable invariant
        (ValueError) is the backstop — a rejected flip reports False and
        the controller retries after its cooldown."""
        opposite = "decode" if phase == "prefill" else "prefill"
        eligible = [r for r in self.replicas
                    if not r.retired and not r.sick and not r.pending_drain]
        cands = ([r for r in eligible if r.phase_role == opposite]
                 + [r for r in eligible
                    if r.phase_role == "mixed" and r.idx != 0])
        for rep in cands:
            was = rep.phase_role
            try:
                self.replicas.set_role(rep.idx, phase)
            except ValueError:
                continue
            logger.info(f"autoscaler: re-balanced replica {rep.idx} "
                        f"{was}->{phase}")
            self._wake.set()
            return True
        return False

    def _set_brownout(self, level):
        """Autoscaler actuator: move the shedding ladder to ``level``.
        Level 0 lifts the brownout (parked work resumes, the door reopens).
        Odd levels EVICT the FairQueue's flows below the level's tier (503
        + brownout Retry-After) and keep shedding arrivals below the bar at
        the door; even levels additionally PREEMPT in-flight work below the
        tier — cancelled outright, or parked for resume through the
        migrate-out transport when ``brownout_park`` is on and a KV demote
        tier exists. De-escalation never re-preempts: stepping DOWN from an
        even level releases parked work."""
        ctl = self.autoscaler
        cfg = ctl.config
        tel = self.telemetry
        prev = ctl.brownout_level
        if level <= 0:
            self._brownout_bar = None
            self._park_pending.clear()
            released = self.replicas.release_parked()
            if released or prev:
                logger.info(f"autoscaler: brownout lifted "
                            f"({released} parked request(s) released)")
            self._wake.set()
            return True
        tier = ctl.brownout_tier(level)
        bar = self._fair.tier_weight(tier)
        self._brownout_bar = bar
        escalating = level > prev
        if not escalating and prev % 2 == 0:
            # stepping down out of a preemption level: stop preempting and
            # let parked decode state resume (the calm signal that drove
            # the de-escalation says there is capacity again)
            self._park_pending.clear()
            self.replicas.release_parked()
        if escalating and level % 2 == 1:
            # evict the queued backlog below the tier, oldest first; each
            # evicted row owes its client a 503 + brownout Retry-After
            retry = str(int(cfg.brownout_retry_after_s))
            for greq, _tenant, _prio in self._fair.evict_flows(tier):
                self.stats["shed_503"] += 1
                self.stats["brownout_evicted"] += 1
                if tel.enabled:
                    tel.counter("gateway/shed_503")
                    tel.counter("autoscale/brownout_evicted")
                if greq.trace is not None:
                    greq.trace.instant("brownout_evicted", level=level)
                self._post(greq, ("failed", 503,
                                  "brownout: request tier shed under overload",
                                  [("Retry-After", retry)]))
        if escalating and level % 2 == 0:
            # preempt in-flight work below the tier: park when the migrate
            # transport can hold the KV for resume, else cancel
            park = bool(cfg.brownout_park) and self.scheduler.kv_tier is not None
            for greq in list(self._active):
                if greq.finished or self._fair.tier_weight(greq.priority) >= bar:
                    continue
                self.stats["brownout_preempted"] += 1
                if tel.enabled:
                    tel.counter("autoscale/brownout_preempted")
                if park:
                    self._park_pending.add(greq)
                else:
                    greq.cancel_requested = True
                    greq.cancel_reason = "brownout"
        logger.info(f"autoscaler: brownout level {prev}->{level} "
                    f"(shedding below {tier!r})")
        self._wake.set()
        return True

    def _park_owned(self, rep):
        """Park brownout-preempted requests whose decode state ``rep``'s
        scheduler owns — must run on its pump thread (migrate_out is a
        scheduler call). Unparkable requests (mid-prefill, already
        migrating, no demote tier) fall back to cancellation so an even
        brownout level always sheds the work one way or the other."""
        for greq in list(self._park_pending):
            if greq.finished or greq.handle is None:
                self._park_pending.discard(greq)
                continue
            req = greq.handle._req
            if req.done or req.cancelled or req.migrating:
                self._park_pending.discard(greq)
                continue
            if not rep.scheduler.owns(req):
                continue  # another replica's pump parks it
            self._park_pending.discard(greq)
            if self.replicas.park_out(rep, req) is not None:
                self.stats["brownout_parked"] += 1
                if self.telemetry.enabled:
                    self.telemetry.counter("autoscale/brownout_parked")
                if greq.trace is not None:
                    greq.trace.instant("brownout_parked", replica=rep.idx)
            else:
                greq.cancel_requested = True
                greq.cancel_reason = "brownout"

    def _flush_radix(self, rep):
        """Evict ``rep``'s whole radix trie through the KV tier (each
        eviction demotes to the prefix store — with a NetPrefixStore
        attached that makes every cached prefix directory-visible), then
        join the async demote fetches so the entries are probe-visible
        before the debug endpoint answers. Runs on ``rep``'s own pump."""
        sched = rep.scheduler
        try:
            if sched.radix is not None:
                while True:
                    victim = sched.radix.evict_lru()
                    if victim is None:
                        break
                    sched.cache.reclaim(victim)
            if sched.kv_tier is not None:
                sched.kv_tier.executor.drain_fetches()
        finally:
            self._flush_radix_pending.discard(rep.idx)

    # ------------------------------------------------------------------ multi-host handoff
    def _handoff_complete(self, req, desc):
        """A cross-process prefill->decode handoff's demote landed (called
        from the KV transfer thread by the WorkerAgent's migrate hook):
        finish the gateway request with a terminal ``("handoff", desc)``
        event — the response carries the descriptor instead of further
        tokens, and the ROUTER resumes the request on a decode worker.
        Not a completion (no EMA fold, no completed count): the request's
        life continues in another process. Returns False when no in-flight
        gateway request owns ``req`` (direct-drive caller)."""
        for greq in list(self._active):
            if greq.handle is not None and greq.handle._req is req:
                self.stats["handoffs_out"] += 1
                self._finish(greq, ("handoff", desc))
                self._wake.set()
                return True
        return False

    def _admit_resume(self, greq):
        """Admit a router-POSTed resume request (event-loop thread): bypass
        the fair queue — the request was already admitted fleet-wide by the
        prefill worker — and park it in the ReplicaSet's migration queue as
        a READY record whose entry points at the remote shard. The normal
        ``admit_migrations`` pull then restores it bit-identically."""
        try:
            handle = self.replicas.inject_resume(
                greq.resume, on_token=self._make_on_token(greq),
                trace=greq.trace, collect_logits=greq.return_logits)
        except (ValueError, KeyError, TypeError) as e:
            self.stats["rejected"] += 1
            self._post(greq, ("failed", 400, f"bad resume descriptor: {e}"))
            return
        greq.handle = handle
        greq.admit_ts = time.monotonic()
        self.stats["resumed_in"] += 1
        self._active.add(greq)
        if self.telemetry.enabled:
            self.telemetry.gauge("gateway/active_requests", len(self._active))
        self._wake.set()

    # ------------------------------------------------------------------ admission math
    def capacity_signals(self):
        """Live capacity-signals dict (``serving/capacity_math.py`` shape):
        the single source both the local Retry-After and the multi-host
        router's fleet-wide merge read. Backlog sums count AVAILABLE
        replicas only — a drained or pending-drain replica's queue is
        already excluded from ``total_slots``/``phase_slots``, and counting
        its backlog against capacity it no longer advertises would inflate
        the estimate for the whole drain."""
        reps = self.replicas
        sched_backlog = sum(len(r.scheduler.queue) for r in reps
                            if r.available())
        prefill_backlog = sum(len(r.scheduler.queue) for r in reps
                              if r.available() and r.prefill_capable())
        return {
            "queued": len(self._fair),
            # _active already covers parked handoffs (their handles are
            # not done) and soon-to-decode prefills — adding
            # pending_migrations() on top would double-count each parked
            # request and over-advertise the backoff
            "inflight": len(self._active),
            "sched_backlog": sched_backlog,
            "prefill_backlog": prefill_backlog,
            "total_slots": reps.total_slots(),
            "prefill_slots": reps.phase_slots("prefill"),
            "decode_slots": reps.phase_slots("decode"),
            "ema_service_s": self._ema_service_s,
            "disaggregated": reps.disaggregated(),
        }

    def _retry_after(self):
        """Advertised backoff, from live state: time for the current backlog
        to drain through the FLEET's slot pools at the measured per-request
        service time (EMA). Floor 1s; capped; integer seconds per RFC 9110.
        The math lives in ``serving/capacity_math.py`` so the multi-host
        router computes fleet-wide backoff with the SAME formula over
        merged per-worker signals (phase-aware under disaggregation: the
        estimate is the WORSE of queued-work/prefill-capacity and
        in-flight/decode-capacity, not the blended depth)."""
        return capacity_math.estimate_retry_after(
            self.capacity_signals(), self.config.retry_after_cap_s)

    def _next_rid(self):
        with self._rid_lock:
            self._rid += 1
            return self._rid

    # ------------------------------------------------------------------ HTTP layer
    async def _handle_conn(self, reader, writer):
        self._open_streams += 1
        try:
            req_line = await asyncio.wait_for(reader.readline(), 30.0)
            parts = req_line.decode("latin-1").split()
            if len(parts) < 2:
                return
            method, path = parts[0].upper(), parts[1]
            headers = {}
            # header-count bound (line LENGTH is already bounded by the
            # stream reader's 64 KiB limit): a client must not grow this
            # dict without limit
            for _ in range(128):
                line = await asyncio.wait_for(reader.readline(), 30.0)
                if line in (b"\r\n", b"\n", b""):
                    break
                key, _, val = line.decode("latin-1").partition(":")
                headers[key.strip().lower()] = val.strip()
            else:
                await self._json(writer, 431,
                                 {"error": {"message": "too many headers"}})
                return
            body = b""
            length = int(headers.get("content-length", "0") or 0)
            if length > int(self.config.max_body_bytes):
                # refuse BEFORE buffering: one fat POST must not OOM the
                # long-lived serving process
                await self._json(writer, 413,
                                 {"error": {"message": "request body exceeds "
                                            f"{self.config.max_body_bytes} bytes"}})
                return
            if length:
                body = await asyncio.wait_for(reader.readexactly(length), 30.0)
            await self._route(method, path, headers, body, reader, writer)
        except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                ConnectionError):
            pass
        except Exception:  # noqa: BLE001 — one bad conn must not kill the server
            logger.exception("gateway: connection handler failed")
        finally:
            self._open_streams -= 1
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass

    async def _route(self, method, path, headers, body, reader, writer):
        path, _, query = path.partition("?")
        if method == "GET" and path == "/healthz":
            await self._json(writer, 200, {"status": "alive"})
        elif method == "GET" and path == "/readyz":
            if self.ready and not self.draining:
                await self._json(writer, 200, {"status": "ready"})
            else:
                await self._json(writer, 503,
                                 {"status": "draining" if self.draining
                                  else "starting"},
                                 extra=[("Retry-After", str(self._retry_after()))])
        elif method == "GET" and path == "/v1/metrics":
            # content negotiation: a Prometheus scraper's Accept leads with
            # text/plain (or openmetrics); everyone else (curl */*,
            # explicit JSON) keeps the structured JSON payload
            accept = headers.get("accept", "")
            want_prom = ("format=prometheus" in query
                         or (("text/plain" in accept or "openmetrics" in accept)
                             and _JSON not in accept))
            if want_prom:
                text = prom.render(self.telemetry.snapshot(),
                                   extra_gauges=self._prom_extra()).encode()
                writer.write(self._head(
                    200, "text/plain; version=0.0.4; charset=utf-8",
                    length=len(text)) + text)
                await writer.drain()
            else:
                await self._json(writer, 200, self._metrics())
        elif method == "GET" and path == "/v1/slo":
            state = (self.slo.state() if self.slo is not None
                     else {"enabled": False,
                           "reason": "telemetry disabled or no objectives"})
            await self._json(writer, 200, state)
        elif method == "GET" and path == "/v1/debug/flight":
            dump = self.telemetry.dump_flight("debug_endpoint")
            if dump is None:
                await self._json(writer, 503,
                                 {"error": {"message": "flight recorder off, "
                                            "or rate-limited"}})
            else:
                await self._json(writer, 200,
                                 {"path": dump,
                                  "note": "file lands after the recorder's "
                                          "post-window elapses"})
        elif method == "POST" and path == "/v1/debug/profile":
            if self.profiler is None:
                await self._json(writer, 503,
                                 {"error": {"message": "telemetry disabled: "
                                            "no profile output path"}})
            else:
                try:
                    req = json.loads(body) if body else {}
                except ValueError:
                    req = {}
                duration_s = float(req.get("duration_ms", 1000.0) or 1000.0) / 1e3
                from ..telemetry.profiler import ProfileBusy
                try:
                    trace_dir = self.profiler.start(duration_s, tag="ondemand")
                except ProfileBusy as e:
                    await self._json(writer, 409, {"error": {"message": str(e)}})
                else:
                    await self._json(writer, 200,
                                     {"path": trace_dir,
                                      "duration_ms": duration_s * 1e3,
                                      "note": "trace files land when the "
                                              "capture window elapses"})
        elif method == "GET" and path == "/v1/autoscaler":
            if self.autoscaler is None:
                await self._json(writer, 200,
                                 {"enabled": False,
                                  "reason": "no continuous_batching.autoscaler "
                                            "config section"})
            else:
                await self._json(writer, 200, self.autoscaler.state())
        elif method == "POST" and path == "/v1/autoscaler":
            if self.autoscaler is None:
                await self._json(writer, 503,
                                 {"error": {"message": "no autoscaler "
                                            "configured"}})
                return
            try:
                req = json.loads(body.decode("utf-8") or "{}")
            except (UnicodeDecodeError, json.JSONDecodeError) as e:
                await self._json(writer, 400, {"error": {"message": str(e)}})
                return
            if not isinstance(req, dict) or \
                    not set(req) <= {"enabled", "dry_run"}:
                await self._json(writer, 400,
                                 {"error": {"message": "body must be a JSON "
                                            "object with only 'enabled' and/or "
                                            "'dry_run' keys"}})
                return
            changed = self.autoscaler.admin(req)
            self._wake.set()
            await self._json(writer, 200,
                             {"changed": changed, **self.autoscaler.state()})
        elif method == "POST" and path == "/v1/store/fetch":
            # multi-host prefix/handoff store: serve THIS shard's KV bytes
            # to a remote restore (memory/net_store.py's wire format: one
            # meta JSON line + concatenated raw leaf bytes). Runs in an
            # executor thread — the pop may do an NVMe load, and the event
            # loop must keep serving heartbeats meanwhile.
            if self.net_store is None:
                await self._json(writer, 404,
                                 {"error": {"message": "no networked store "
                                            "attached (worker mode only)"}})
                return
            try:
                req = json.loads(body.decode("utf-8") or "{}")
                key = tuple(int(t) for t in req["key"])
                consume = bool(req.get("consume", True))
            except (ValueError, KeyError, TypeError, UnicodeDecodeError) as e:
                await self._json(writer, 400, {"error": {"message": str(e)}})
                return
            loop = asyncio.get_running_loop()
            out = await loop.run_in_executor(
                None, lambda: self.net_store.serve_fetch(key, consume=consume))
            if out is None:
                await self._json(writer, 404,
                                 {"error": {"message": "entry not resident "
                                            "(claimed, reaped, or evicted)"}})
                return
            payload, blob = out
            writer.write(self._head(200, "application/octet-stream",
                                    length=len(payload) + len(blob)))
            writer.write(payload)
            writer.write(blob)
            await writer.drain()
        elif method == "POST" and path == "/v1/debug/flush_radix":
            # force-demote every replica's radix trie through the KV tier
            # (multihost tests drive cross-host prefix restore with this);
            # each pump flushes its own scheduler, the endpoint waits
            self._flush_radix_pending |= {
                r.idx for r in self.replicas
                if not r.retired and r.scheduler.radix is not None}
            self._wake.set()
            for _ in range(600):
                if not self._flush_radix_pending:
                    break
                await asyncio.sleep(0.05)
            await self._json(writer, 200,
                             {"flushed": not self._flush_radix_pending})
        elif method == "GET" and path == "/v1/replicas":
            await self._json(writer, 200, {"replicas": self.replicas.states()})
        elif method == "POST" and path.startswith("/v1/replicas/"):
            await self._replica_admin(path, body, writer)
        elif method == "POST" and path == "/v1/completions":
            await self._completions(headers, body, reader, writer)
        else:
            await self._json(writer, 404, {"error": {"message": f"no route {method} {path}"}})

    async def _replica_admin(self, path, body, writer):
        """``POST /v1/replicas/<idx>/drain`` stops placement onto a replica
        (in-flight work finishes; resumable); ``.../resume`` re-admits it
        (clearing drain AND sick — the operator asserting recovery);
        ``.../role`` (body ``{"role": "prefill"|"decode"|"mixed"}``) flips
        its phase role at runtime — disaggregation's per-replica override
        (the fleet must keep both phases coverable; violations 400)."""
        parts = path.strip("/").split("/")  # v1 replicas <idx> <action>
        if len(parts) != 4 or parts[3] not in ("drain", "resume", "role"):
            await self._json(writer, 404,
                             {"error": {"message": "POST /v1/replicas/<idx>/"
                                        "{drain|resume|role}"}})
            return
        try:
            idx = int(parts[2])
            if not 0 <= idx < len(self.replicas):
                raise ValueError
        except ValueError:
            await self._json(writer, 400,
                             {"error": {"message": f"no replica {parts[2]!r} "
                                        f"(fleet size {len(self.replicas)})"}})
            return
        if parts[3] == "role":
            try:
                req = json.loads(body.decode("utf-8") or "{}")
                role = req.get("role") if isinstance(req, dict) else None
                state = self.replicas.set_role(idx, role)
            except (ValueError, UnicodeDecodeError,
                    json.JSONDecodeError) as e:
                await self._json(writer, 400, {"error": {"message": str(e)}})
                return
        else:
            state = (self.replicas.drain(idx) if parts[3] == "drain"
                     else self.replicas.resume(idx))
        self._wake.set()
        await self._json(writer, 200, {"replica": state})

    def _prom_extra(self):
        """Gateway/scheduler state the sink doesn't own, exposed as plain
        gauges on the Prometheus surface so a scraper sees one coherent
        endpoint."""
        sched = self.scheduler
        out = {
            "gateway/ready": 1.0 if (self.ready and not self.draining) else 0.0,
            "gateway/queue_depth": float(len(self._fair)),
            "gateway/active_requests": float(len(self._active)),
            "gateway/oldest_queue_wait_s": self._fair.oldest_wait_s(),
            "gateway/retry_after_s": float(self._retry_after()),
            "scheduler/num_slots": float(sched.num_slots),
            "scheduler/active_slots": float(sched.cache.active_slots),
            "scheduler/slot_occupancy": float(sched.cache.occupancy()),
            "scheduler/compiled_programs": float(sched.compiled_program_count()),
            # elastic fleet: "replicas" is the LIVE (non-retired) count —
            # a scraped capacity dashboard must not count freed pools
            "serving/replicas": float(self.replicas.active_count()),
            "serving/replicas_available": float(
                sum(1 for r in self.replicas if r.available())),
            "serving/replicas_pending_drain": float(
                sum(1 for r in self.replicas
                    if r.pending_drain and not r.retired)),
            "serving/tp_size": float(sched.tp_size),
            "serving/ep_size": float(sched.ep_size),
        }
        if sched.experts is not None:
            out.update({
                "serving/experts_resident": sched.experts.resident_fraction(),
                "serving/expert_loads": float(sched.experts.loads),
                "serving/expert_evicts": float(sched.experts.evicts),
                # replays are per-scheduler state (the store is fleet-shared
                # but each replica runs its own replay loop): sum the fleet
                "serving/expert_replays": float(
                    sum(r.scheduler.expert_replays for r in self.replicas)),
            })
        if self.replicas.disaggregated():
            # phase split + handoff pressure (the decode-side half of the
            # phase-aware Retry-After, scrapeable): per-replica roles are in
            # /v1/replicas; migrations_{out,in} fold as {replica=...}
            # counter series through the telemetry sink
            out.update({
                "serving/replicas_prefill_capable": float(
                    sum(1 for r in self.replicas
                        if r.available() and r.prefill_capable())),
                "serving/replicas_decode_capable": float(
                    sum(1 for r in self.replicas
                        if r.available() and r.decode_capable())),
                "serving/migrations_pending": float(
                    self.replicas.pending_migrations()),
            })
        if sched.adapters is not None:
            out.update({
                "serving/adapters_registered": float(
                    len(sched.adapters.registered())),
                "serving/adapters_resident": float(
                    sched.adapters.stats()["resident"]),
                "serving/adapter_hit_rate": sched.adapters.hit_rate(),
            })
        if self.autoscaler is not None:
            out["autoscale/enabled"] = 1.0 if self.autoscaler.enabled else 0.0
            out["autoscale/brownout_level"] = float(self.autoscaler.brownout_level)
            for action, n in self.autoscaler.counters.items():
                out[f"autoscale/decisions_{action}"] = float(n)
        return out

    def _metrics(self):
        sched = self.scheduler
        return {
            "ready": self.ready,
            "draining": self.draining,
            "gateway": {**self.stats,
                        "queue_depth": len(self._fair),
                        "active_requests": len(self._active),
                        "queue_depth_per_flow": {"/".join(k): v
                                                 for k, v in self._fair.depths().items()},
                        "ema_service_s": self._ema_service_s,
                        "oldest_queue_wait_s": self._fair.oldest_wait_s(),
                        "retry_after_s": self._retry_after()},
            "slo": self.slo.state() if self.slo is not None else None,
            "scheduler": {"num_slots": sched.num_slots,
                          "active_slots": sched.cache.active_slots,
                          "queue_depth": len(sched.queue),
                          "slot_occupancy": sched.cache.occupancy(),
                          "compiled_programs": sched.compiled_program_count(),
                          "tp_size": sched.tp_size,
                          "ep_size": sched.ep_size,
                          # fused decode blocks: whether the step programs
                          # run 3 resident kernels/layer, and the per-
                          # condition reasons when they don't
                          "fused_decode_block": getattr(
                              sched, "_fused_block", False),
                          "fused_decode_reasons": list(getattr(
                              sched, "_fused_block_reasons", ()))},
            "adapters": (sched.adapters.stats()
                         if sched.adapters is not None else None),
            "expert_store": (sched.experts.stats()
                             if sched.experts is not None else None),
            "replicas": self.replicas.states(),
            # elastic fleet controller rollup (live detail: /v1/autoscaler)
            "autoscaler": (self.autoscaler.state()
                           if self.autoscaler is not None else None),
            # capacity rollup (telemetry/capacity.py): per-compiled-program
            # roofline table + goodput + host-gap totals for the primary
            # scheduler; the live gauges are in the telemetry snapshot
            "capacity": ({
                "programs": sched.capacity.program_table(),
                "goodput_fraction": sched.capacity.goodput_fraction,
                "samples": sched.capacity.samples,
                "host_gaps": sched._gap.gaps,
                "host_gap_total_s": round(sched._gap.total_gap_s, 6),
                "profiling": (self.profiler.active
                              if self.profiler is not None else None),
            } if sched.capacity is not None else None),
            # disaggregated serving rollup (per-replica phase_role and
            # migrations_{out,in} are in the replicas list above)
            "disaggregation": ({
                "roles": [r.phase_role for r in self.replicas],
                "migrations": sum(r.scheduler.migrations_out
                                  for r in self.replicas),
                "pending": self.replicas.pending_migrations(),
                "failed": self.replicas.migrations_failed,
                "migrate_min_tokens": self.replicas.migrate_min_tokens,
            } if self.replicas.disaggregated() else None),
            # multi-host serving: the networked shard's traffic counters
            # (net_bytes_{in,out}, remote_restores, leases_expired, ...) —
            # present only when a WorkerAgent attached a NetPrefixStore
            "net_store": (self.net_store.stats()
                          if self.net_store is not None else None),
            "telemetry": self.telemetry.snapshot(),
        }

    # -------------------------------------------------------------- completions
    def _parse_completion(self, headers, body):
        """Request body -> kwargs. Raises ValueError with a client-facing
        message on malformed input."""
        try:
            req = json.loads(body.decode("utf-8") or "{}")
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise ValueError(f"body is not valid JSON: {e}")
        if not isinstance(req, dict):
            raise ValueError("body must be a JSON object")
        resume = req.get("resume")
        if resume is not None:
            # cross-process migration resume (router -> decode worker): the
            # descriptor IS the request — prompt/sampling params travel in
            # it so the resumed decode is bit-identical to the in-process
            # continuation it replaces
            if not isinstance(resume, dict):
                raise ValueError("'resume' must be a handoff descriptor object")
            for field in ("key", "kv_len", "version", "owner_url", "prompt",
                          "max_new_tokens"):
                if field not in resume:
                    raise ValueError(f"resume descriptor missing {field!r}")
            req = dict(req, prompt=resume["prompt"],
                       max_tokens=int(resume["max_new_tokens"]),
                       eos_token_id=resume.get("eos_token_id"),
                       do_sample=resume.get("do_sample", False),
                       temperature=resume.get("temperature", 0.0),
                       top_k=resume.get("top_k", 0),
                       top_p=resume.get("top_p", 1.0),
                       seed=resume.get("seed", 0),
                       adapter_id=resume.get("adapter_id"))
        prompt = req.get("prompt")
        if isinstance(prompt, str):
            try:
                prompt = [int(t) for t in prompt.split()]
            except ValueError:
                raise ValueError("string prompts must be whitespace-separated "
                                 "decimal token ids (the engine has no tokenizer)")
        if (not isinstance(prompt, (list, tuple)) or not prompt
                or not all(isinstance(t, int) and not isinstance(t, bool) for t in prompt)):
            raise ValueError("'prompt' must be a non-empty list of token ids")
        cfg = self.config
        max_tokens = req.get("max_tokens", cfg.default_max_tokens)
        if not isinstance(max_tokens, int) or max_tokens < 0:
            raise ValueError("'max_tokens' must be a non-negative integer")
        temperature = float(req.get("temperature") or 0.0)
        do_sample = bool(req.get("do_sample", temperature > 0.0))
        timeout_s = req.get("timeout_s")
        if timeout_s is None:
            timeout_s = float(cfg.request_timeout_s)  # <= 0: operator opt-out
        else:
            if not isinstance(timeout_s, (int, float)) \
                    or isinstance(timeout_s, bool) or timeout_s <= 0:
                # a client 0/negative must NOT mean "no deadline": only the
                # operator (request_timeout_s <= 0) can disable the policy
                raise ValueError("'timeout_s' must be a positive number")
            timeout_s = float(timeout_s)
            if cfg.request_timeout_s > 0:  # body overrides downward only
                timeout_s = min(timeout_s, float(cfg.request_timeout_s))
        tenant = (headers.get(cfg.tenant_header.lower())
                  or req.get("user") or "anonymous")
        priority = (headers.get(cfg.priority_header.lower())
                    or req.get("priority") or cfg.default_priority)
        sched = self.scheduler
        # model variant (multi-LoRA serving): `adapter_id` selects a
        # registered LoRA adapter; `model` doubles as the OpenAI-shaped
        # spelling when it names one. Unknown/unavailable ids 400 here —
        # never after queueing
        adapter_id = req.get("adapter_id")
        if adapter_id is None:
            m = req.get("model")
            if (isinstance(m, str) and sched.adapters is not None
                    and m in sched.adapters.registered()):
                adapter_id = m
        if adapter_id is not None:
            if not isinstance(adapter_id, str):
                raise ValueError("'adapter_id' must be a string")
            if sched.adapters is None:
                raise ValueError("multi-LoRA serving is not enabled "
                                 "(continuous_batching.multi_lora)")
            sched.adapters.check_registered(adapter_id)
        # capacity pre-check mirrors DecodeScheduler.submit's validation so
        # impossible requests 400 immediately instead of queueing first
        budget = _round_up(max(1, max_tokens), sched.steps_per_sync)
        # spannable capacity: one request may chain up to
        # long_context.max_extents slot extents (chunked mode; the
        # monolithic path stays bounded by one slot)
        cap = (sched.cache.spannable_len if sched.prefill_chunk > 0
               else sched.max_len)
        if len(prompt) >= cap or len(prompt) + budget > cap:
            raise ValueError(
                f"prompt ({len(prompt)} tokens) + max_tokens ({max_tokens}) exceeds "
                f"the per-slot KV capacity {sched.max_len} x "
                f"{sched.cache.max_extents} extent(s) = {cap} spannable rows")
        return dict(
            prompt=np.asarray(prompt, np.int32),
            max_new_tokens=max_tokens,
            eos_token_id=req.get("eos_token_id"),
            do_sample=do_sample,
            temperature=temperature if temperature > 0 else 1.0,
            top_k=int(req.get("top_k") or 0),
            top_p=float(req.get("top_p") or 1.0),
            seed=int(req.get("seed") or 0),
            tenant=str(tenant),
            priority=str(priority),
            deadline=(time.monotonic() + timeout_s) if timeout_s > 0 else None,
            stream=bool(req.get("stream", False)),
            adapter_id=adapter_id,
            return_logits=bool(req.get("return_logits", False)),
            resume=resume,
        )

    async def _completions(self, headers, body, reader, writer):
        tel = self.telemetry
        self.stats["requests"] += 1
        if tel.enabled:
            tel.counter("gateway/requests")
        if self.draining or not self.ready:
            self.stats["shed_503"] += 1
            if tel.enabled:
                tel.counter("gateway/shed_503")
            await self._json(writer, 503,
                             {"error": {"message": "gateway is draining",
                                        "type": "unavailable"}},
                             extra=[("Retry-After", str(self._retry_after()))])
            return
        try:
            kwargs = self._parse_completion(headers, body)
        except (ValueError, TypeError) as e:
            # TypeError covers non-numeric JSON (e.g. "top_k": [1]) reaching
            # int()/float(): still a client error, must answer 400 — not a
            # logged exception and a silently dropped connection
            self.stats["rejected"] += 1
            await self._json(writer, 400,
                             {"error": {"message": str(e), "type": "invalid_request"}})
            return
        # brownout door: while the shedding ladder is engaged, arrivals in
        # priority classes below the bar 503 immediately with the brownout
        # Retry-After — evicting the backlog once and then re-queueing the
        # same tier would just rebuild it
        bar = self._brownout_bar
        if bar is not None and self._fair.tier_weight(kwargs["priority"]) < bar:
            self.stats["shed_503"] += 1
            self.stats["brownout_shed"] += 1
            if tel.enabled:
                tel.counter("gateway/shed_503")
                tel.counter("autoscale/brownout_shed")
            retry = str(int(self.autoscaler.config.brownout_retry_after_s))
            await self._json(writer, 503,
                             {"error": {"message": "brownout: request tier "
                                        "shed under overload",
                                        "type": "overloaded"}},
                             extra=[("Retry-After", retry)])
            return
        # request identity: accept an inbound W3C traceparent / x-request-id,
        # else mint one; echoed back as x-request-id and used as the span
        # tree's track id when request tracing is on
        trace_id, parent, _ = extract_trace_context(headers)
        trace = None
        if tel.enabled and getattr(tel, "trace_requests", False):
            trace = RequestTrace(tel, trace_id, parent,
                                 tenant=kwargs["tenant"],
                                 priority=kwargs["priority"])
            trace.mark("queued")
        greq = _GatewayRequest(self._next_rid(), loop=asyncio.get_running_loop(),
                               trace=trace, trace_id=trace_id, **kwargs)
        if trace is not None:
            trace.rid = greq.rid
            # per-request track: a client may reuse an x-request-id across
            # concurrent retries, and two requests must never share one
            # async track (interleaved trees, colliding flow ids). The bare
            # id is still what x-request-id echoes.
            trace.track = f"{trace_id}:{greq.rid}"
        if greq.resume is not None:
            # cross-process resume: fleet-wide admission already happened on
            # the prefill worker — parking it behind the fair queue would
            # double-charge its tenant and could deadlock a full queue
            self._admit_resume(greq)
            if greq.stream:
                await self._respond_stream(greq, reader, writer)
            else:
                await self._respond_unary(greq, reader, writer)
            return
        try:
            self._fair.push(greq, greq.tenant, greq.priority, cost=greq.cost,
                            adapter=greq.adapter_id)
        except QueueFull:
            self.stats["shed_429"] += 1
            if tel.enabled:
                tel.counter("gateway/shed_429")
            await self._json(writer, 429,
                             {"error": {"message": "server overloaded: request "
                                        "queue is full, retry later",
                                        "type": "overloaded"}},
                             extra=[("Retry-After", str(self._retry_after())),
                                    ("x-request-id", greq.trace_id)])
            return
        if tel.enabled:
            tel.gauge("gateway/queue_depth", len(self._fair))
        self._wake.set()
        if greq.stream:
            await self._respond_stream(greq, reader, writer)
        else:
            await self._respond_unary(greq, reader, writer)

    async def _next_event(self, greq, eof_task):
        """One event from the pump, or ('disconnect',) when the client goes
        away first. The generous timeout is a safety net — the pump enforces
        the real deadline. With deadlines disabled by the OPERATOR
        (``request_timeout_s <= 0``) there is no safety net either: the
        opt-out must not collapse into a ~90s ceiling."""
        if self.config.request_timeout_s > 0:
            timeout = (self.config.request_timeout_s
                       + self.config.drain_timeout_s + 30)
        else:
            timeout = None
        get_task = asyncio.ensure_future(greq.events.get())
        done, _ = await asyncio.wait({get_task, eof_task}, timeout=timeout,
                                     return_when=asyncio.FIRST_COMPLETED)
        if get_task in done:
            return get_task.result()
        get_task.cancel()
        if eof_task in done:
            return ("disconnect", )
        # safety-net trip: CANCEL the request, don't just abandon it — an
        # orphan would sit in the fair queue (or its slot) and decode a full
        # budget for a client that already got the 500
        greq.cancel_requested = True
        greq.cancel_reason = "gateway timeout"
        self._wake.set()
        return ("failed", 500, "gateway timed out waiting on the scheduler")

    def _client_gone(self, greq):
        self.stats["disconnects"] += 1
        if self.telemetry.enabled:
            self.telemetry.counter("gateway/disconnects")
        greq.cancel_requested = True
        greq.cancel_reason = "disconnect"
        self._wake.set()

    @staticmethod
    async def _watch_eof(reader):
        """Resolves when the client closes its half of the connection (EOF
        past the request body = nothing more to pipeline on a
        Connection: close exchange)."""
        try:
            while True:
                data = await reader.read(4096)
                if not data:
                    return
        except Exception:  # noqa: BLE001 — reset == gone
            return

    def _chunk(self, greq, toks, finish_reason):
        return {"id": f"cmpl-{greq.rid}", "object": "text_completion.chunk",
                "model": type(self.engine.module).__name__,
                "choices": [{"index": 0,
                             "text": "".join(f"{t} " for t in toks),
                             "token_ids": toks,
                             "finish_reason": finish_reason}]}

    async def _respond_stream(self, greq, reader, writer):
        eof_task = asyncio.ensure_future(self._watch_eof(reader))
        tel = self.telemetry
        headers_sent = False
        try:
            while True:
                ev = await self._next_event(greq, eof_task)
                kind = ev[0]
                if kind == "disconnect":
                    self._client_gone(greq)
                    return
                if kind == "failed":
                    # optional 4th element: extra response headers (the
                    # brownout 503 carries its own Retry-After)
                    status, msg = ev[1], ev[2]
                    if not headers_sent:
                        await self._json(writer, status,
                                         {"error": {"message": msg}},
                                         extra=list(ev[3]) if len(ev) > 3 else ())
                    return
                if not headers_sent:
                    headers_sent = True
                    writer.write(self._head(200, "text/event-stream",
                                            [("Cache-Control", "no-cache"),
                                             ("x-request-id", greq.trace_id)]))
                    if tel.enabled:
                        tel.histogram("gateway/ttfb_ms",
                                      (time.monotonic() - greq.enq_ts) * 1e3)
                if kind == "token":
                    _, tok, reason = ev
                    payload = json.dumps(self._chunk(greq, [tok], reason))
                    writer.write(f"data: {payload}\n\n".encode())
                    await writer.drain()
                    if reason is not None:
                        break
                elif kind == "done":
                    payload = json.dumps(self._chunk(greq, [], ev[1]))
                    writer.write(f"data: {payload}\n\n".encode())
                    break
                elif kind == "cancelled":
                    payload = json.dumps(self._chunk(greq, [], ev[1]))
                    writer.write(f"data: {payload}\n\n".encode())
                    break
                elif kind == "handoff":
                    # cross-process migration: the stream ends HERE with the
                    # handoff descriptor — the router (the only client that
                    # ever sees this event) consumes it, resumes the request
                    # on a decode worker, and stitches that worker's stream
                    # onto everything already relayed
                    writer.write(f"data: {json.dumps({'handoff': ev[1]})}\n\n"
                                 .encode())
                    break
            writer.write(b"data: [DONE]\n\n")
            await writer.drain()
        except ConnectionError:
            self._client_gone(greq)
        finally:
            eof_task.cancel()

    async def _respond_unary(self, greq, reader, writer):
        eof_task = asyncio.ensure_future(self._watch_eof(reader))
        toks = []
        finish_reason = None
        handoff = None
        try:
            while True:
                ev = await self._next_event(greq, eof_task)
                kind = ev[0]
                if kind == "disconnect":
                    self._client_gone(greq)
                    return
                if kind == "failed":
                    status, msg = ev[1], ev[2]
                    await self._json(writer, status, {"error": {"message": msg}},
                                     extra=list(ev[3]) if len(ev) > 3 else ())
                    return
                if kind == "token":
                    _, tok, reason = ev
                    toks.append(tok)
                    if reason is not None:
                        finish_reason = reason
                        break
                elif kind == "done":
                    finish_reason = ev[1]
                    break
                elif kind == "cancelled":
                    finish_reason = ev[1]
                    break
                elif kind == "handoff":
                    # cross-process migration: partial response — the tokens
                    # decoded so far plus the descriptor the router needs to
                    # resume the request on a decode worker and concatenate
                    finish_reason = "handoff"
                    handoff = ev[1]
                    break
            if finish_reason == "deadline" and not toks:
                await self._json(writer, 504,
                                 {"error": {"message": "deadline expired"}},
                                 extra=[("x-request-id", greq.trace_id)])
                return
            if self.telemetry.enabled:
                self.telemetry.histogram("gateway/ttfb_ms",
                                         (time.monotonic() - greq.enq_ts) * 1e3)
            out = {
                "id": f"cmpl-{greq.rid}", "object": "text_completion",
                "model": type(self.engine.module).__name__,
                "choices": [{"index": 0,
                             "text": " ".join(str(t) for t in toks),
                             "token_ids": toks,
                             "finish_reason": finish_reason}],
                "usage": {"prompt_tokens": int(len(greq.prompt)),
                          "completion_tokens": len(toks),
                          "total_tokens": int(len(greq.prompt)) + len(toks)},
            }
            if handoff is not None:
                out["handoff"] = handoff
            if greq.return_logits and greq.handle is not None:
                # float32 -> JSON double is exact: the logits survive the
                # process boundary bitwise (the multihost identity matrix
                # asserts on them)
                out["logits"] = [np.asarray(step, np.float32).tolist()
                                 for step in greq.handle._req.logits]
            await self._json(writer, 200, out,
                             extra=[("x-request-id", greq.trace_id)])
        except ConnectionError:
            self._client_gone(greq)
        finally:
            eof_task.cancel()

    # ------------------------------------------------------------------ HTTP writing
    _REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
                409: "Conflict",
                413: "Content Too Large", 429: "Too Many Requests",
                431: "Request Header Fields Too Large",
                503: "Service Unavailable", 504: "Gateway Timeout",
                500: "Internal Server Error"}

    def _head(self, status, ctype, extra=(), length=None):
        lines = [f"HTTP/1.1 {status} {self._REASONS.get(status, 'Unknown')}",
                 f"Content-Type: {ctype}", "Connection: close"]
        if length is not None:
            lines.append(f"Content-Length: {length}")
        for key, val in extra:
            lines.append(f"{key}: {val}")
        return ("\r\n".join(lines) + "\r\n\r\n").encode()

    async def _json(self, writer, status, obj, extra=()):
        body = json.dumps(obj).encode()
        writer.write(self._head(status, _JSON, extra, length=len(body)) + body)
        await writer.drain()
