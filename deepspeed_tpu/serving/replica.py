"""Multi-replica serving: N independent decode schedulers behind one gateway.

The data-parallel half of pod-scale serving (the tensor-parallel half lives
in the scheduler's sharded step programs): a :class:`ReplicaSet` fronts N
:class:`~deepspeed_tpu.inference.scheduler.DecodeScheduler` replicas — each
its own slot pool (tp-sharded over the mesh's ``tensor`` axis when tp>1) —
behind one dispatch policy, in the AlpaServe/"replica groups" sense rather
than N processes: one weight tree, ONE compiled program set (replicas share
the primary scheduler's program cache, so replica count adds ZERO XLA
programs), N independent KV pools and decode loops.

Dispatch policy (the gateway's fair queue pops in DRR order, then this
layer places):

- **Prefix-sticky**: prompts whose leading ``prefill_chunk`` tokens match a
  previously-dispatched prompt route to the replica that served it — that
  replica's radix trie holds the prefix, so admission copies KV instead of
  recomputing prefill. The sticky index is a bounded host-side LRU keyed on
  the leading chunk (NOT a cross-thread read of another replica's trie —
  pump threads own their schedulers), re-pointed whenever placement falls
  elsewhere, so it tracks the most recent owner exactly like the trie's MRU
  donor choice.
- **Least-loaded**: otherwise the replica minimizing expected drain time —
  ``(busy_slots + 1) x service-time EMA`` (the same EMA the gateway's
  Retry-After advertises, tracked per replica) — with a round-robin tie
  break so an idle fleet doesn't pile onto replica 0.

Per-replica lifecycle: ``drain(i)`` stops placement and lets in-flight work
finish (resumable); a replica whose ``step()`` raises is marked **sick** —
its requests fail, its sticky entries purge, and the rest of the fleet keeps
serving (one sick replica sheds instead of sinking the fleet). A sick
replica can be ``resume()``d after operator intervention.

With the hierarchical KV tier enabled (``continuous_batching.
hierarchical_kv``), the fleet additionally shares ONE host-side prefix
store (``memory/prefix_store.GlobalPrefixStore`` — threaded through the
scheduler's ``_init_kwargs`` exactly like the shared compiled-program
cache): a prefix radix-evicted on any replica demotes there, and ANY
replica's admission can restore it, so sticky routing misses stop being
cold prefills.

Why replicas (vs one bigger pool): each replica is its own scheduler loop —
on a pod, its own tensor-sharded device group stepping independently; on
one host, independent pools whose aggregate KV capacity (and radix
residency) scales with N. Compile count stays O(1) because programs are
per-shard-SHAPE, not per-replica.

Telemetry: gauges ``serving/replica/<id>/{slot_occupancy,queue_depth,
tok_s}``; counters ``serving/replica/<id>/{dispatched,tokens}``,
``serving/dispatch/{sticky,least_loaded}``, ``serving/replica_sick``,
``serving/replica_drains``. All reach ``/v1/metrics`` JSON and render as
labeled Prometheus series (``telemetry/prometheus.py``).
"""

import collections
import threading
import time

import numpy as np


class Replica:
    """One scheduler + its fleet bookkeeping (placement load signals,
    health/drain state, throughput EMA). The scheduler itself stays
    single-threaded: exactly one pump thread calls :meth:`step`."""

    def __init__(self, idx, scheduler, telemetry=None):
        self.idx = idx
        self.scheduler = scheduler
        self.telemetry = telemetry if telemetry is not None else scheduler.telemetry
        self.draining = False
        self.sick = False
        self.sick_error = None
        self.dispatched = 0
        self.tokens = 0
        self.ema_service_s = None   # per-replica Retry-After-style service EMA
        self.tok_s = 0.0            # EWMA of delivered tokens/sec
        self._last_step_end = None

    # ---------------------------------------------------------------- load
    def busy_slots(self):
        s = self.scheduler
        return (s.cache.active_slots + len(s.queue)
                + (1 if s._prefill is not None else 0))

    def has_capacity(self):
        return self.busy_slots() < self.scheduler.num_slots

    def available(self):
        """Placement-eligible: healthy and accepting new work."""
        return not self.sick and not self.draining

    def idle(self):
        s = self.scheduler
        return not (s.active or s.queue or s._prefill is not None)

    def expected_drain_s(self, fallback_ema):
        """Placement score: expected time for this replica's backlog (+ the
        incoming request) to clear at its measured service rate."""
        ema = self.ema_service_s if self.ema_service_s is not None else fallback_ema
        return (self.busy_slots() + 1) * ema / max(1, self.scheduler.num_slots)

    # ---------------------------------------------------------------- loop
    def step(self):
        """One scheduler iteration plus throughput accounting. Called ONLY
        from this replica's pump thread."""
        t0 = time.monotonic()
        delivered = self.scheduler.step()
        now = time.monotonic()
        self.tokens += delivered
        # inter-step host overhead counts, but an IDLE gap (pump parked
        # waiting for work) must not: a lull would fold a near-zero sample
        # into the EWMA and understate a lightly-loaded replica
        prev = self._last_step_end
        start = prev if (prev is not None and t0 - prev < 1.0) else t0
        dt = now - start
        self._last_step_end = now
        if dt > 0:
            inst = delivered / dt
            self.tok_s = inst if self.tok_s == 0.0 else 0.9 * self.tok_s + 0.1 * inst
        tel = self.telemetry
        if tel.enabled:
            tel.gauges([
                (f"serving/replica/{self.idx}/slot_occupancy",
                 self.scheduler.cache.occupancy(), None),
                (f"serving/replica/{self.idx}/queue_depth",
                 float(len(self.scheduler.queue)), None),
                (f"serving/replica/{self.idx}/tok_s", self.tok_s, None)])
            if delivered:
                tel.counter(f"serving/replica/{self.idx}/tokens", delivered)
        return delivered

    def observe_service(self, service_s):
        """Fold one naturally-completed request's wall time into the
        placement EMA (same exclusion rule as the gateway's Retry-After EMA:
        cancelled/failed requests don't count)."""
        self.ema_service_s = (service_s if self.ema_service_s is None
                              else 0.9 * self.ema_service_s + 0.1 * service_s)

    def state(self):
        s = self.scheduler
        return {
            "idx": self.idx,
            "status": ("sick" if self.sick else
                       "draining" if self.draining else "active"),
            "error": self.sick_error,
            "num_slots": s.num_slots,
            "active_slots": s.cache.active_slots,
            "cached_slots": s.cache.cached_slots,
            "queue_depth": len(s.queue),
            "slot_occupancy": round(s.cache.occupancy(), 4),
            "dispatched": self.dispatched,
            "tokens": self.tokens,
            "tok_s": round(self.tok_s, 2),
            "ema_service_s": self.ema_service_s,
            "tp_size": s.tp_size,
            "prefix_cache_hit_rate": (round(s.radix.hit_rate(), 4)
                                      if s.radix is not None else None),
            # hierarchical KV tier (fleet-global host store shared by every
            # replica): this replica's demote/restore counts plus the shared
            # store's residency — any replica can restore a prefix any
            # other computed (memory/kv_tier.py)
            "kv_tier": s.kv_tier.stats() if s.kv_tier is not None else None,
            # multi-LoRA: the fleet-shared paged adapter store (one object,
            # same numbers from every replica — an adapter loaded through
            # any replica is resident for all)
            "adapters": s.adapters.stats() if s.adapters is not None else None,
        }


class ReplicaSet:
    """N replicas behind one dispatch policy. Thread-safe: the gateway's
    pump threads race :meth:`dispatch`/:meth:`route` under the internal
    lock; each replica's ``step`` stays exclusive to its own pump."""

    def __init__(self, replicas, sticky_capacity=2048):
        if not replicas:
            raise ValueError("ReplicaSet needs at least one replica")
        self.replicas = list(replicas)
        self.telemetry = self.replicas[0].telemetry
        self._lock = threading.RLock()
        self._rr = 0  # round-robin tie break cursor
        # sticky prefix index: leading-chunk key -> replica idx (bounded LRU)
        self._sticky = collections.OrderedDict()
        self._sticky_capacity = int(sticky_capacity)
        chunk = self.primary.prefill_chunk
        self._sticky_chunk = chunk if chunk > 0 else 64

    # ---------------------------------------------------------------- build
    @classmethod
    def build(cls, engine, n=None, **scheduler_overrides):
        """N replicas over ONE engine: replica 0 is the engine's singleton
        scheduler (so a single-replica gateway is byte-for-byte the
        pre-replica path), siblings clone its exact configuration and share
        its compiled-program cache — same shapes, same programs, zero new
        XLA compiles per added replica. ``n`` defaults to the engine's
        ``continuous_batching.replicas``."""
        from ..inference.scheduler import DecodeScheduler
        if n is None:
            n = int(getattr(engine._config.continuous_batching, "replicas", 1) or 1)
        if n < 1:
            raise ValueError(f"replicas must be >= 1, got {n}")
        primary = engine.scheduler(**scheduler_overrides)
        scheds = [primary]
        for _ in range(1, n):
            scheds.append(DecodeScheduler(engine, compiled_cache=primary._compiled,
                                          **primary._init_kwargs))
        return cls([Replica(i, s) for i, s in enumerate(scheds)])

    @property
    def primary(self):
        return self.replicas[0].scheduler

    def __len__(self):
        return len(self.replicas)

    def __iter__(self):
        return iter(self.replicas)

    # ---------------------------------------------------------------- fleet state
    def total_slots(self):
        """Slots across placement-eligible replicas (the gateway's
        Retry-After backlog math divides by this)."""
        return sum(r.scheduler.num_slots for r in self.replicas
                   if r.available()) or self.replicas[0].scheduler.num_slots

    def any_capacity(self):
        return any(r.available() and r.has_capacity() for r in self.replicas)

    def healthy(self):
        return [r for r in self.replicas if not r.sick]

    def all_sick(self):
        return all(r.sick for r in self.replicas)

    def compiled_program_count(self):
        """One shared program set — the fleet's compile count IS the
        primary's (the O(1)-in-replicas guard reads this)."""
        return self.primary.compiled_program_count()

    def states(self):
        return [r.state() for r in self.replicas]

    # ---------------------------------------------------------------- lifecycle
    def drain(self, idx):
        """Stop placing onto replica ``idx``; in-flight work finishes (its
        pump keeps stepping). Idempotent; resumable."""
        with self._lock:
            rep = self.replicas[idx]
            rep.draining = True
            self._purge_sticky(idx)
        tel = self.telemetry
        if tel.enabled:
            tel.counter("serving/replica_drains")
        return rep.state()

    def resume(self, idx):
        """Re-admit replica ``idx`` to placement (clears drain AND sick —
        resuming a sick replica is the operator asserting it recovered)."""
        with self._lock:
            rep = self.replicas[idx]
            rep.draining = False
            rep.sick = False
            rep.sick_error = None
        return rep.state()

    def mark_sick(self, idx, error):
        """Health-out replica ``idx`` (its step raised): no further
        placement, sticky entries purge so its prompt families re-home.
        Idempotent — re-marking an already-sick replica neither
        re-increments the health-out counter nor re-scans the sticky map
        (a persistently-raising backend would otherwise spin both)."""
        with self._lock:
            rep = self.replicas[idx]
            if rep.sick:
                return
            rep.sick = True
            rep.sick_error = str(error)[:500]
            self._purge_sticky(idx)
        tel = self.telemetry
        if tel.enabled:
            tel.counter("serving/replica_sick")

    def _purge_sticky(self, idx):
        for key in [k for k, v in self._sticky.items() if v == idx]:
            del self._sticky[key]

    # ---------------------------------------------------------------- dispatch
    def _sticky_key(self, prompt, adapter=None):
        # the adapter id is part of the prefix identity: a prefix cached
        # under adapter A on replica 0 is COLD data for adapter B (the
        # radix roots are per-adapter), so sticky routing must not send
        # B's matching prompt there expecting a hit
        p = np.asarray(prompt, np.int32).reshape(-1)
        return (adapter, p[:self._sticky_chunk].tobytes())

    def route(self, prompt, adapter=None):
        """The replica to place ``prompt`` on, or None when no eligible
        replica has a free slot. Sticky first, least-loaded otherwise; the
        sticky index re-points to wherever placement actually lands, so the
        NEXT matching prompt follows the freshest cached copy. ``adapter``
        scopes stickiness per model variant (multi-LoRA serving)."""
        with self._lock:
            candidates = [r for r in self.replicas
                          if r.available() and r.has_capacity()]
            if not candidates:
                return None
            key = self._sticky_key(prompt, adapter)
            hit = self._sticky.get(key)
            tel = self.telemetry
            if hit is not None:
                rep = self.replicas[hit]
                if rep.available() and rep.has_capacity():
                    self._sticky.move_to_end(key)
                    if tel.enabled:
                        tel.counter("serving/dispatch/sticky")
                    return rep
                if not rep.available():
                    del self._sticky[key]  # sick/draining owner: re-home
            known = [r.ema_service_s for r in candidates
                     if r.ema_service_s is not None]
            fallback = (sum(known) / len(known)) if known else 1.0
            n = len(self.replicas)
            rep = min(candidates,
                      key=lambda r: (r.expected_drain_s(fallback),
                                     (r.idx - self._rr) % n))
            self._rr = (rep.idx + 1) % n
            self._record_sticky(key, rep.idx)
            if tel.enabled:
                tel.counter("serving/dispatch/least_loaded")
            return rep

    def _record_sticky(self, key, idx):
        self._sticky[key] = idx
        self._sticky.move_to_end(key)
        while len(self._sticky) > self._sticky_capacity:
            self._sticky.popitem(last=False)

    def dispatch(self, prompt, **submit_kwargs):
        """Route + submit in one step: returns ``(replica, handle)`` or
        ``(None, None)`` when the fleet has no free slot. The direct-drive
        entry point for benches/tests; the gateway calls :meth:`route` and
        submits itself (it owns request bookkeeping)."""
        rep = self.route(prompt, adapter=submit_kwargs.get("adapter_id"))
        if rep is None:
            return None, None
        handle = rep.scheduler.submit(prompt, **submit_kwargs)
        self.note_dispatch(rep)
        return rep, handle

    def note_dispatch(self, rep):
        """Account one placement on ``rep`` (called after a successful
        submit so failed validation doesn't skew the counters)."""
        rep.dispatched += 1
        tel = self.telemetry
        if tel.enabled:
            tel.counter(f"serving/replica/{rep.idx}/dispatched")

    # ---------------------------------------------------------------- drive (testing/bench)
    def drain_all_work(self):
        """Single-threaded convenience pump: step every replica until the
        whole fleet is idle (benches and tests; the gateway runs one pump
        thread per replica instead)."""
        while True:
            progressed = False
            for rep in self.replicas:
                if not rep.idle() and not rep.sick:
                    rep.step()
                    progressed = True
            if not progressed:
                return
