"""Multi-replica serving: N independent decode schedulers behind one gateway.

The data-parallel half of pod-scale serving (the tensor-parallel half lives
in the scheduler's sharded step programs): a :class:`ReplicaSet` fronts N
:class:`~deepspeed_tpu.inference.scheduler.DecodeScheduler` replicas — each
its own slot pool (tp-sharded over the mesh's ``tensor`` axis when tp>1) —
behind one dispatch policy, in the AlpaServe/"replica groups" sense rather
than N processes: one weight tree, ONE compiled program set (replicas share
the primary scheduler's program cache, so replica count adds ZERO XLA
programs), N independent KV pools and decode loops.

Dispatch policy (the gateway's fair queue pops in DRR order, then this
layer places):

- **Prefix-sticky**: prompts whose leading ``prefill_chunk`` tokens match a
  previously-dispatched prompt route to the replica that served it — that
  replica's radix trie holds the prefix, so admission copies KV instead of
  recomputing prefill. The sticky index is a bounded host-side LRU keyed on
  the leading chunk (NOT a cross-thread read of another replica's trie —
  pump threads own their schedulers), re-pointed whenever placement falls
  elsewhere, so it tracks the most recent owner exactly like the trie's MRU
  donor choice.
- **Least-loaded**: otherwise the replica minimizing expected drain time —
  ``(busy_slots + 1) x service-time EMA`` (the same EMA the gateway's
  Retry-After advertises, tracked per replica) — with a round-robin tie
  break so an idle fleet doesn't pile onto replica 0.

Per-replica lifecycle: ``drain(i)`` stops placement and lets in-flight work
finish (resumable); a replica whose ``step()`` raises is marked **sick** —
its requests fail, its sticky entries purge, and the rest of the fleet keeps
serving (one sick replica sheds instead of sinking the fleet). A sick
replica can be ``resume()``d after operator intervention.

With the hierarchical KV tier enabled (``continuous_batching.
hierarchical_kv``), the fleet additionally shares ONE host-side prefix
store (``memory/prefix_store.GlobalPrefixStore`` — threaded through the
scheduler's ``_init_kwargs`` exactly like the shared compiled-program
cache): a prefix radix-evicted on any replica demotes there, and ANY
replica's admission can restore it, so sticky routing misses stop being
cold prefills.

**Disaggregated prefill/decode** (``continuous_batching.disaggregation``,
DistServe/Splitwise): replicas carry a phase role — ``prefill``,
``decode``, or ``mixed`` (the default; a zero-role fleet behaves exactly
as before). Placement only considers prefill-CAPABLE replicas (``prefill``
or ``mixed``); when a prompt's chunked prefill completes on a ``prefill``
replica, the request's whole KV demotes through the shared prefix store
(``memory/kv_tier.KVTier.demote_request`` — the same two compiled
tier programs the hierarchical tier uses) and parks in the fleet's
migration queue, from which decode-capable replicas PULL as their pumps
find capacity (pull placement self-balances and makes sick-decode
failover free: a parked handoff is bound to no replica, so any healthy
decode replica re-places it). Decode resumes bit-identically — the
sampling seeds fold absolute step indices, the KV rows move byte-exact,
and the request object (tokens, logits, hooks, adapter pin) travels
as-is. ``migrate_min_tokens`` colocates short prompts (the handoff round
trip isn't worth it); a fleet whose decode side vanishes entirely falls
back to colocating on whatever is left rather than stalling.

**Elastic fleet** (``continuous_batching.autoscaler`` —
``serving/controller.py`` drives these): :meth:`ReplicaSet.add_replica`
grows the fleet at runtime over the SAME weight tree and compiled-program
dict (zero new XLA programs; warmup is pool allocation);
:meth:`ReplicaSet.begin_scale_down` / :meth:`ReplicaSet.finish_scale_down`
shrink it two-phase — pending-drain replicas stop counting toward every
advertised-capacity surface immediately, then retire from their own pump
thread once idle, releasing their KV pool's HBM;
:meth:`ReplicaSet.park_out` / :meth:`ReplicaSet.release_parked` implement
brownout preemption-with-resume over the PR 13 migrate-out transport
(held handoff records that decode pumps skip until the brownout lifts).

Why replicas (vs one bigger pool): each replica is its own scheduler loop —
on a pod, its own tensor-sharded device group stepping independently; on
one host, independent pools whose aggregate KV capacity (and radix
residency) scales with N. Compile count stays O(1) because programs are
per-shard-SHAPE, not per-replica.

Telemetry: gauges ``serving/replica/<id>/{slot_occupancy,queue_depth,
tok_s}``; counters ``serving/replica/<id>/{dispatched,tokens}``,
``serving/dispatch/{sticky,least_loaded}``, ``serving/replica_sick``,
``serving/replica_drains``. All reach ``/v1/metrics`` JSON and render as
labeled Prometheus series (``telemetry/prometheus.py``).
"""

import collections
import threading
import time

import numpy as np


# handoff-key sentinel: negative (never a real token), far below the
# adapter-uid namespace sentinels (-(uid)-1); a migration key is
#   adapter_namespace + (_MIG_SENTINEL, unique_counter)
# so adapter invalidation (store.drop_prefix on the uid namespace) reclaims
# parked handoffs too, and no probe of real prompt tokens can ever match one
_MIG_SENTINEL = -(1 << 30)

_PHASE_ROLES = ("prefill", "decode", "mixed")


class _Migration:
    """One prefill→decode handoff in flight: the request object plus where
    its KV is parked. ``entry`` stays None until the demote's async
    device→host fetch lands (``ready`` flips then) — decode pumps only see
    READY records."""

    __slots__ = ("req", "key", "kv_len", "version", "entry", "ready",
                 "src_idx", "t_start", "held")

    def __init__(self, req, key, src_idx, t_start):
        self.req = req
        self.key = key
        self.kv_len = 0
        self.version = 0
        self.entry = None
        self.ready = False
        self.src_idx = src_idx
        self.t_start = t_start
        # brownout parking (serving/controller.py): a held record is NOT
        # claimable by decode pumps — release_parked() flips it back into
        # the normal pull rotation when the brownout lifts
        self.held = False


class _FleetPump:
    """Handle-compatible pump for a migrated-out request: ``result()`` on a
    request whose handoff is parked must drive the WHOLE fleet (the prefill
    scheduler alone would spin forever), so migrate-out re-points the
    handle's scheduler here until a decode replica adopts the request."""

    __slots__ = ("_rs", "engine")

    def __init__(self, rs):
        self._rs = rs
        self.engine = rs.primary.engine

    def step(self):
        return self._rs.pump_once()


class Replica:
    """One scheduler + its fleet bookkeeping (placement load signals,
    health/drain state, phase role, throughput EMA). The scheduler itself
    stays single-threaded: exactly one pump thread calls :meth:`step`."""

    def __init__(self, idx, scheduler, telemetry=None, phase_role="mixed"):
        self.idx = idx
        self.scheduler = scheduler
        # request traces stamp the replica that executed each phase (the
        # migration-aware tools/trace_summary.py --requests view pairs a
        # prefill replica with the decode replica that adopted the handoff)
        scheduler.replica_idx = idx
        self.telemetry = telemetry if telemetry is not None else scheduler.telemetry
        self.draining = False
        self.sick = False
        self.sick_error = None
        # elastic scale-down lifecycle (serving/controller.py): pending_drain
        # = the controller is shrinking the fleet through this replica — it
        # stops counting toward EVERY advertised-capacity surface
        # (total_slots / phase_slots / Retry-After / metrics) immediately,
        # not when the drain completes; retired = drained and released (its
        # pump thread exited, its KV pool freed, its index reusable)
        self.pending_drain = False
        self.retired = False
        self.dispatched = 0
        self.tokens = 0
        # disaggregated serving: "prefill" replicas run prefills and hand
        # finished prompts to the decode side; "decode" replicas receive
        # migrations and never take fresh placements; "mixed" does both
        # (and neither migrates nor changes any pre-disaggregation behavior)
        self.phase_role = phase_role
        self.ema_service_s = None   # per-replica Retry-After-style service EMA
        self.tok_s = 0.0            # EWMA of delivered tokens/sec
        self._last_step_end = None

    # ---------------------------------------------------------------- phase
    def prefill_capable(self):
        """Eligible for fresh prompt placement (gateway/FairQueue pops)."""
        return self.phase_role in ("prefill", "mixed")

    def decode_capable(self):
        """Eligible to adopt migrated-in decode work."""
        return self.phase_role in ("decode", "mixed")

    # ---------------------------------------------------------------- load
    def busy_slots(self):
        s = self.scheduler
        return (s.cache.active_slots + len(s.queue)
                + (1 if s._prefill is not None else 0))

    def has_capacity(self):
        return self.busy_slots() < self.scheduler.num_slots

    def available(self):
        """Placement-eligible: healthy and accepting new work. A
        pending-drain (or retired) replica is never available — the
        controller's scale-down must stop it counting toward advertised
        capacity the moment the decision lands, not when the drain ends."""
        return not (self.sick or self.draining
                    or self.pending_drain or self.retired)

    def idle(self):
        s = self.scheduler
        return not (s.active or s.queue or s._prefill is not None)

    def expected_drain_s(self, fallback_ema):
        """Placement score: expected time for this replica's backlog (+ the
        incoming request) to clear at its measured service rate."""
        ema = self.ema_service_s if self.ema_service_s is not None else fallback_ema
        return (self.busy_slots() + 1) * ema / max(1, self.scheduler.num_slots)

    # ---------------------------------------------------------------- loop
    def step(self):
        """One scheduler iteration plus throughput accounting. Called ONLY
        from this replica's pump thread."""
        t0 = time.monotonic()
        delivered = self.scheduler.step()
        now = time.monotonic()
        self.tokens += delivered
        # inter-step host overhead counts, but an IDLE gap (pump parked
        # waiting for work) must not: a lull would fold a near-zero sample
        # into the EWMA and understate a lightly-loaded replica
        prev = self._last_step_end
        start = prev if (prev is not None and t0 - prev < 1.0) else t0
        dt = now - start
        self._last_step_end = now
        if dt > 0:
            inst = delivered / dt
            self.tok_s = inst if self.tok_s == 0.0 else 0.9 * self.tok_s + 0.1 * inst
        tel = self.telemetry
        if tel.enabled:
            tel.gauges([
                (f"serving/replica/{self.idx}/slot_occupancy",
                 self.scheduler.cache.occupancy(), None),
                (f"serving/replica/{self.idx}/queue_depth",
                 float(len(self.scheduler.queue)), None),
                (f"serving/replica/{self.idx}/tok_s", self.tok_s, None)])
            if delivered:
                tel.counter(f"serving/replica/{self.idx}/tokens", delivered)
        return delivered

    def observe_service(self, service_s):
        """Fold one naturally-completed request's wall time into the
        placement EMA (same exclusion rule as the gateway's Retry-After EMA:
        cancelled/failed requests don't count)."""
        self.ema_service_s = (service_s if self.ema_service_s is None
                              else 0.9 * self.ema_service_s + 0.1 * service_s)

    def state(self):
        if self.retired:
            # the KV pool is released: report the terminal record without
            # touching pool-backed stats
            return {"idx": self.idx, "status": "retired", "error": None,
                    "phase_role": self.phase_role,
                    "dispatched": self.dispatched, "tokens": self.tokens}
        s = self.scheduler
        return {
            "idx": self.idx,
            "status": ("sick" if self.sick else
                       "pending_drain" if self.pending_drain else
                       "draining" if self.draining else "active"),
            "error": self.sick_error,
            # disaggregated serving: this replica's phase role and how many
            # requests it has handed off / adopted (the gateway's
            # /v1/replicas + /v1/metrics surface)
            "phase_role": self.phase_role,
            "migrations_out": s.migrations_out,
            "migrations_in": s.migrations_in,
            "num_slots": s.num_slots,
            "active_slots": s.cache.active_slots,
            "cached_slots": s.cache.cached_slots,
            "queue_depth": len(s.queue),
            "slot_occupancy": round(s.cache.occupancy(), 4),
            "dispatched": self.dispatched,
            "tokens": self.tokens,
            "tok_s": round(self.tok_s, 2),
            # capacity accounting (telemetry/capacity.py): this replica's
            # own pump-thread host-gap totals and goodput — per-replica
            # because each pump fences and attributes independently
            "goodput_fraction": (round(s.capacity.goodput_fraction, 5)
                                 if s.capacity is not None else None),
            "host_gap_total_s": (round(s._gap.total_gap_s, 4)
                                 if s._gap is not None else None),
            "ema_service_s": self.ema_service_s,
            "tp_size": s.tp_size,
            "ep_size": s.ep_size,
            # cold-expert paging (MoE serving): the fleet-shared store —
            # a page hot-loaded through any replica is resident for all
            "expert_store": s.experts.stats() if s.experts is not None else None,
            "prefix_cache_hit_rate": (round(s.radix.hit_rate(), 4)
                                      if s.radix is not None else None),
            # hierarchical KV tier (fleet-global host store shared by every
            # replica): this replica's demote/restore counts plus the shared
            # store's residency — any replica can restore a prefix any
            # other computed (memory/kv_tier.py)
            "kv_tier": s.kv_tier.stats() if s.kv_tier is not None else None,
            # multi-LoRA: the fleet-shared paged adapter store (one object,
            # same numbers from every replica — an adapter loaded through
            # any replica is resident for all)
            "adapters": s.adapters.stats() if s.adapters is not None else None,
        }


class ReplicaSet:
    """N replicas behind one dispatch policy. Thread-safe: the gateway's
    pump threads race :meth:`dispatch`/:meth:`route` under the internal
    lock; each replica's ``step`` stays exclusive to its own pump."""

    def __init__(self, replicas, sticky_capacity=2048, roles=None,
                 migrate_min_tokens=0):
        if not replicas:
            raise ValueError("ReplicaSet needs at least one replica")
        self.replicas = list(replicas)
        self.telemetry = self.replicas[0].telemetry
        self._lock = threading.RLock()
        self._rr = 0  # round-robin tie break cursor
        # sticky prefix index: leading-chunk key -> replica idx (bounded LRU)
        self._sticky = collections.OrderedDict()
        self._sticky_capacity = int(sticky_capacity)
        chunk = self.primary.prefill_chunk
        self._sticky_chunk = chunk if chunk > 0 else 64
        # disaggregated prefill/decode: the fleet-wide handoff queue (pull
        # model — decode pumps claim READY records as they find capacity)
        # plus the migrate-time knobs. Hooks install lazily the first time
        # any replica takes a non-mixed role.
        self._migrations = collections.deque()
        self._mig_id = 0
        self.migrate_min_tokens = max(0, int(migrate_min_tokens))
        self.migrations_failed = 0
        self._pump_proxy = _FleetPump(self)
        self._hooks_installed = False
        self._warmup_pending = False
        if roles:
            for idx, role in enumerate(roles):
                if idx < len(self.replicas):
                    self.set_role(idx, role)
            # build time: no pump threads exist yet, so the constructor IS
            # the pump-owned context — warm the tier programs here, before
            # the gateway's recompile watch can arm
            self._run_pending_warmup(self.replicas[0])

    # ---------------------------------------------------------------- build
    @classmethod
    def build(cls, engine, n=None, **scheduler_overrides):
        """N replicas over ONE engine: replica 0 is the engine's singleton
        scheduler (so a single-replica gateway is byte-for-byte the
        pre-replica path), siblings clone its exact configuration and share
        its compiled-program cache — same shapes, same programs, zero new
        XLA compiles per added replica. ``n`` defaults to the engine's
        ``continuous_batching.replicas``; the ``disaggregation`` config
        section seeds per-replica phase roles (all-``mixed`` when absent —
        byte-identical to the pre-disaggregation fleet)."""
        from ..inference.scheduler import DecodeScheduler
        cb = engine._config.continuous_batching
        if n is None:
            n = int(getattr(cb, "replicas", 1) or 1)
        if n < 1:
            raise ValueError(f"replicas must be >= 1, got {n}")
        primary = engine.scheduler(**scheduler_overrides)
        scheds = [primary]
        for _ in range(1, n):
            scheds.append(DecodeScheduler(engine, compiled_cache=primary._compiled,
                                          **primary._init_kwargs))
        dg = getattr(cb, "disaggregation", None)
        roles = list(getattr(dg, "roles", []) or []) if (
            dg is not None and dg.enabled) else []
        mmt = int(getattr(dg, "migrate_min_tokens", 0) or 0) if (
            dg is not None and dg.enabled) else 0
        return cls([Replica(i, s) for i, s in enumerate(scheds)],
                   roles=roles, migrate_min_tokens=mmt)

    @property
    def primary(self):
        return self.replicas[0].scheduler

    def __len__(self):
        return len(self.replicas)

    def __iter__(self):
        return iter(self.replicas)

    # ---------------------------------------------------------------- fleet state
    def total_slots(self):
        """Slots across placement-eligible replicas (the gateway's
        Retry-After backlog math divides by this)."""
        return sum(r.scheduler.num_slots for r in self.replicas
                   if r.available()) or self.replicas[0].scheduler.num_slots

    def phase_slots(self, phase):
        """Available slots on one side of the phase split (``"prefill"`` /
        ``"decode"`` capability — mixed counts for both): the gateway's
        phase-aware Retry-After divides each side's backlog by its own
        capacity instead of the blended fleet total."""
        want = (Replica.prefill_capable if phase == "prefill"
                else Replica.decode_capable)
        return sum(r.scheduler.num_slots for r in self.replicas
                   if r.available() and want(r))

    def disaggregated(self):
        """Any non-mixed role among LIVE replicas (phase-aware paths switch
        on). A retired replica's stale role must not pin the fleet into
        phase-aware math after elastic scale-down removed the split."""
        return any(r.phase_role != "mixed" for r in self.replicas
                   if not r.retired)

    def any_capacity(self):
        """A fresh prompt can be placed right now: an available
        PREFILL-capable replica has a free slot (decode-only replicas are
        not placement targets — that is the disaggregation contract)."""
        return any(r.available() and r.has_capacity() and r.prefill_capable()
                   for r in self.replicas)

    def healthy(self):
        """Replicas that could serve (not sick, not retired) — retired
        slots are index placeholders, not failover capacity."""
        return [r for r in self.replicas if not r.sick and not r.retired]

    def all_sick(self):
        """No live replica left: every non-retired replica is sick (a
        retired slot must not read as a healthy survivor)."""
        return all(r.sick or r.retired for r in self.replicas)

    def compiled_program_count(self):
        """One shared program set — the fleet's compile count IS the
        primary's (the O(1)-in-replicas guard reads this)."""
        return self.primary.compiled_program_count()

    def states(self):
        return [r.state() for r in self.replicas]

    # ---------------------------------------------------------------- lifecycle
    def drain(self, idx):
        """Stop placing onto replica ``idx``; in-flight work finishes (its
        pump keeps stepping). Idempotent; resumable."""
        with self._lock:
            rep = self.replicas[idx]
            rep.draining = True
            self._purge_sticky(idx)
        tel = self.telemetry
        if tel.enabled:
            tel.counter("serving/replica_drains")
        return rep.state()

    def resume(self, idx):
        """Re-admit replica ``idx`` to placement (clears drain AND sick —
        resuming a sick replica is the operator asserting it recovered)."""
        with self._lock:
            rep = self.replicas[idx]
            rep.draining = False
            rep.sick = False
            rep.sick_error = None
        return rep.state()

    def mark_sick(self, idx, error):
        """Health-out replica ``idx`` (its step raised): no further
        placement, sticky entries purge so its prompt families re-home.
        Idempotent — re-marking an already-sick replica neither
        re-increments the health-out counter nor re-scans the sticky map
        (a persistently-raising backend would otherwise spin both)."""
        with self._lock:
            rep = self.replicas[idx]
            if rep.sick:
                return
            rep.sick = True
            rep.sick_error = str(error)[:500]
            self._purge_sticky(idx)
        tel = self.telemetry
        if tel.enabled:
            tel.counter("serving/replica_sick")

    def _purge_sticky(self, idx):
        for key in [k for k, v in self._sticky.items() if v == idx]:
            del self._sticky[key]

    # ---------------------------------------------------------------- elastic fleet
    # (serving/controller.py drives these through cooldown-guarded
    # transitions; the gateway owns pump-thread lifecycle)
    def add_replica(self, phase_role="mixed"):
        """Grow the fleet by one scheduler sharing the primary's weight
        tree AND compiled-program dict — same shapes, same programs, ZERO
        new XLA compiles (the O(1)-programs invariant the gateway's
        recompile watch guards), so scale-up warmup is just pool
        allocation. Reuses a retired replica's index when one exists
        (indices stay dense for /v1/replicas); otherwise appends. The
        caller owns starting a pump thread: ``on_replica_added`` fires
        with the new replica after it is routable."""
        from ..inference.scheduler import DecodeScheduler
        primary = self.primary
        sched = DecodeScheduler(primary.engine, compiled_cache=primary._compiled,
                                **primary._init_kwargs)
        if self._hooks_installed:
            # a disaggregated fleet's migrate hook consults CURRENT roles
            # per prefill completion, so installing it on a mixed newcomer
            # is inert until someone flips its role
            sched.migrate_hook = self._maybe_migrate
        with self._lock:
            slot = next((i for i, r in enumerate(self.replicas) if r.retired),
                        None)
            idx = slot if slot is not None else len(self.replicas)
            rep = Replica(idx, sched, phase_role=phase_role)
            if slot is None:
                self.replicas.append(rep)
            else:
                self.replicas[slot] = rep
        tel = self.telemetry
        if tel.enabled:
            tel.counter("serving/replica_added")
        cb = self.on_replica_added
        if cb is not None:
            cb(rep)
        return rep

    def begin_scale_down(self, idx):
        """Two-phase scale-down, phase 1 (any thread): mark replica ``idx``
        pending-drain — no further placement, EXCLUDED from every
        advertised-capacity surface immediately (a draining replica that
        still counted toward slots would understate Retry-After for the
        whole drain) — and purge its sticky entries so its prompt families
        re-home. Phase 2 (:meth:`finish_scale_down`) retires it from its
        own pump thread once idle. Replica 0 never scales down: it owns
        the shared compiled-program cache and the fleet-wide pump duties."""
        if idx == 0:
            raise ValueError("replica 0 cannot scale down (it owns the shared "
                             "compiled-program cache and the primary pump)")
        with self._lock:
            rep = self.replicas[idx]
            if rep.retired or rep.pending_drain:
                return rep.state()
            rep.pending_drain = True
            rep.draining = True
            self._purge_sticky(idx)
        tel = self.telemetry
        if tel.enabled:
            tel.counter("serving/replica_drains")
        return rep.state()

    def finish_scale_down(self, rep):
        """Two-phase scale-down, phase 2 (``rep``'s OWN pump thread, once
        its in-flight work finished): retire the replica and drop its KV
        pool tree — the device buffers backing its slots are the HBM the
        scale-down exists to reclaim. Returns True when the replica
        retired (its pump thread should exit)."""
        if not rep.pending_drain or rep.retired or not rep.idle():
            return False
        with self._lock:
            if rep.retired:
                return False
            rep.retired = True
        # the scheduler never steps again: releasing the pool frees the
        # dominant HBM cost of the replica (shared stores — prefix tier,
        # adapters, experts — are fleet-global and stay)
        rep.scheduler.cache.pool = None
        tel = self.telemetry
        if tel.enabled:
            tel.counter("serving/replica_retired")
            tel.gauge(f"serving/replica/{rep.idx}/slot_occupancy", 0.0)
        return True

    def active_count(self):
        """Fleet size as capacity planning sees it (retired slots are
        index placeholders, not replicas)."""
        return sum(1 for r in self.replicas if not r.retired)

    def park_out(self, rep, req):
        """Brownout preemption WITH resume: demote ``req``'s whole KV
        through the migration transport (PR 13's migrate-out path) and
        HOLD the parked record — decode pumps skip held records — until
        :meth:`release_parked` re-admits it when the brownout lifts. Must
        run on ``rep``'s own pump thread (migrate_out touches its pool).
        Returns the record, or None when the request isn't parkable (no
        transport, not decoding here, mid-prefill, already terminal)."""
        sched = rep.scheduler
        if sched.kv_tier is None or req.done or req.cancelled or req.migrating:
            return None
        if req.slot is None or sched.active.get(req.slot) is not req:
            return None
        if sched._prefill is not None and sched._prefill.req is req:
            return None
        with self._lock:
            self._mig_id += 1
            mig_id = self._mig_id
        ns = (sched.adapters.namespace(req.adapter_ref.uid)
              if req.adapter_ref is not None else ())
        key = tuple(ns) + (_MIG_SENTINEL, mig_id)
        record = _Migration(req, key, rep.idx, time.monotonic())
        record.version = int(sched.cache.weights_version)
        record.held = True

        def on_ready(entry):
            record.entry = entry
            record.ready = True
            cb = self.on_migration_ready
            if cb is not None:
                cb()
        record.kv_len = sched.migrate_out(req, key, on_ready)
        if req.handle is not None:
            req.handle._sched = self._pump_proxy
        with self._lock:
            self._migrations.append(record)
        tel = self.telemetry
        if tel.enabled:
            tel.counter("serving/parked")
        return record

    def release_parked(self):
        """Lift the brownout hold: every held record re-enters the normal
        pull rotation, so decode-capable pumps adopt and resume them
        bit-identically (sampling seeds fold absolute step indices; the
        KV rows moved byte-exact). Returns the number released."""
        released = 0
        with self._lock:
            for rec in self._migrations:
                if rec.held:
                    rec.held = False
                    released += 1
        if released:
            cb = self.on_migration_ready
            if cb is not None:
                cb()
        return released

    # ---------------------------------------------------------------- phase roles
    def set_role(self, idx, role):
        """Assign replica ``idx`` a phase role (config seeding and the
        gateway's ``POST /v1/replicas/<i>/role`` runtime override). A
        non-mixed role requires the migration transport (the hierarchical
        prefix store — ``continuous_batching.disaggregation.enabled``
        creates it; ``hierarchical_kv`` also provides it) and a fleet that
        keeps BOTH phases coverable; violating either reverts and raises."""
        if role not in _PHASE_ROLES:
            raise ValueError(f"phase_role must be one of {_PHASE_ROLES}, got {role!r}")
        rep = self.replicas[idx]
        if rep.retired:
            raise ValueError(f"replica {idx} is retired (scaled down); "
                             f"add_replica() reuses its index")
        if role != "mixed" and self.primary.kv_tier is None:
            raise ValueError(
                "phase roles need the hierarchical-KV prefix store as the "
                "migration transport: enable continuous_batching.disaggregation "
                "(or hierarchical_kv) so the fleet shares a GlobalPrefixStore")
        prev, rep.phase_role = rep.phase_role, role
        if not (any(r.prefill_capable() for r in self.replicas if not r.retired)
                and any(r.decode_capable() for r in self.replicas
                        if not r.retired)):
            rep.phase_role = prev
            raise ValueError(
                f"role {role!r} on replica {idx} would leave the fleet with no "
                f"{'prefill' if role == 'decode' else 'decode'}-capable replica "
                f"(roles: {[r.phase_role for r in self.replicas]})")
        if role == "decode":
            with self._lock:
                self._purge_sticky(idx)  # no fresh placements land here
        if role != "mixed" and not self._hooks_installed:
            try:
                self._install_migration_hooks()
            except Exception:
                rep.phase_role = prev  # docstring contract: revert AND raise
                raise
        return rep.state()

    def _install_migration_hooks(self):
        """First non-mixed role: every scheduler gets the migrate hook (it
        consults the CURRENT role at each prefill completion, so runtime
        role flips take effect immediately) and the tier-program warmup is
        FLAGGED for the primary's pump — set_role may run on the gateway's
        admin (event-loop) thread, and warming inline there would race the
        pump's concurrent pool updates. The pump executes it at its next
        ``admit_migrations`` turn, which both pump loops run BEFORE any
        step that could migrate."""
        if self.primary.prefill_chunk <= 0:
            raise ValueError("disaggregated serving requires chunked prefill "
                             "(prefill_chunk > 0): migration hands off at "
                             "chunk-prefill completion")
        for rep in self.replicas:
            rep.scheduler.migrate_hook = self._maybe_migrate
        self._warmup_pending = True
        self._hooks_installed = True

    def _run_pending_warmup(self, rep):
        """Compile tier_slice/tier_restore into the SHARED program cache
        (one warmup serves every replica). Runs on a pump-owned turn — for
        build-time roles that is the constructor (no pumps yet); for a
        runtime role flip, the primary's next pump turn. A flip on a warm
        gateway may trip the recompile watch once — an expected compile,
        visible as exactly these two tier programs in the flight dump."""
        if self._warmup_pending and rep is self.replicas[0]:
            self._warmup_pending = False
            self.primary.kv_tier.warmup()

    # ---------------------------------------------------------------- migration
    def _maybe_migrate(self, sched, req):
        """The scheduler-side migrate hook: decide whether the request a
        prefill sync just finished should hand off to the decode side, and
        if so drive ``migrate_out``. Runs on the PREFILL replica's pump
        thread. Returns True when the request was taken."""
        rep = next((r for r in self.replicas if r.scheduler is sched), None)
        if rep is None or rep.phase_role != "prefill":
            return False  # mixed/decode replicas keep their decodes
        if req.prompt.size < self.migrate_min_tokens:
            return False  # colocate: the handoff isn't worth a short prompt
        with self._lock:
            target_exists = any(r.decode_capable() and r.available()
                                for r in self.replicas if r is not rep)
            if not target_exists:
                return False  # degraded fleet: colocate rather than stall
            self._mig_id += 1
            mig_id = self._mig_id
        ns = (sched.adapters.namespace(req.adapter_ref.uid)
              if req.adapter_ref is not None else ())
        key = tuple(ns) + (_MIG_SENTINEL, mig_id)
        record = _Migration(req, key, rep.idx, time.monotonic())
        record.version = int(sched.cache.weights_version)

        def on_ready(entry):
            # transfer-thread callback: the handoff entry is probe-visible
            # (or the fetch failed — entry None settles the request on the
            # next pull). Attribute stores are atomic; ready flips LAST.
            record.entry = entry
            record.ready = True
            cb = self.on_migration_ready
            if cb is not None:
                cb()
        record.kv_len = sched.migrate_out(req, key, on_ready)
        if req.handle is not None:
            # a parked request is owned by NO scheduler; result() must
            # drive the fleet until a decode replica adopts it
            req.handle._sched = self._pump_proxy
        with self._lock:
            self._migrations.append(record)
        tel = self.telemetry
        if tel.enabled:
            tel.counter("serving/migrations")
            tel.counter(f"serving/replica/{rep.idx}/migrations_out")
        return True

    # gateway wakeup for parked decode pumps (set by Gateway; None = polling
    # direct-drive callers)
    on_migration_ready = None
    # gateway hook: a freshly added replica needs a pump thread (set by
    # Gateway; None = direct-drive callers, whose pump_once covers it)
    on_replica_added = None

    def pending_migrations(self):
        return len(self._migrations)

    def admit_migrations(self, rep):
        """Let ``rep``'s pump claim parked handoffs (called from that pump's
        thread, once per turn): cancelled/failed records settle on ANY pump;
        ready records admit onto an available decode-capable replica — or
        onto ANY available replica when the decode side has vanished
        entirely (degraded colocation beats stalling the requests).
        Returns the number of records consumed."""
        self._run_pending_warmup(rep)  # runtime role flip: warm on the pump
        if not self._migrations:
            return 0
        sched = rep.scheduler
        consumed = 0
        while True:
            record = None
            settle = False
            with self._lock:
                no_decode_side = not any(r.decode_capable() and r.available()
                                         for r in self.replicas)
                can_admit = (rep.available() and not rep.sick
                             and (rep.decode_capable() or no_decode_side))
                for i, rec in enumerate(self._migrations):
                    # settle only READY records: a cancel racing the
                    # in-flight demote fetch must wait for the store put to
                    # land — settling early would discard nothing and the
                    # late-landing pinned entry would leak forever
                    if rec.ready and (rec.req.cancelled or rec.entry is None):
                        record, settle = rec, True
                        del self._migrations[i]
                        break
                    # held records (brownout parking) settle above but are
                    # never adopted until release_parked() lifts the hold
                    if (rec.ready and can_admit and not rec.req.cancelled
                            and not rec.held):
                        record = rec
                        del self._migrations[i]
                        break
                if record is None:
                    return consumed
            if settle:
                sched.admit_migration(record)  # settles without a slot
                if not record.req.cancelled:
                    self.migrations_failed += 1
                consumed += 1
                continue
            try:
                outcome = sched.admit_migration(record)
            except Exception:
                # the scheduler settled the request before re-raising;
                # account the fleet-level failure, then let the pump's
                # sick-replica handling see the error
                self.migrations_failed += 1
                raise
            if outcome == "resumed":
                consumed += 1
                rep.dispatched += 1
                tel = self.telemetry
                if tel.enabled:
                    tel.counter(f"serving/replica/{rep.idx}/migrations_in")
                    tel.counter("serving/migration_tokens", record.kv_len)
                    tel.histogram("serving/migration_ms",
                                  (time.monotonic() - record.t_start) * 1e3)
            elif outcome == "settled":
                self.migrations_failed += 1
                consumed += 1
            else:  # no free slot on this replica: park it again
                with self._lock:
                    self._migrations.appendleft(record)
                return consumed

    def _fail_handoffs(self):
        """No replica can ever adopt the parked handoffs (the whole fleet is
        sick/unavailable): settle them as failed instead of leaving their
        clients waiting on a queue nobody drains. In-flight demote fetches
        are joined first so their store entries land and can be discarded
        (a late-landing pinned entry would otherwise leak)."""
        for rep in self.replicas:
            tier = rep.scheduler.kv_tier
            if tier is not None:
                tier.executor.drain_fetches()
        with self._lock:
            records, self._migrations = list(self._migrations), collections.deque()
        for rec in records:
            # the primary's settle helper: shared store/adapter refs, and
            # the same cancel-vs-failure accounting as every other settle
            # site (a client cancel landing here is a cancel, not a failure)
            self.primary._settle_migration(
                rec, error="migration failed: no serving replica available")
            if not rec.req.cancelled:
                self.migrations_failed += 1
        return len(records)

    def inject_resume(self, desc, on_token=None, trace=None,
                      collect_logits=False):
        """Cross-process migration, decode side: rebuild the request a
        PREFILL WORKER handed off (its descriptor carries the prompt,
        sampling params, and where the KV is parked) and park it in this
        fleet's migration queue as a READY record whose entry points at the
        remote shard. ``admit_migrations`` then pulls it through the exact
        in-process adoption path — ``admit_migration`` restores the KV
        (the NetPrefixStore fetches the bytes from the owner over HTTP) and
        decode resumes bit-identically: the rebuilt request carries the
        original seed (sampling keys fold ABSOLUTE step indices), the
        already-decoded tokens, and the original budget rounding. Returns
        the request's :class:`~deepspeed_tpu.inference.scheduler.
        SchedulerHandle` (fleet-pumped until adoption). Raises ValueError
        on a descriptor this fleet cannot honor."""
        from ..inference.scheduler import (SchedulerHandle, _Request,
                                           _round_up)
        from ..memory.net_store import RemoteEntry
        if desc.get("adapter_id") is not None:
            raise ValueError("cross-process resume does not carry adapter "
                             "page pins; route adapter traffic to a worker "
                             "with the adapter resident instead")
        sched = self.primary
        if sched.kv_tier is None:
            raise ValueError("resume requires the hierarchical KV tier as "
                             "the migration transport (continuous_batching."
                             "disaggregation or hierarchical_kv)")
        with self._lock:
            self._mig_id += 1
            rid = -self._mig_id  # never collides with submit()'s own rids
        req = _Request(rid, np.asarray(desc["prompt"], np.int32),
                       int(desc["max_new_tokens"]), desc.get("eos_token_id"),
                       bool(desc.get("do_sample", False)),
                       float(desc.get("temperature", 1.0)),
                       int(desc.get("top_k", 0)),
                       float(desc.get("top_p", 1.0)),
                       int(desc.get("seed", 0)), bool(collect_logits),
                       sched.telemetry.now(), on_token=on_token, trace=trace)
        # tokens the prefill side's final fused sync already decoded (and
        # already streamed): part of the KV rows, and the absolute decode
        # step the sampling keys fold continues from len(out)
        req.out = [int(t) for t in desc.get("done_tokens", ())]
        if len(req.out) >= req.max_new_tokens:
            raise ValueError("resume descriptor is already complete")
        req.migrating = True
        # the same overshoot rounding submit() stamped on the original
        # request: admission sizes extent chains against it
        budget = _round_up(req.max_new_tokens, sched.steps_per_sync)
        if sched.spec_tokens > 0:
            budget = max(budget, req.max_new_tokens + sched._spec_width - 1)
        req.row_budget = int(budget)
        handle = SchedulerHandle(self._pump_proxy, req)
        req.handle = handle
        key = tuple(int(t) for t in desc["key"])
        entry = RemoteEntry(key, int(desc["kv_len"]), int(desc["version"]),
                            int(desc.get("nbytes", 0)), True,
                            desc["owner_url"], desc.get("owner_wid"))
        record = _Migration(req, key, None, time.monotonic())
        record.kv_len = int(desc["kv_len"])
        record.version = int(desc["version"])
        record.entry = entry
        record.ready = True
        with self._lock:
            self._migrations.append(record)
        tel = self.telemetry
        if tel.enabled:
            tel.counter("serving/migrations")
        cb = self.on_migration_ready
        if cb is not None:
            cb()
        return handle

    # ---------------------------------------------------------------- dispatch
    def _sticky_key(self, prompt, adapter=None):
        # the adapter id is part of the prefix identity: a prefix cached
        # under adapter A on replica 0 is COLD data for adapter B (the
        # radix roots are per-adapter), so sticky routing must not send
        # B's matching prompt there expecting a hit
        p = np.asarray(prompt, np.int32).reshape(-1)
        return (adapter, p[:self._sticky_chunk].tobytes())

    def route(self, prompt, adapter=None):
        """The replica to place ``prompt`` on, or None when no eligible
        replica has a free slot. Sticky first, least-loaded otherwise; the
        sticky index re-points to wherever placement actually lands, so the
        NEXT matching prompt follows the freshest cached copy. ``adapter``
        scopes stickiness per model variant (multi-LoRA serving)."""
        with self._lock:
            candidates = [r for r in self.replicas
                          if r.available() and r.has_capacity()
                          and r.prefill_capable()]
            if not candidates:
                return None
            key = self._sticky_key(prompt, adapter)
            hit = self._sticky.get(key)
            tel = self.telemetry
            if hit is not None:
                rep = self.replicas[hit]
                if rep.available() and rep.has_capacity() and rep.prefill_capable():
                    self._sticky.move_to_end(key)
                    if tel.enabled:
                        tel.counter("serving/dispatch/sticky")
                    return rep
                if not rep.available() or not rep.prefill_capable():
                    del self._sticky[key]  # sick/draining/decode-role owner: re-home
            known = [r.ema_service_s for r in candidates
                     if r.ema_service_s is not None]
            fallback = (sum(known) / len(known)) if known else 1.0
            n = len(self.replicas)
            rep = min(candidates,
                      key=lambda r: (r.expected_drain_s(fallback),
                                     (r.idx - self._rr) % n))
            self._rr = (rep.idx + 1) % n
            self._record_sticky(key, rep.idx)
            if tel.enabled:
                tel.counter("serving/dispatch/least_loaded")
            return rep

    def _record_sticky(self, key, idx):
        self._sticky[key] = idx
        self._sticky.move_to_end(key)
        while len(self._sticky) > self._sticky_capacity:
            self._sticky.popitem(last=False)

    def dispatch(self, prompt, **submit_kwargs):
        """Route + submit in one step: returns ``(replica, handle)`` or
        ``(None, None)`` when the fleet has no free slot. The direct-drive
        entry point for benches/tests; the gateway calls :meth:`route` and
        submits itself (it owns request bookkeeping)."""
        rep = self.route(prompt, adapter=submit_kwargs.get("adapter_id"))
        if rep is None:
            return None, None
        handle = rep.scheduler.submit(prompt, **submit_kwargs)
        self.note_dispatch(rep)
        return rep, handle

    def note_dispatch(self, rep):
        """Account one placement on ``rep`` (called after a successful
        submit so failed validation doesn't skew the counters)."""
        rep.dispatched += 1
        tel = self.telemetry
        if tel.enabled:
            tel.counter(f"serving/replica/{rep.idx}/dispatched")

    # ---------------------------------------------------------------- drive (testing/bench)
    def pump_once(self):
        """One single-threaded fleet turn: let every replica claim parked
        handoffs, then step the non-idle ones. Returns whether anything
        progressed (the gateway's per-replica pump threads do the same two
        calls per turn, one replica each)."""
        progressed = False
        for rep in self.replicas:
            if rep.retired:
                continue
            if self.admit_migrations(rep):
                progressed = True
            if not rep.idle() and not rep.sick:
                rep.step()
                progressed = True
            elif rep.pending_drain and self.finish_scale_down(rep):
                progressed = True
        return progressed

    def drain_all_work(self):
        """Single-threaded convenience pump: step every replica (and place
        parked migrations) until the whole fleet is idle (benches and
        tests; the gateway runs one pump thread per replica instead)."""
        while True:
            if self.pump_once():
                continue
            if not self._migrations:
                return
            # handoffs pending but nothing progressed: either their
            # device->host fetch is still in flight (join it — ready flips
            # and the next turn places them) or no replica can ever take
            # them (fail rather than spin)
            if any(not rec.ready for rec in list(self._migrations)):
                for rep in self.replicas:
                    tier = rep.scheduler.kv_tier
                    if tier is not None:
                        tier.executor.drain_fetches()
                continue
            if all(rec.held for rec in list(self._migrations)):
                # only brownout-parked records remain and this is a
                # direct-drive pump with no controller to lift the hold:
                # release rather than spin (the gateway path releases
                # explicitly on de-escalation and on begin_drain)
                self.release_parked()
                continue
            if not any(r.available() for r in self.replicas):
                self._fail_handoffs()
                continue
