"""Multi-host serving: the router tier + the per-process worker agent.

Pod-count scale-out for the serving stack (ROADMAP "Multi-host serving"):
every serving PR so far scaled within ONE process — the ReplicaSet shares a
weight tree by reference and the GlobalPrefixStore is an in-process object.
This module crosses the process boundary with two pieces, stdlib-only like
the gateway:

- :class:`WorkerAgent` rides inside each ``python -m deepspeed_tpu.serving
  --worker`` process (its own mesh/engine/DecodeScheduler fleet behind its
  own :class:`~deepspeed_tpu.serving.gateway.Gateway`): registers with the
  router, heartbeats capacity signals (the gateway's
  ``capacity_signals()`` dict — the SAME shape the local Retry-After
  reads), swaps every scheduler's KV-tier store for a
  :class:`~deepspeed_tpu.memory.net_store.NetPrefixStore` shard, and (on
  ``--worker-role prefill``) installs the cross-process migrate hook: a
  finished chunked prefill demotes the request's whole KV into the shard,
  the gateway answers the router with a terminal ``handoff`` descriptor,
  and a decode worker resumes it bit-identically.

- :class:`Router` fronts the worker fleet over plain HTTP: ``POST
  /v1/completions`` places each request with the SAME signals the
  in-process ReplicaSet uses — sticky prefix (leading-chunk LRU), phase
  role, adapter residency, least-loaded ``(busy + 1) x service-EMA /
  slots`` from heartbeats — then proxies the stream. A worker dying
  mid-request sheds (retry on another worker when no bytes were relayed,
  honest truncation after) instead of sinking the fleet; fleet-wide
  Retry-After merges per-worker signals through
  ``serving/capacity_math.py`` so the router can never double-count a
  draining worker's backlog. The router also hosts the store DIRECTORY
  (``/v1/store/*``) — metadata only; KV bytes move worker-to-worker.

Worker protocol (all JSON over HTTP/1.1, ``Connection: close``):

    POST /v1/workers/register   {wid, url, role, weights_version, ...}
    POST /v1/workers/heartbeat  {wid, signals, store, weights_version}
         -> 404 when unknown (restarted router): worker re-registers
    POST /v1/workers/deregister {wid}
    GET  /v1/workers            fleet state (placement signals included)

Telemetry: counters ``serving/router/{requests,routed_local,routed_remote,
worker_sick,shed_503,handoff_resumes,retries}``; per-worker labeled
families ``serving/worker/<wid>/...`` on the Prometheus surface (256-label
cardinality cap, like tenants); ``serving/router/store_net_bytes_{in,out}``
and ``serving/router/remote_restore_ms`` are emitted worker-side by the
NetPrefixStore (the bytes move between workers, not through the router).
"""

import asyncio
import collections
import json
import threading
import time
import urllib.parse
import zlib

import numpy as np

from ..memory.net_store import DirectoryClient, NetPrefixStore, StoreDirectory
from ..utils.logging import logger
from . import capacity_math
from .replica import _MIG_SENTINEL, _Migration

_JSON = "application/json"


# ---------------------------------------------------------------------- worker


class WorkerAgent:
    """The in-process glue between one worker's Gateway and the router.

    ``attach()`` wires the store facade + migrate hook; ``start()`` spawns
    the registration/heartbeat daemon; ``stop()`` deregisters. The agent
    never owns scheduler state — every scheduler interaction happens on
    hooks the pump threads already run."""

    def __init__(self, gateway, router_url, wid, role="mixed",
                 heartbeat_s=2.0, lease_s=30.0, advertise_host=None,
                 migrate_min_tokens=0):
        if role not in ("prefill", "decode", "mixed"):
            raise ValueError(f"worker role must be prefill|decode|mixed, "
                             f"got {role!r}")
        self.gateway = gateway
        self.router_url = router_url.rstrip("/")
        self.wid = wid
        self.role = role
        self.heartbeat_s = float(heartbeat_s)
        self.lease_s = float(lease_s)
        self.migrate_min_tokens = max(0, int(migrate_min_tokens))
        host = advertise_host or gateway.host or "127.0.0.1"
        if host == "0.0.0.0":  # noqa: S104 — advertised URL must be routable
            host = "127.0.0.1"
        self.url = f"http://{host}:{gateway.port}"
        # stable per-worker key tag: handoff keys must be unique FLEET-wide,
        # and two workers' counters both start at 1
        self._wid_tag = int(zlib.crc32(str(wid).encode()) & 0x7FFFFFFF)
        self._mig_lock = threading.Lock()
        self._mig_id = 0
        self.directory = DirectoryClient(self.router_url)
        self.net_store = None
        self.registered = False
        self._stop = threading.Event()
        self._thread = None

    # ------------------------------------------------------------------ wiring
    def attach(self):
        """Swap every replica's KV-tier store for ONE shared NetPrefixStore
        shard (the local GlobalPrefixStore is already fleet-shared
        in-process; the facade adds the directory mirror + remote fetch)
        and install the cross-process migrate hook on prefill workers."""
        gw = self.gateway
        primary = gw.replicas.primary
        if primary.kv_tier is not None:
            local = primary.kv_tier.store
            self.net_store = NetPrefixStore(
                local, self.directory, self.wid, self.url,
                lease_s=self.lease_s, telemetry=gw.telemetry)
            for rep in gw.replicas:
                if rep.scheduler.kv_tier is not None:
                    rep.scheduler.kv_tier.store = self.net_store
            gw.net_store = self.net_store
        if self.role == "prefill":
            if primary.kv_tier is None:
                raise ValueError(
                    "a prefill-role worker needs the hierarchical-KV prefix "
                    "store as the migration transport: enable "
                    "continuous_batching.disaggregation (or hierarchical_kv)")
            if primary.prefill_chunk <= 0:
                raise ValueError("cross-process handoff requires chunked "
                                 "prefill (prefill_chunk > 0)")
            if gw.replicas._hooks_installed:
                # in-process disaggregation owns the hook: a fleet that is
                # ALSO phase-split internally migrates within the process
                # first; cross-process roles then belong on whole workers
                raise ValueError(
                    "worker role 'prefill' conflicts with in-process "
                    "disaggregation roles — use one phase split, not both")
            for rep in gw.replicas:
                rep.scheduler.migrate_hook = self._maybe_migrate_remote
        return self

    def _maybe_migrate_remote(self, sched, req):
        """Scheduler migrate hook, cross-process flavor (prefill pump
        thread, right after the final prefill sync delivered its tokens):
        demote the request's whole KV into this worker's shard and answer
        the router with a handoff descriptor instead of decoding here.
        Mirrors ``ReplicaSet._maybe_migrate``, but the adopter is another
        PROCESS found by the router, so there is no in-fleet record — the
        gateway request finishes with a terminal ``handoff`` event."""
        if req.migrating or sched.kv_tier is None:
            return False
        if req.prompt.size < self.migrate_min_tokens:
            return False  # colocate: the round trip isn't worth a short prompt
        with self._mig_lock:
            self._mig_id += 1
            mig_id = self._mig_id
        ns = (sched.adapters.namespace(req.adapter_ref.uid)
              if req.adapter_ref is not None else ())
        key = tuple(ns) + (_MIG_SENTINEL, self._wid_tag, mig_id)
        record = _Migration(req, key, None, time.monotonic())
        record.version = int(sched.cache.weights_version)
        gw = self.gateway

        def on_ready(entry):
            # KV transfer thread: the shard put landed (and the directory
            # registration with it) — or failed. Either way the request
            # must reach a terminal state; it is owned by no scheduler.
            record.entry = entry
            record.ready = True
            if entry is None:
                sched._settle_migration(
                    record, error="cross-process handoff demote failed")
            elif not gw._handoff_complete(req, self._desc(req, record)):
                # no gateway request owns it (direct-drive caller): nobody
                # will ever resume it — fail loudly, reclaim the entry
                sched._settle_migration(
                    record, error="cross-process handoff had no gateway "
                                  "request to answer")
            gw._wake.set()

        record.kv_len = sched.migrate_out(req, key, on_ready)
        tel = gw.telemetry
        if tel.enabled:
            tel.counter("serving/migrations")
        return True

    def _desc(self, req, record):
        """The handoff descriptor: everything a decode worker needs to
        rebuild the request bit-identically (sampling keys fold ABSOLUTE
        step indices, so seed + done-tokens + prompt pin the continuation)
        plus where the KV bytes are parked."""
        return {"key": list(record.key), "kv_len": int(record.kv_len),
                "version": int(record.version),
                "nbytes": int(record.entry.nbytes),
                "owner_url": self.url, "owner_wid": self.wid,
                "prompt": [int(t) for t in req.prompt],
                "done_tokens": [int(t) for t in req.out],
                "max_new_tokens": int(req.max_new_tokens),
                "eos_token_id": req.eos_token_id,
                "do_sample": bool(req.do_sample),
                "temperature": float(req.temperature),
                "top_k": int(req.top_k), "top_p": float(req.top_p),
                "seed": int(req.seed), "adapter_id": req.adapter_id}

    # ------------------------------------------------------------------ heartbeat
    def signals(self):
        """The gateway's capacity-signals dict, stamped with this worker's
        process-level role (the router zeroes the opposite phase's slots
        when merging — a prefill worker's pool serves no fleet decodes)."""
        sig = self.gateway.capacity_signals()
        sig["role"] = self.role
        return sig

    def _heartbeat_body(self):
        gw = self.gateway
        return {"wid": self.wid, "url": self.url, "role": self.role,
                "signals": self.signals(),
                "weights_version": int(gw.replicas.primary.cache.weights_version),
                "store": (self.net_store.stats()
                          if self.net_store is not None else None),
                "adapters": (sorted(gw.replicas.primary.adapters.registered())
                             if gw.replicas.primary.adapters is not None
                             else []),
                "draining": bool(gw.draining),
                "compiled_programs": int(
                    gw.replicas.primary.compiled_program_count()),
                "stats": {"active_requests": len(gw._active),
                          "completed": gw.stats["completed"],
                          "handoffs_out": gw.stats["handoffs_out"],
                          "resumed_in": gw.stats["resumed_in"]}}

    def _register_body(self):
        gw = self.gateway
        return {"wid": self.wid, "url": self.url, "role": self.role,
                "prefill_chunk": int(gw.replicas.primary.prefill_chunk),
                "weights_version": int(gw.replicas.primary.cache.weights_version),
                "signals": self.signals()}

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"worker-agent-{self.wid}")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self.registered:
            self.directory._try("/v1/workers/deregister", {"wid": self.wid})
            self.registered = False

    def _run(self):
        """Registration + heartbeat daemon: register (with retry — the
        router may come up after the workers), then heartbeat every
        ``heartbeat_s``; a 404 means the router restarted and forgot us —
        re-register, carrying on. Owner-side lease reaping rides the same
        cadence."""
        while not self._stop.is_set() and not self.gateway.draining:
            try:
                if not self.registered:
                    out = self.directory._try("/v1/workers/register",
                                              self._register_body())
                    self.registered = out is not None and out.get("ok", False)
                else:
                    out = self.directory._try("/v1/workers/heartbeat",
                                              self._heartbeat_body())
                    if out is not None and out.get("unknown"):
                        self.registered = False
                        continue  # re-register immediately
                if self.net_store is not None:
                    self.net_store.reap_expired()
            except Exception:  # noqa: BLE001 — the daemon must survive blips
                logger.warning("worker agent heartbeat failed", exc_info=True)
            self._stop.wait(self.heartbeat_s)
        if self.registered:
            self.directory._try("/v1/workers/deregister", {"wid": self.wid})
            self.registered = False


# ---------------------------------------------------------------------- router


class _Worker:
    """Router-side view of one registered worker process."""

    __slots__ = ("wid", "url", "host", "port", "role", "prefill_chunk",
                 "weights_version", "signals", "store", "adapters",
                 "draining", "compiled_programs", "stats", "last_seen",
                 "sick", "sick_error", "routed")

    def __init__(self, wid, url, role, prefill_chunk, weights_version,
                 signals):
        self.wid = wid
        self.url = url.rstrip("/")
        parsed = urllib.parse.urlsplit(self.url)
        self.host = parsed.hostname
        self.port = parsed.port or 80
        self.role = role
        self.prefill_chunk = int(prefill_chunk or 64)
        self.weights_version = int(weights_version or 0)
        self.signals = dict(signals or {})
        self.store = None
        self.adapters = []
        self.draining = False
        self.compiled_programs = 0
        self.stats = {}
        self.last_seen = time.monotonic()
        self.sick = False
        self.sick_error = None
        self.routed = 0

    def prefill_capable(self):
        return self.role in ("prefill", "mixed")

    def decode_capable(self):
        return self.role in ("decode", "mixed")

    def available(self, now, timeout_s):
        return (not self.sick and not self.draining
                and (now - self.last_seen) <= timeout_s)

    def merged_signals(self):
        """Role-adjusted capacity signals for the fleet merge: a worker
        whose whole PROCESS is one phase contributes no slots to the other
        phase, whatever its local (all-mixed) fleet reports."""
        sig = dict(self.signals)
        if self.role == "prefill":
            sig["decode_slots"] = 0
        elif self.role == "decode":
            sig["prefill_slots"] = 0
        return sig

    def expected_drain_score(self, fallback_ema):
        """The ReplicaSet's least-loaded placement score, over the wire:
        ``(busy + 1) x service-EMA / slots`` from the last heartbeat."""
        sig = self.signals
        ema = sig.get("ema_service_s")
        ema = float(ema) if ema is not None else fallback_ema
        busy = (int(sig.get("queued", 0)) + int(sig.get("inflight", 0))
                + int(sig.get("sched_backlog", 0)))
        return (busy + 1) * ema / max(1, int(sig.get("total_slots", 1)))

    def state(self):
        return {"wid": self.wid, "url": self.url, "role": self.role,
                "status": "sick" if self.sick else
                          ("draining" if self.draining else "active"),
                "error": self.sick_error,
                "weights_version": self.weights_version,
                "signals": self.signals, "store": self.store,
                "adapters": self.adapters, "routed": self.routed,
                "compiled_programs": self.compiled_programs,
                "age_s": round(time.monotonic() - self.last_seen, 3),
                "stats": self.stats}


class Router:
    """The fleet frontend: placement + proxy + store directory (see module
    docstring). One asyncio event loop owns everything; worker I/O is
    per-request ``asyncio.open_connection`` (Connection: close both ways,
    matching the gateway's HTTP dialect)."""

    def __init__(self, host="127.0.0.1", port=0, heartbeat_timeout_s=10.0,
                 retry_after_cap_s=600, sticky_capacity=2048,
                 reap_interval_s=5.0, proxy_timeout_s=300.0):
        self.host = host
        self.port = None
        self._want_port = int(port)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.retry_after_cap_s = int(retry_after_cap_s)
        self.reap_interval_s = float(reap_interval_s)
        self.proxy_timeout_s = float(proxy_timeout_s)
        self.directory = StoreDirectory()
        self.workers = {}
        self._lock = threading.Lock()
        self._sticky = collections.OrderedDict()
        self._sticky_capacity = int(sticky_capacity)
        self._rr = 0
        self.counters = collections.Counter({
            "requests": 0, "routed_local": 0, "routed_remote": 0,
            "worker_sick": 0, "shed_503": 0, "handoff_resumes": 0,
            "retries": 0, "resume_failovers": 0})
        self._worker_labels = set()
        self._t0 = time.monotonic()
        self.ready = False
        self._loop = None
        self._server = None
        self._loop_thread = None
        self._done = threading.Event()

    # ------------------------------------------------------------------ lifecycle
    def start_background(self, timeout=60.0):
        started = threading.Event()

        def runner():
            asyncio.run(self._serve(started.set))

        self._loop_thread = threading.Thread(target=runner, daemon=True,
                                             name="router-loop")
        self._loop_thread.start()
        if not started.wait(timeout):
            raise RuntimeError("router failed to bind within timeout")
        return self

    def run(self, ready_cb=None):
        asyncio.run(self._serve(ready_cb or (lambda: None)))

    def close(self):
        loop = self._loop
        if loop is not None and loop.is_running():
            loop.call_soon_threadsafe(self._shutdown)
        self._done.wait(10.0)

    def _shutdown(self):
        if self._server is not None:
            self._server.close()
        for task in asyncio.all_tasks(self._loop):
            task.cancel()

    async def _serve(self, ready_cb):
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self._want_port,
            limit=1 << 20)
        self.port = self._server.sockets[0].getsockname()[1]
        self.ready = True
        reaper = asyncio.ensure_future(self._reaper())
        ready_cb()
        try:
            async with self._server:
                await self._server.serve_forever()
        except (asyncio.CancelledError, KeyboardInterrupt):
            pass
        finally:
            reaper.cancel()
            self.ready = False
            self._done.set()

    async def _reaper(self):
        """Periodic hygiene: expire handoff leases the owners never
        reclaimed (dead-owner case) and flag heartbeat-silent workers sick
        so placement stops choosing them before a proxy failure does."""
        while True:
            await asyncio.sleep(self.reap_interval_s)
            self.directory.reap()
            now = time.monotonic()
            with self._lock:
                for w in self.workers.values():
                    if (not w.sick
                            and now - w.last_seen > self.heartbeat_timeout_s):
                        self._mark_sick(w, "heartbeat timeout")

    def _mark_sick(self, worker, error):
        if worker.sick:
            return
        worker.sick = True
        worker.sick_error = str(error)[:300]
        self.counters["worker_sick"] += 1
        logger.warning(f"router: worker {worker.wid} marked sick ({error})")

    # ------------------------------------------------------------------ placement
    def _sticky_key(self, prompt, adapter):
        # caller holds self._lock (non-reentrant)
        chunk = 64
        for w in self.workers.values():
            chunk = w.prefill_chunk or chunk
            break
        return (adapter, tuple(prompt[:chunk]))

    def _record_sticky(self, key, wid):
        self._sticky[key] = wid
        self._sticky.move_to_end(key)
        while len(self._sticky) > self._sticky_capacity:
            self._sticky.popitem(last=False)

    def _place(self, prompt, adapter=None, phase="prefill", exclude=()):
        """Mirror of ``ReplicaSet.route`` over the wire: eligible workers
        (healthy, heartbeat-fresh, phase-capable, not excluded by an
        earlier failed attempt), sticky prefix first (same leading-chunk
        LRU), adapter residency preferred, else least-loaded by the
        expected-drain score with a round-robin tie break."""
        now = time.monotonic()
        want = (_Worker.prefill_capable if phase == "prefill"
                else _Worker.decode_capable)
        with self._lock:
            cands = [w for w in self.workers.values()
                     if w.available(now, self.heartbeat_timeout_s)
                     and want(w) and w.wid not in exclude]
            if not cands:
                # degraded fleet: any live worker beats stalling (the same
                # colocation fallback the in-process fleet takes when one
                # phase vanishes)
                cands = [w for w in self.workers.values()
                         if w.available(now, self.heartbeat_timeout_s)
                         and w.wid not in exclude]
            if not cands:
                return None
            skey = None
            if phase == "prefill" and prompt:
                skey = self._sticky_key(prompt, adapter)
                wid = self._sticky.get(skey)
                if wid is not None:
                    w = self.workers.get(wid)
                    if w is not None and w in cands:
                        self._sticky.move_to_end(skey)
                        w.routed += 1
                        return w
            if adapter is not None:
                resident = [w for w in cands if adapter in (w.adapters or ())]
                if resident:
                    cands = resident
            emas = [w.signals.get("ema_service_s") for w in cands]
            emas = [e for e in emas if e is not None]
            fallback = float(np.mean(emas)) if emas else 1.0
            order = sorted(
                cands, key=lambda w: (w.expected_drain_score(fallback),
                                      (hash(w.wid) - self._rr) % (len(cands) + 1)))
            self._rr += 1
            chosen = order[0]
            if skey is not None:
                self._record_sticky(skey, chosen.wid)
            chosen.routed += 1
            return chosen

    def _fleet_retry_after(self):
        with self._lock:
            now = time.monotonic()
            live = [w.merged_signals() for w in self.workers.values()
                    if w.available(now, self.heartbeat_timeout_s)]
        merged = capacity_math.merge_signals(live)
        return capacity_math.estimate_retry_after(merged,
                                                  self.retry_after_cap_s)

    # ------------------------------------------------------------------ HTTP layer
    async def _handle_conn(self, reader, writer):
        try:
            req_line = await asyncio.wait_for(reader.readline(), 30.0)
            parts = req_line.decode("latin-1").split()
            if len(parts) < 2:
                return
            method, path = parts[0].upper(), parts[1]
            headers = {}
            for _ in range(128):
                line = await asyncio.wait_for(reader.readline(), 30.0)
                if line in (b"\r\n", b"\n", b""):
                    break
                key, _, val = line.decode("latin-1").partition(":")
                headers[key.strip().lower()] = val.strip()
            else:
                await self._json(writer, 431,
                                 {"error": {"message": "too many headers"}})
                return
            body = b""
            length = int(headers.get("content-length", "0") or 0)
            if length > (64 << 20):
                await self._json(writer, 413,
                                 {"error": {"message": "body too large"}})
                return
            if length:
                body = await asyncio.wait_for(reader.readexactly(length), 60.0)
            await self._route(method, path, headers, body, reader, writer)
        except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                ConnectionError):
            pass
        except Exception:  # noqa: BLE001 — one bad conn must not kill the loop
            logger.exception("router: connection handler failed")
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass

    async def _route(self, method, path, headers, body, reader, writer):
        path, _, query = path.partition("?")
        if method == "GET" and path == "/healthz":
            await self._json(writer, 200, {"status": "alive"})
        elif method == "GET" and path == "/readyz":
            now = time.monotonic()
            with self._lock:
                live = sum(1 for w in self.workers.values()
                           if w.available(now, self.heartbeat_timeout_s))
            if live:
                await self._json(writer, 200, {"status": "ready",
                                               "workers": live})
            else:
                await self._json(
                    writer, 503, {"status": "no live workers"},
                    extra=[("Retry-After", str(self._fleet_retry_after()))])
        elif method == "POST" and path == "/v1/workers/register":
            await self._worker_register(body, writer)
        elif method == "POST" and path == "/v1/workers/heartbeat":
            await self._worker_heartbeat(body, writer)
        elif method == "POST" and path == "/v1/workers/deregister":
            req = self._parse_json(body)
            wid = (req or {}).get("wid")
            with self._lock:
                self.workers.pop(wid, None)
            self.directory.drop_worker(wid)
            await self._json(writer, 200, {"ok": True})
        elif method == "GET" and path == "/v1/workers":
            with self._lock:
                states = [w.state() for w in self.workers.values()]
            await self._json(writer, 200, {"workers": states})
        elif method == "GET" and path == "/v1/metrics":
            accept = headers.get("accept", "")
            want_prom = ("format=prometheus" in query
                         or (("text/plain" in accept or "openmetrics" in accept)
                             and _JSON not in accept))
            if want_prom:
                from ..telemetry import prometheus as prom
                text = prom.render(self._prom_snapshot(),
                                   extra_gauges=self._prom_extra()).encode()
                writer.write(self._head(
                    200, "text/plain; version=0.0.4; charset=utf-8",
                    length=len(text)) + text)
                await writer.drain()
            else:
                await self._json(writer, 200, self._metrics())
        elif method == "POST" and path == "/v1/store/register":
            req = self._parse_json(body)
            if req is None or "key" not in req:
                await self._json(writer, 400,
                                 {"error": {"message": "bad register body"}})
                return
            self.directory.register(
                req.get("wid"), req.get("url"), req["key"],
                req.get("length", len(req["key"])), req.get("version", 0),
                req.get("nbytes", 0), req.get("pinned", False),
                lease_s=req.get("lease_s"))
            await self._json(writer, 200, {"ok": True})
        elif method == "POST" and path == "/v1/store/unregister":
            req = self._parse_json(body)
            ok = self.directory.unregister((req or {}).get("key", ()))
            await self._json(writer, 200, {"ok": ok})
        elif method == "POST" and path == "/v1/store/probe":
            req = self._parse_json(body) or {}
            rec = self.directory.probe(req.get("key", ()),
                                       req.get("version", 0),
                                       exclude_wid=req.get("wid"))
            if rec is None:
                await self._json(writer, 200, {"found": False})
            else:
                rec = dict(rec, key=list(rec["key"]))
                rec.pop("expires_at", None)
                await self._json(writer, 200, {"found": True, "entry": rec})
        elif method == "POST" and path == "/v1/store/drop":
            req = self._parse_json(body) or {}
            n = self.directory.drop(wid=req.get("wid"),
                                    version=req.get("version"),
                                    prefix=req.get("prefix"))
            await self._json(writer, 200, {"dropped": n})
        elif method == "POST" and path == "/v1/completions":
            await self._completions(headers, body, reader, writer)
        else:
            await self._json(writer, 404,
                             {"error": {"message": f"no route {method} {path}"}})

    async def _worker_register(self, body, writer):
        req = self._parse_json(body)
        if not req or not req.get("wid") or not req.get("url"):
            await self._json(writer, 400,
                             {"error": {"message": "register needs wid+url"}})
            return
        wid = req["wid"]
        w = _Worker(wid, req["url"], req.get("role", "mixed"),
                    req.get("prefill_chunk", 64),
                    req.get("weights_version", 0), req.get("signals"))
        with self._lock:
            known = wid in self.workers
            self.workers[wid] = w
        if known:
            # a re-registering wid is a RESTARTED process: its old shard's
            # rows are gone, so its directory records are garbage
            self.directory.drop_worker(wid)
            with self._lock:
                stale = [k for k, v in self._sticky.items() if v == wid]
                for k in stale:
                    del self._sticky[k]
        logger.info(f"router: worker {wid} registered ({w.role}) at {w.url}")
        await self._json(writer, 200, {"ok": True,
                                       "heartbeat_timeout_s":
                                           self.heartbeat_timeout_s})

    async def _worker_heartbeat(self, body, writer):
        req = self._parse_json(body) or {}
        wid = req.get("wid")
        with self._lock:
            w = self.workers.get(wid)
            if w is not None:
                w.last_seen = time.monotonic()
                w.sick = False
                w.sick_error = None
                w.signals = dict(req.get("signals") or w.signals)
                w.role = req.get("role", w.role)
                w.store = req.get("store", w.store)
                w.adapters = req.get("adapters", w.adapters)
                w.draining = bool(req.get("draining", False))
                w.weights_version = int(req.get("weights_version",
                                                w.weights_version))
                w.compiled_programs = int(req.get("compiled_programs",
                                                  w.compiled_programs))
                w.stats = req.get("stats", w.stats)
        if w is None:
            await self._json(writer, 200, {"unknown": True})
        else:
            await self._json(writer, 200, {"ok": True})

    # ------------------------------------------------------------------ proxying
    async def _completions(self, headers, body, reader, writer):
        self.counters["requests"] += 1
        try:
            req = json.loads(body.decode("utf-8") or "{}")
            if not isinstance(req, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, UnicodeDecodeError) as e:
            await self._json(writer, 400, {"error": {"message": str(e)}})
            return
        prompt = req.get("prompt") or []
        if isinstance(prompt, str):
            try:
                prompt = [int(t) for t in prompt.split()]
            except ValueError:
                prompt = []
        stream = bool(req.get("stream", False))
        adapter = req.get("adapter_id")
        tried = set()
        while True:
            worker = self._place(prompt, adapter=adapter, phase="prefill",
                                 exclude=tried)
            if worker is None:
                self.counters["shed_503"] += 1
                await self._json(
                    writer, 503,
                    {"error": {"message": "no live worker can serve the "
                               "request", "type": "unavailable"}},
                    extra=[("Retry-After", str(self._fleet_retry_after()))])
                return
            self._count_locality(worker)
            outcome = await self._proxy(worker, headers, body, req, stream,
                                        writer)
            if outcome == "retry":
                # shed-and-retry: the worker died before ANY byte reached
                # the client, so another worker can serve transparently
                tried.add(worker.wid)
                self.counters["retries"] += 1
                continue
            return

    def _count_locality(self, worker):
        local = worker.host in ("127.0.0.1", "localhost", self.host)
        self.counters["routed_local" if local else "routed_remote"] += 1

    def _forward_headers(self, headers, body_len):
        out = [("Content-Length", str(body_len)),
               ("Content-Type", _JSON), ("Connection", "close")]
        for h in ("x-tenant", "x-priority", "x-request-id", "traceparent"):
            if h in headers:
                out.append((h, headers[h]))
        return out

    async def _open_worker(self, worker, body_bytes, headers):
        """One POST /v1/completions to a worker; returns (reader, writer,
        status, resp_headers) or None on connect/greeting failure (the
        caller marks the worker sick and retries elsewhere)."""
        try:
            wr_reader, wr_writer = await asyncio.wait_for(
                asyncio.open_connection(worker.host, worker.port,
                                        limit=1 << 20), 10.0)
        except (OSError, asyncio.TimeoutError):
            return None
        try:
            head = [f"POST /v1/completions HTTP/1.1",
                    f"Host: {worker.host}:{worker.port}"]
            for k, v in self._forward_headers(headers, len(body_bytes)):
                head.append(f"{k}: {v}")
            wr_writer.write(("\r\n".join(head) + "\r\n\r\n").encode()
                            + body_bytes)
            await wr_writer.drain()
            status_line = await asyncio.wait_for(wr_reader.readline(),
                                                 self.proxy_timeout_s)
            parts = status_line.decode("latin-1").split()
            if len(parts) < 2:
                raise ConnectionError("empty response")
            status = int(parts[1])
            resp_headers = {}
            for _ in range(128):
                line = await asyncio.wait_for(wr_reader.readline(), 30.0)
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode("latin-1").partition(":")
                resp_headers[k.strip().lower()] = v.strip()
            return wr_reader, wr_writer, status, resp_headers
        except (OSError, ValueError, asyncio.TimeoutError, ConnectionError):
            wr_writer.close()
            return None

    async def _proxy(self, worker, headers, body, req, stream, writer):
        """Proxy one request to ``worker``; returns "retry" when it failed
        before any client byte (safe to re-place) or "done". Handoff
        stitching happens here: the prefill worker's terminal handoff
        event/field is CONSUMED (never relayed) and the decode worker's
        resumed response is stitched on, so the client sees ONE stream."""
        opened = await self._open_worker(worker, body, headers)
        if opened is None:
            self._mark_sick(worker, "connect/greeting failed")
            return "retry"
        wreader, wwriter, status, resp_headers = opened
        try:
            if stream and status == 200:
                return await self._relay_stream(worker, wreader, headers,
                                                req, writer)
            return await self._relay_unary(worker, wreader, status,
                                           resp_headers, headers, req, writer)
        finally:
            try:
                wwriter.close()
            except Exception:  # noqa: BLE001
                pass

    async def _read_body(self, wreader, resp_headers):
        length = resp_headers.get("content-length")
        if length is not None:
            return await asyncio.wait_for(
                wreader.readexactly(int(length)), self.proxy_timeout_s)
        return await asyncio.wait_for(wreader.read(64 << 20),
                                      self.proxy_timeout_s)

    async def _relay_unary(self, worker, wreader, status, resp_headers,
                           headers, req, writer):
        try:
            raw = await self._read_body(wreader, resp_headers)
        except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                ConnectionError):
            self._mark_sick(worker, "died mid-response")
            return "retry"
        try:
            doc = json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            doc = None
        if status == 200 and isinstance(doc, dict) and doc.get("handoff"):
            stitched = await self._resume_unary(doc, headers, req)
            if stitched is None:
                await self._json(writer, 502,
                                 {"error": {"message": "handoff resume "
                                            "failed on every decode worker"}})
                return "done"
            await self._json(writer, 200, stitched)
            return "done"
        # verbatim relay (any status): the worker's answer IS the answer
        writer.write(self._head(status, resp_headers.get("content-type",
                                                         _JSON),
                                length=len(raw)) + raw)
        await writer.drain()
        return "done"

    async def _resume_unary(self, doc, headers, req):
        """Resume a unary handoff on a decode worker and stitch the two
        partial responses into one client answer."""
        desc = doc["handoff"]
        resume_req = {"resume": desc, "stream": False,
                      "return_logits": bool(req.get("return_logits", False))}
        body = json.dumps(resume_req).encode()
        # the owner is NOT pre-excluded: with no decode-capable worker left,
        # resuming on the prefill owner (loopback restore from its own
        # shard) is the degraded-colocation fallback, same as in-process
        tried = set()
        while True:
            worker = self._place(desc.get("prompt", ()), phase="decode",
                                 exclude=tried)
            if worker is None:
                return None
            self.counters["handoff_resumes"] += 1
            opened = await self._open_worker(worker, body, headers)
            if opened is None:
                self._mark_sick(worker, "connect failed on resume")
                tried.add(worker.wid)
                self.counters["resume_failovers"] += 1
                continue
            wreader, wwriter, status, resp_headers = opened
            try:
                raw = await self._read_body(wreader, resp_headers)
            except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                    ConnectionError):
                self._mark_sick(worker, "died mid-resume")
                return None  # the handoff entry was consumed: cannot retry
            finally:
                try:
                    wwriter.close()
                except Exception:  # noqa: BLE001
                    pass
            try:
                part = json.loads(raw.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                return None
            if status != 200:
                return None
            return self._stitch_unary(doc, part)

    @staticmethod
    def _stitch_unary(first, second):
        c1 = first["choices"][0]
        c2 = second["choices"][0]
        toks = list(c1.get("token_ids", ())) + list(c2.get("token_ids", ()))
        out = dict(second)
        out["choices"] = [dict(c2, token_ids=toks,
                               text=" ".join(str(t) for t in toks))]
        usage = dict(second.get("usage", {}))
        usage["completion_tokens"] = len(toks)
        usage["total_tokens"] = usage.get("prompt_tokens", 0) + len(toks)
        out["usage"] = usage
        if "logits" in first or "logits" in second:
            out["logits"] = list(first.get("logits", ())) + \
                list(second.get("logits", ()))
        out.pop("handoff", None)
        return out

    async def _relay_stream(self, worker, wreader, headers, req, writer):
        """Relay an SSE stream, stitching across handoffs. Events are
        parsed (never blindly piped) so the handoff descriptor can be
        consumed and the first stream's [DONE] suppressed; everything else
        relays byte-faithfully re-serialized."""
        client_started = False
        current_worker = worker
        current_reader = wreader
        while True:
            handoff = None
            try:
                while True:
                    line = await asyncio.wait_for(current_reader.readline(),
                                                  self.proxy_timeout_s)
                    if not line:
                        # EOF without [DONE]: the worker died mid-stream
                        raise ConnectionError("stream ended early")
                    text = line.decode("utf-8", "replace").strip()
                    if not text:
                        continue
                    if not text.startswith("data:"):
                        continue
                    payload = text[5:].strip()
                    if payload == "[DONE]":
                        if not client_started:
                            writer.write(self._head(
                                200, "text/event-stream",
                                [("Cache-Control", "no-cache")]))
                            client_started = True
                        writer.write(b"data: [DONE]\n\n")
                        await writer.drain()
                        return "done"
                    try:
                        event = json.loads(payload)
                    except ValueError:
                        event = None
                    if isinstance(event, dict) and event.get("handoff"):
                        handoff = event["handoff"]
                        break  # consume, never relay; stitch below
                    if not client_started:
                        writer.write(self._head(
                            200, "text/event-stream",
                            [("Cache-Control", "no-cache")]))
                        client_started = True
                    writer.write(f"data: {payload}\n\n".encode())
                    await writer.drain()
            except (asyncio.TimeoutError, ConnectionError,
                    asyncio.IncompleteReadError):
                self._mark_sick(current_worker, "died mid-stream")
                if not client_started:
                    return "retry"
                # bytes already reached the client: shed honestly — a
                # truncated stream without [DONE], never a silent re-run
                # that could double tokens
                return "done"
            # ---- stitch: resume on a decode worker, relay ITS stream
            resume_req = {"resume": handoff, "stream": True,
                          "return_logits": bool(req.get("return_logits",
                                                        False))}
            body = json.dumps(resume_req).encode()
            tried = set()
            opened = None
            nxt = None
            while opened is None:
                nxt = self._place(handoff.get("prompt", ()), phase="decode",
                                  exclude=tried)
                if nxt is None:
                    break
                self.counters["handoff_resumes"] += 1
                opened = await self._open_worker(nxt, body, headers)
                if opened is None:
                    self._mark_sick(nxt, "connect failed on resume")
                    tried.add(nxt.wid)
                    self.counters["resume_failovers"] += 1
            if opened is None:
                if not client_started:
                    await self._json(writer, 502,
                                     {"error": {"message": "handoff resume "
                                                "failed: no decode worker"}})
                return "done"
            nreader, _, status, _ = opened
            if status != 200:
                if not client_started:
                    await self._json(writer, 502,
                                     {"error": {"message": f"resume worker "
                                                f"answered {status}"}})
                return "done"
            current_worker, current_reader = nxt, nreader
            # loop: relay the resumed stream (a second handoff would stitch
            # again, though decode workers never hand off)

    # ------------------------------------------------------------------ metrics
    def _metrics(self):
        with self._lock:
            states = [w.state() for w in self.workers.values()]
        return {"ready": self.ready,
                "router": dict(self.counters,
                               workers=len(states),
                               retry_after_s=self._fleet_retry_after(),
                               uptime_s=round(time.monotonic() - self._t0, 3)),
                "directory": self.directory.stats(),
                "workers": states}

    def _prom_snapshot(self):
        """A telemetry-sink-shaped snapshot (prometheus.render's input
        contract) hand-built from router state — the router runs no
        TelemetrySink of its own."""
        counters = {f"serving/router/{name}": {"count": int(n), "total": int(n)}
                    for name, n in self.counters.items()}
        return {"counters": counters, "gauges": {}, "histograms": {},
                "uptime_s": round(time.monotonic() - self._t0, 3)}

    def _prom_extra(self):
        now = time.monotonic()
        dstats = self.directory.stats()
        out = {"router/ready": 1.0 if self.ready else 0.0,
               "router/retry_after_s": float(self._fleet_retry_after()),
               "router/store_entries": float(dstats["entries"]),
               "router/store_handoffs": float(dstats["handoffs"]),
               "router/store_leases_expired": float(dstats["leases_expired"])}
        with self._lock:
            workers = list(self.workers.values())
        out["router/workers"] = float(len(workers))
        out["router/workers_live"] = float(
            sum(1 for w in workers
                if w.available(now, self.heartbeat_timeout_s)))
        for w in workers:
            # per-worker labeled families, behind the same 256-label
            # cardinality cap as tenants: wids are operator-controlled but
            # an autoscaled fleet churns them
            wid = w.wid
            if wid not in self._worker_labels:
                if len(self._worker_labels) < 256:
                    self._worker_labels.add(wid)
                else:
                    wid = "__other__"
            sig = w.signals
            out[f"serving/worker/{wid}/up"] = (
                1.0 if w.available(now, self.heartbeat_timeout_s) else 0.0)
            out[f"serving/worker/{wid}/inflight"] = float(
                sig.get("inflight", 0))
            out[f"serving/worker/{wid}/queued"] = float(sig.get("queued", 0))
            out[f"serving/worker/{wid}/total_slots"] = float(
                sig.get("total_slots", 0))
            out[f"serving/worker/{wid}/routed"] = float(w.routed)
            if sig.get("ema_service_s") is not None:
                out[f"serving/worker/{wid}/ema_service_s"] = float(
                    sig["ema_service_s"])
        return out

    # ------------------------------------------------------------------ HTTP writing
    _REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
                413: "Content Too Large", 429: "Too Many Requests",
                431: "Request Header Fields Too Large", 502: "Bad Gateway",
                503: "Service Unavailable", 500: "Internal Server Error"}

    @staticmethod
    def _parse_json(body):
        try:
            doc = json.loads(body.decode("utf-8") or "{}")
        except (ValueError, UnicodeDecodeError):
            return None
        return doc if isinstance(doc, dict) else None

    def _head(self, status, ctype, extra=(), length=None):
        lines = [f"HTTP/1.1 {status} {self._REASONS.get(status, 'Unknown')}",
                 f"Content-Type: {ctype}", "Connection: close"]
        if length is not None:
            lines.append(f"Content-Length: {length}")
        for key, val in extra:
            lines.append(f"{key}: {val}")
        return ("\r\n".join(lines) + "\r\n\r\n").encode()

    async def _json(self, writer, status, obj, extra=()):
        body = json.dumps(obj).encode()
        writer.write(self._head(status, _JSON, extra, length=len(body)) + body)
        await writer.drain()
