"""Unified telemetry subsystem (structured spans, gauges, counters,
windowed histograms with JSONL + Perfetto/Chrome-trace export), plus the
production observability layer on top of it: request-scoped tracing
(``tracing``), the SLO burn-rate engine (``slo``), the anomaly flight
recorder (``flight_recorder``), Prometheus text exposition
(``prometheus``), serving roofline/goodput/host-gap capacity accounting
(``capacity``), and on-demand XLA device profiling (``profiler``).

See ``benchmarks/OBSERVABILITY.md`` for the config keys, the event schema,
and how to open the exported trace in Perfetto.
"""

from .sink import TelemetrySink, get_sink, set_sink  # noqa: F401
from .tracing import RequestTrace, extract_trace_context, make_trace_id  # noqa: F401
from .slo import DEFAULT_SERVING_OBJECTIVES, SLOEngine  # noqa: F401
from .flight_recorder import FlightRecorder  # noqa: F401
from .capacity import CapacityMeter, CapacityModel, HostGapTracker  # noqa: F401
from .profiler import ProfileBusy, XlaProfiler  # noqa: F401
