"""Unified telemetry subsystem (structured spans, gauges, counters,
histograms with JSONL + Perfetto/Chrome-trace export).

See ``benchmarks/OBSERVABILITY.md`` for the config keys, the event schema,
and how to open the exported trace in Perfetto.
"""

from .sink import TelemetrySink, get_sink, set_sink  # noqa: F401
