"""Serving capacity accounting: per-program roofline registry, sampled
fenced dispatch timing, host-gap attribution, and goodput.

Training has reported MFU since PR 1 (``runtime/engine``'s interval
gauges), but serving had no utilization accounting at all — an operator
could see tok/s fall without any way to tell *device is slow* apart from
*host is starving the device*. This module closes that gap with three
cooperating pieces, all owned by the scheduler's pump thread and all
no-ops when the telemetry sink is disabled:

- :class:`CapacityModel` — analytic FLOPs and HBM bytes per dispatched
  step program, derived from the model config and the dispatch's batch
  shape (live rows, per-row context, query columns, K substeps). The
  numbers count what the DEVICE executes (the full padded slot block),
  which is what makes the live MFU/bandwidth gauges roofline-honest and
  lets a test cross-check them against ``jit(...).lower().cost_analysis()``.

- :class:`CapacityMeter` — the per-compiled-program registry. Every
  program the scheduler builds (fused/spec/prefill/copy/tier_slice/
  tier_restore, LoRA variants included) registers here at warm/build
  time; a *sampled* fenced-timing window (every ``sample_every``-th sync,
  default 1/32 — the async dispatch pipeline is never fenced on the hot
  path) turns one dispatch's wall time into ``serving/mfu``,
  ``serving/hbm_bw_util``, and a per-program-kind roofline classification
  gauge (``serving/roofline/<kind>``: analytic arithmetic intensity over
  the machine balance — >= 1 means compute-bound, < 1 bandwidth-bound).
  Sampling uses only ``block_until_ready`` on arrays the program already
  produced, so it adds ZERO new XLA programs after warmup. The meter also
  owns goodput: useful vs wasted token-FLOPs (speculative rejected
  columns, MoE miss-replay dispatches, migration/restore traffic
  converted at the machine balance) rolled into the
  ``serving/goodput_fraction`` gauge.

- :class:`HostGapTracker` — device-idle attribution for the pump thread.
  The gap between one sync's fence and the next dispatch is pure host
  time; the scheduler stamps its admission / trie-probe / sampling-host /
  on_token-delivery / tier-transfer sections into the open gap and the
  tracker emits a ``serving/host_gap_ms`` histogram plus per-bucket
  ``serving/host_gap/<bucket>_ms`` counters whose sum equals the measured
  gap exactly (residue lands in ``other``; over-attribution from timer
  overlap is scaled back proportionally).

Everything here is stdlib + numpy on the host side; the only device
interaction is the sampled fence.
"""

import numpy as np

# host-gap attribution buckets, in emission order. "other" is the residue
# between the measured gap and the stamped sections — it absorbs pump-loop
# overhead, GIL waits, and anything not explicitly instrumented.
GAP_BUCKETS = ("admission", "trie_probe", "sampling_host", "on_token",
               "tier_transfer", "other")

_GATED_ACTS = ("swiglu", "geglu")


def _cfg(model_config, name, default=None):
    return getattr(model_config, name, default)


class CapacityModel:
    """Analytic FLOPs/HBM-bytes for one transformer step dispatch.

    All coefficients are precomputed from the model config at build so the
    per-sample cost is a handful of float multiplies. ``matmul_flops_per_col``
    counts every projection, the ACTIVE expert MLPs (``moe_top_k`` of
    ``num_experts``; dense models count one), and the LM head — per query
    column, full slot block (the program computes padded rows too).
    Attention score/value FLOPs scale with each live row's context and are
    added per dispatch."""

    __slots__ = ("matmul_flops_per_col", "attn_flops_per_ctx_tok",
                 "weight_read_bytes", "kv_bytes_per_token", "num_slots")

    def __init__(self, model_config, kv_bytes_per_token, num_slots,
                 tp_size=1, ep_size=1):
        h = int(_cfg(model_config, "hidden_size", 0) or 0)
        L = int(_cfg(model_config, "num_layers", 0) or 0)
        nh = int(_cfg(model_config, "num_heads", 1) or 1)
        kvh = int(_cfg(model_config, "kv_heads", nh) or nh)
        hd = int(_cfg(model_config, "head_size", max(1, h // max(1, nh))))
        ffn = int(_cfg(model_config, "ffn_size", 4 * h) or 4 * h)
        V = int(_cfg(model_config, "vocab_size", 0) or 0)
        E = int(_cfg(model_config, "num_experts", 0) or 0)
        topk = int(_cfg(model_config, "moe_top_k", 1) or 1)
        act = str(_cfg(model_config, "activation", "gelu"))
        mlp_mats = 3 if act in _GATED_ACTS else 2

        attn_proj = L * (h * hd * (nh + 2 * kvh)  # qkv
                         + nh * hd * h)           # o
        mlp_active = L * mlp_mats * h * ffn * (min(topk, E) if E > 0 else 1)
        mlp_total = L * mlp_mats * h * ffn * (E if E > 0 else 1)
        lm_head = h * V
        active_params = attn_proj + mlp_active + lm_head
        # 2 FLOPs per MAC; per query column the program runs every matmul
        self.matmul_flops_per_col = 2.0 * active_params
        # QK^T + AV: 2 matmuls x 2 FLOPs x (heads*head_dim) per context
        # token per query column, per layer
        self.attn_flops_per_ctx_tok = 4.0 * L * nh * hd
        # active weights read once per on-device step (the K-step loop
        # re-reads them each iteration); router/embeddings are noise
        if _cfg(model_config, "int8_weights", False):
            # int8 serving streams 1 byte/param plus the fp32 per-group
            # scales (4 bytes per group of `int8_group_size` params) —
            # without this the fused decode-block kind would report half
            # its real hbm_bw_util
            gs = int(_cfg(model_config, "int8_group_size", 0) or 128)
            dtype_bytes = 1.0 + 4.0 / max(1, gs)
        else:
            dtype_bytes = 2  # serving compute dtype is bf16 — the honest
            # upper bound for unknown dtypes too
            try:
                dtype_bytes = np.dtype(
                    np.asarray(0, _cfg(model_config, "dtype")).dtype).itemsize
            except Exception:  # noqa: BLE001 — unknown dtype: keep the bound
                pass
        self.weight_read_bytes = float((attn_proj + mlp_active + lm_head)
                                       * dtype_bytes)
        del mlp_total
        self.kv_bytes_per_token = float(kv_bytes_per_token)
        self.num_slots = int(num_slots)

    def dispatch_cost(self, live_ctx, width, ksteps, kv_mult=1.0):
        """(flops, hbm_bytes) for ONE step dispatch: ``width`` query columns
        over the full slot block plus ``ksteps - 1`` single-column substeps,
        with ``live_ctx`` the live rows' context lengths (attention + KV
        traffic scale with these). ``kv_mult`` scales the KV-read term for
        the multi-extent block walk — the extent kernel DMAs every extent's
        pool column per KV block, so its KV traffic is ``max_extents``× the
        contiguous walk even when most extents sit behind the mask."""
        ksteps = max(1, int(ksteps))
        cols_full = self.num_slots * (max(1, int(width)) + (ksteps - 1))
        ctx_sum = float(np.sum(live_ctx)) if len(live_ctx) else 0.0
        cols_per_row = max(1, int(width)) + (ksteps - 1)
        flops = (cols_full * self.matmul_flops_per_col
                 + cols_per_row * ctx_sum * self.attn_flops_per_ctx_tok)
        bytes_ = ksteps * (self.weight_read_bytes
                           + ctx_sum * self.kv_bytes_per_token
                           * max(1.0, float(kv_mult)))
        return flops, bytes_

    def flops_per_token(self, ctx):
        """Per useful token at context ``ctx`` — the goodput unit."""
        return (self.matmul_flops_per_col
                + float(ctx) * self.attn_flops_per_ctx_tok)


def program_shape(key):
    """(width, ksteps) batch shape encoded in a compiled-program cache key:
    fused/fused_block keys carry (chunk, ksteps), spec/spec_block keys
    carry the draft width (the verify program scores ``width`` columns in
    one pass); everything else (prefill/copy/tier ops) is shape-accounted
    as a single column. The ``*_block`` kinds are the fused decode-block
    retags — same tuple positions, priced separately in the roofline."""
    if (isinstance(key, tuple) and len(key) >= 5
            and key[0] in ("fused", "fused_block", "fused_ext",
                           "fused_seqp")):
        return int(key[3]), int(key[4])
    if (isinstance(key, tuple) and len(key) >= 4
            and key[0] in ("spec", "spec_block")):
        return int(key[3]), 1
    return 1, 1


def _program_kind(key):
    """Registry kind for a compiled-program cache key: the key's leading
    tag (``fused``/``spec``/``prefill``/``copy``/``tier_slice``/...),
    ``+lora`` suffixed for adapter variants."""
    if isinstance(key, tuple):
        kind = str(key[0])
        if key and key[-1] == "lora":
            kind += "+lora"
        return kind
    return str(key)


class CapacityMeter:
    """Per-compiled-program roofline registry + sampled fenced timing +
    goodput accounting. One instance per scheduler; only built when the
    sink is enabled (the disabled path allocates nothing)."""

    def __init__(self, sink, model, *, peak_flops, peak_hbm_bw, n_devices=1,
                 sample_every=32):
        self.sink = sink
        self.model = model
        self.peak_flops = float(peak_flops) * max(1, int(n_devices))
        self.peak_hbm_bw = float(peak_hbm_bw) * max(1, int(n_devices))
        # machine balance: FLOPs/byte at the roofline ridge point
        self.balance = self.peak_flops / max(1.0, self.peak_hbm_bw)
        self.sample_every = max(1, int(sample_every))
        self.programs = {}      # key -> {"kind", "samples", "mfu", "bw", ...}
        self._by_id = {}        # id(fn) -> key
        self.samples = 0
        # goodput accumulators (token-FLOPs)
        self.useful_flops = 0.0
        self.wasted_flops = 0.0

    # ---------------------------------------------------------------- registry
    def register(self, key, fn):
        """Idempotently register a compiled program under its cache key —
        called from the scheduler's program-cache lookup, so shared-cache
        replicas register the same fn once per scheduler at zero cost."""
        if id(fn) in self._by_id:
            return
        self._by_id[id(fn)] = key
        self.programs.setdefault(
            key, {"kind": _program_kind(key), "samples": 0,
                  "mfu": 0.0, "hbm_bw_util": 0.0, "intensity": 0.0})

    def key_for(self, fn):
        return self._by_id.get(id(fn))

    def should_sample(self, sync_seq):
        return sync_seq % self.sample_every == 0

    # ---------------------------------------------------------------- sampling
    def observe_dispatch(self, key, dur_s, live_ctx, width, ksteps,
                         kv_mult=1.0):
        """Fold one fenced dispatch sample into the live gauges. ``dur_s``
        is the fence-to-fence wall time of the dispatch alone."""
        if dur_s <= 0.0:
            return
        flops, bytes_ = self.model.dispatch_cost(live_ctx, width, ksteps,
                                                 kv_mult)
        mfu = flops / dur_s / self.peak_flops
        bw = bytes_ / dur_s / self.peak_hbm_bw
        intensity = flops / max(1.0, bytes_)
        self.samples += 1
        ent = self.programs.get(key)
        if ent is None:
            self.register(key, object())  # unkeyed dispatch: still account
            ent = self.programs[key]
        ent["samples"] += 1
        ent["mfu"] = mfu
        ent["hbm_bw_util"] = bw
        ent["intensity"] = intensity
        sink = self.sink
        if sink is not None and sink.enabled:
            sink.gauge("serving/mfu", mfu)
            sink.gauge("serving/hbm_bw_util", bw)
            # >= 1: compute-bound (intensity past the ridge); < 1: the
            # program is bandwidth-bound at this batch shape
            sink.gauge(f"serving/roofline/{ent['kind']}",
                       intensity / max(1e-9, self.balance))
            sink.counter("serving/capacity_samples")

    # ---------------------------------------------------------------- goodput
    def account(self, useful_tokens, wasted_tokens=0, ctx=0.0,
                wasted_bytes=0.0):
        """Fold one sync's goodput inputs: tokens delivered to requests,
        tokens computed-then-discarded (rejected speculative columns, MoE
        miss replays), and pure-traffic waste (migration demote/restore,
        evicted-then-recomputed prefixes) in bytes — converted to
        FLOP-equivalents at the machine balance so one fraction covers
        both compute and bandwidth waste."""
        ft = self.model.flops_per_token(ctx)
        self.useful_flops += max(0, useful_tokens) * ft
        wasted = max(0, wasted_tokens) * ft
        if wasted_bytes > 0.0:
            wasted += float(wasted_bytes) * self.balance
        self.wasted_flops += wasted
        sink = self.sink
        if sink is not None and sink.enabled:
            if wasted > 0.0:
                sink.counter("serving/goodput/wasted_token_flops", int(wasted))
            total = self.useful_flops + self.wasted_flops
            if total > 0.0:
                sink.gauge("serving/goodput_fraction",
                           self.useful_flops / total)

    @property
    def goodput_fraction(self):
        total = self.useful_flops + self.wasted_flops
        return self.useful_flops / total if total > 0.0 else 1.0

    # ---------------------------------------------------------------- snapshot
    def program_table(self):
        """Registry view for ``/v1/metrics`` extra surfaces / debugging:
        per-program kind, sample count, last MFU/bandwidth/roofline class."""
        out = {}
        for key, ent in self.programs.items():
            out[str(key)] = {
                "kind": ent["kind"], "samples": ent["samples"],
                "mfu": round(ent["mfu"], 5),
                "hbm_bw_util": round(ent["hbm_bw_util"], 5),
                "bound": ("compute" if ent["intensity"] >= self.balance
                          else "bandwidth"),
            }
        return out


class HostGapTracker:
    """Device-idle (host-gap) attribution for one pump thread.

    Lifecycle per sync: the scheduler calls :meth:`sync_end` when a
    dispatch's results are fenced on the host (the device goes idle),
    stamps host sections into the open gap via :meth:`add`, and calls
    :meth:`dispatch` the moment the next program is handed to the device —
    closing the gap, normalizing attribution so the per-bucket counters
    sum EXACTLY to the measured gap, and emitting the histogram. All
    methods are single-float arithmetic; the tracker is only constructed
    when the sink is enabled."""

    __slots__ = ("sink", "_open_ts", "_acc", "gaps", "total_gap_s")

    def __init__(self, sink):
        self.sink = sink
        self._open_ts = None
        self._acc = {b: 0.0 for b in GAP_BUCKETS if b != "other"}
        self.gaps = 0
        self.total_gap_s = 0.0

    def sync_end(self, ts):
        """Device results just landed on the host: the idle gap opens."""
        self._open_ts = ts

    def add(self, bucket, dur, steal_from=None):
        """Stamp ``dur`` seconds of host work into ``bucket``.
        ``steal_from`` moves the time out of an ENCLOSING section (e.g. the
        trie probe runs inside the admission region) so nested timers never
        double-count. The debit may land before the enclosing section is
        stamped — the accumulator is allowed to go negative and is floored
        at :meth:`dispatch`, so stamp order doesn't matter."""
        if dur <= 0.0:
            return
        self._acc[bucket] += dur
        if steal_from is not None:
            self._acc[steal_from] -= dur

    def dispatch(self, ts):
        """The next program is being handed to the device: close the gap,
        emit, and reset. A dispatch before any sync (warmup) just clears
        the accumulators."""
        open_ts, self._open_ts = self._open_ts, None
        acc = self._acc
        if open_ts is None:
            for b in acc:
                acc[b] = 0.0
            return
        gap = max(0.0, ts - open_ts)
        for b in acc:  # floor deferred-steal debits (see :meth:`add`)
            if acc[b] < 0.0:
                acc[b] = 0.0
        attributed = sum(acc.values())
        if attributed > gap > 0.0:
            # timer overlap / clock skew: scale back so the invariant
            # "buckets sum to the measured gap" holds exactly
            scale = gap / attributed
            for b in acc:
                acc[b] *= scale
            attributed = gap
        other = max(0.0, gap - attributed)
        self.gaps += 1
        self.total_gap_s += gap
        sink = self.sink
        if sink is not None and sink.enabled:
            sink.histogram("serving/host_gap_ms", gap * 1e3)
            for b, v in acc.items():
                if v > 0.0:
                    sink.counter(f"serving/host_gap/{b}_ms", v * 1e3)
            if other > 0.0:
                sink.counter("serving/host_gap/other_ms", other * 1e3)
        for b in acc:
            acc[b] = 0.0
