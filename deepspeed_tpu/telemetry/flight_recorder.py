"""Anomaly flight recorder: a cheap always-on ring of recent events.

Production serving failures are diagnosed from what happened in the seconds
AROUND an anomaly — a deadline-expiry storm, an unexpected XLA recompile, a
backend error, an SLO burn-rate trip — but the JSONL stream is sampled
(histograms summarize at flush) and the Perfetto trace is capped. The
flight recorder keeps the last ``capacity`` events at FULL resolution
(every span/gauge/counter/histogram observation as a compact tuple) in a
bounded ring; a trigger snapshots the ring (the iterations *preceding* the
anomaly), keeps collecting for ``post_window_s`` (the iterations
*following* it), then writes one self-contained JSON dump under the sink's
output path.

Triggers (all route through :meth:`TelemetrySink.dump_flight`):

- SLO burn-rate alert (``telemetry/slo.py`` -> the gateway's alert hook)
- scheduler/backend step failure (``serving/gateway.py`` pump)
- unexpected XLA recompile after warmup (gateway pump watches
  ``DecodeScheduler.compiled_program_count()``)
- ``SIGUSR1`` (``python -m deepspeed_tpu.serving`` installs the handler)
- ``GET /v1/debug/flight`` (operator-forced dump)

Recording cost is one deque append per event — the ring only exists when
the sink is enabled, so the default-off hot path is untouched.
"""

import json
import os
from collections import deque


class FlightRecorder:
    """Bounded full-resolution event ring + dump lifecycle.

    Ring/pending mutation happens under the owning sink's lock (the sink
    calls :meth:`record`/:meth:`trigger`/:meth:`take_ready` from its
    producer paths); the file write (:meth:`write_dump`) takes only local
    state, so the sink runs it OUTSIDE the producer lock.
    """

    __slots__ = ("capacity", "post_window_s", "min_interval_s", "_ring",
                 "_pending", "_last_trigger_ts", "_seq", "dumps")

    def __init__(self, capacity=8192, post_window_s=0.25, min_interval_s=1.0):
        self.capacity = max(64, int(capacity))
        self.post_window_s = max(0.0, float(post_window_s))
        self.min_interval_s = max(0.0, float(min_interval_s))
        self._ring = deque(maxlen=self.capacity)
        self._pending = []        # dumps still collecting their post-window
        self._last_trigger_ts = None
        self._seq = 0
        self.dumps = []           # paths written this process

    def record(self, ts, kind, name, value, attrs=None, track=None):
        """One event into the ring (and into any dump still collecting its
        post-window). Compact list form keeps the ring cheap to append and
        the dump file grep-able."""
        if track is not None:
            attrs = dict(attrs or (), track=track)
        ev = [round(ts, 6), kind, name, value] + ([attrs] if attrs else [])
        self._ring.append(ev)
        for pending in self._pending:
            pending["events_after"].append(ev)

    def trigger(self, sink, reason, attrs=None):
        """Snapshot the ring now; the dump is finalized once the post-window
        elapses (:meth:`take_ready`, driven by the sink's flush path) or at
        sink close. Rate-limited: triggers inside ``min_interval_s`` of the
        previous one are dropped (an alert storm must not turn the recorder
        into a disk-filling anomaly of its own). Returns the dump path or
        None."""
        now = sink.now()
        if (self._last_trigger_ts is not None
                and now - self._last_trigger_ts < self.min_interval_s):
            return None
        self._last_trigger_ts = now
        self._seq += 1
        safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in str(reason))
        path = os.path.join(sink.output_path, f"flight_{self._seq:03d}_{safe}.json")
        self._pending.append({
            "reason": str(reason), "attrs": attrs or {},
            "trigger_ts": round(now, 6), "started_at": sink.started_at,
            "post_window_s": self.post_window_s, "path": path,
            "event_format": ["ts", "kind", "name", "value", "attrs?"],
            "events_before": list(self._ring), "events_after": [],
            "deadline": now + self.post_window_s,
        })
        return path

    def take_ready(self, now, force=False):
        """Pop dumps whose post-window has elapsed (all of them when
        ``force``, e.g. at sink close — a truncated post-window beats a lost
        dump). Call under the sink lock; pass the result to
        :meth:`write_dump` outside it."""
        if not self._pending:
            return []
        ready = [p for p in self._pending if force or now >= p["deadline"]]
        self._pending = [p for p in self._pending if p not in ready]
        return ready

    def write_dump(self, pending):
        """Write one dump document (atomic rename); safe outside any lock."""
        path = pending.pop("path")
        pending.pop("deadline", None)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(pending, f)
            os.replace(tmp, path)
            self.dumps.append(path)
        except OSError:  # a full disk must not take the serving process down
            pass
        return path
