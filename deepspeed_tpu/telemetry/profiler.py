"""On-demand XLA device profiling, duration-bounded and race-safe.

A burn-rate alert or a flight-recorder trip tells an operator *when*
something went wrong; a real device trace tells them *what the device was
doing*. This module wraps ``jax.profiler.start_trace``/``stop_trace`` in a
small manager so a capture can be requested safely from any thread:

- ``POST /v1/debug/profile`` (serving gateway) starts a capture of a
  bounded duration; a second request while one is in flight gets 409.
- The training engine polls :meth:`maybe_capture` at its report interval,
  so a capture requested mid-run (``engine.request_profile(...)``) starts
  at a step boundary instead of mid-dispatch.

Traces land next to the flight dumps (the sink's ``output_path``), one
directory per capture (``xla_trace_<seq>_<tag>/``), in the standard
XLA/TensorBoard layout (``plugins/profile/<run>/*.xplane.pb``). Stopping
is belt-and-braces: a daemon timer fires at the deadline AND
:meth:`poll` (called from the gateway pump / engine report path) stops an
overdue capture even if the timer thread was lost."""

import os
import threading
import time


class ProfileBusy(RuntimeError):
    """A capture is already in flight (HTTP surfaces map this to 409)."""


_MAX_DURATION_S = 120.0


class XlaProfiler:
    """Duration-bounded ``jax.profiler`` capture manager (one per process
    surface: the gateway and the training engine each own one, writing
    under the same telemetry output path)."""

    def __init__(self, output_path):
        self.output_path = output_path
        self._lock = threading.Lock()
        self._active = None      # {"dir", "deadline", "tag"} while capturing
        self._seq = 0
        self._pending = None     # requested duration awaiting a boundary
        self.captures = []       # directories of completed captures

    # ---------------------------------------------------------------- capture
    def start(self, duration_s=1.0, tag="ondemand"):
        """Begin a capture; returns the trace directory. Raises
        :class:`ProfileBusy` when one is already in flight."""
        duration_s = min(max(0.05, float(duration_s)), _MAX_DURATION_S)
        safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in str(tag))
        with self._lock:
            if self._active is not None:
                raise ProfileBusy(
                    f"a profile capture is already in flight "
                    f"({self._active['dir']})")
            self._seq += 1
            trace_dir = os.path.join(self.output_path,
                                     f"xla_trace_{self._seq:03d}_{safe}")
            os.makedirs(trace_dir, exist_ok=True)
            import jax
            jax.profiler.start_trace(trace_dir)
            self._active = {"dir": trace_dir, "tag": safe,
                            "deadline": time.monotonic() + duration_s}
        timer = threading.Timer(duration_s, self._stop_if_due, args=(True, ))
        timer.daemon = True
        timer.start()
        return trace_dir

    def _stop_if_due(self, force=False):
        with self._lock:
            active = self._active
            if active is None:
                return None
            if not force and time.monotonic() < active["deadline"]:
                return None
            self._active = None
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001 — a failed stop must not
                pass           # wedge the manager (capture dir stays partial)
            self.captures.append(active["dir"])
            return active["dir"]

    def poll(self):
        """Stop an overdue capture (cheap; call from pump/report loops).
        Returns the finished trace dir when this call stopped one."""
        if self._active is None:
            return None
        return self._stop_if_due(force=False)

    def stop(self):
        """Force-stop the in-flight capture (process shutdown)."""
        return self._stop_if_due(force=True)

    @property
    def active(self):
        a = self._active
        return dict(a) if a is not None else None

    # ------------------------------------------------------- training boundary
    def request(self, duration_s=1.0):
        """Ask for a capture at the next report boundary (training engine).
        Raises :class:`ProfileBusy` when one is in flight or pending."""
        with self._lock:
            if self._active is not None or self._pending is not None:
                raise ProfileBusy("a profile capture is already in flight "
                                  "or pending")
            self._pending = min(max(0.05, float(duration_s)), _MAX_DURATION_S)

    def maybe_capture(self, tag="report"):
        """Report-interval hook: start the pending capture, if any. Also
        stops an overdue one. Returns the trace dir when a capture began."""
        self.poll()
        with self._lock:
            pending, self._pending = self._pending, None
        if pending is None:
            return None
        return self.start(pending, tag=tag)


def trace_artifacts(trace_dir):
    """The device-trace artifact files under one capture directory (the
    ``.xplane.pb`` / ``.trace.json.gz`` files TensorBoard loads) — what
    the tests and the gateway response use to prove the capture is real."""
    out = []
    for root, _dirs, files in os.walk(trace_dir):
        for f in files:
            if f.endswith((".xplane.pb", ".trace.json.gz", ".trace.json")):
                out.append(os.path.join(root, f))
    return sorted(out)
