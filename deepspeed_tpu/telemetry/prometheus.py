"""Prometheus text exposition for the telemetry snapshot.

Renders :meth:`TelemetrySink.snapshot` (plus any extra scalar gauges the
gateway wants to expose) in the Prometheus text format (version 0.0.4), so
a standard scraper pointed at ``GET /v1/metrics`` with the usual
``Accept: text/plain`` header works with zero glue. Mapping:

- counters -> ``# TYPE ... counter`` with a ``_total`` suffix;
  ``gateway/tenant/<t>/tokens``, ``comm/<op>/<group>/bytes``, and
  ``serving/replica/<id>/...`` become labeled series instead of a
  per-tenant/per-group/per-replica metric-name explosion.
- gauges   -> ``# TYPE ... gauge`` (``serving/replica/<id>/...`` gauges
  fold into labeled series the same way).
- histograms -> ``# TYPE ... summary`` (windowed quantiles:
  ``{quantile="0.5|0.95|0.99"}`` + ``_sum`` + ``_count``) PLUS a parallel
  ``<name>_hist`` native histogram family — lifetime cumulative
  ``_bucket``/``le`` counts on the sink's fixed ladder, so external
  alerting can compute its own quantiles over any rate() window.

Everything is prefixed ``dstpu_`` and sanitized to the metric-name charset.
Stdlib-only by design (same budget as the gateway).
"""

import re

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
_TENANT_RE = re.compile(r"^gateway/tenant/(?P<tenant>.+)/tokens$")
_COMM_RE = re.compile(r"^comm/(?P<op>[^/]+)/(?P<group>[^/]+)/bytes$")
_REPLICA_RE = re.compile(r"^serving/replica/(?P<replica>\d+)/(?P<metric>.+)$")
_ADAPTER_RE = re.compile(r"^serving/adapter/(?P<adapter>.+)/"
                         r"(?P<metric>loads|evicts|requests|tokens)$")
# multi-host serving (serving/router.py): per-worker fleet families fold
# into one labeled series per metric, same shape as per-replica — the
# router caps wid cardinality at 256 labels before these ever render
_WORKER_RE = re.compile(r"^serving/worker/(?P<worker>[^/]+)/(?P<metric>.+)$")

_PREFIX = "dstpu_"


def _name(raw):
    return _PREFIX + _NAME_RE.sub("_", raw.strip("/"))


def _labels(pairs):
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in pairs)
    return "{" + inner + "}"


def _escape(value):
    return str(value).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(value):
    value = float(value)
    # the text format has non-finite literals; int(nan/inf) would raise —
    # and a NaN loss gauge must not fail the whole scrape mid-incident
    if value != value:
        return "NaN"
    if value in (float("inf"), float("-inf")):
        return "+Inf" if value > 0 else "-Inf"
    return repr(int(value)) if value == int(value) else repr(value)


def _counter_series(raw_name):
    """(metric_name, label_pairs) for one counter, folding the
    client/topology-cardinality families into labels."""
    m = _TENANT_RE.match(raw_name)
    if m:
        return _PREFIX + "gateway_tenant_tokens_total", [("tenant", m.group("tenant"))]
    m = _COMM_RE.match(raw_name)
    if m:
        return _PREFIX + "comm_bytes_total", [("op", m.group("op")),
                                              ("group", m.group("group"))]
    m = _REPLICA_RE.match(raw_name)
    if m:
        return (_name("serving/replica/" + m.group("metric")) + "_total",
                [("replica", m.group("replica"))])
    m = _WORKER_RE.match(raw_name)
    if m:
        return (_name("serving/worker/" + m.group("metric")) + "_total",
                [("worker", m.group("worker"))])
    m = _ADAPTER_RE.match(raw_name)
    if m:  # per-adapter multi-LoRA counters: one labeled family per metric.
        # "per_adapter" (not "adapter") keeps the labeled family's name
        # disjoint from the fleet-total counters (serving/adapter_loads ->
        # dstpu_serving_adapter_loads_total) — mixing an unlabeled
        # aggregate into a labeled family would double-count sum() queries
        return (_name("serving/per_adapter/" + m.group("metric")) + "_total",
                [("adapter", m.group("adapter"))])
    return _name(raw_name) + "_total", []


def _gauge_series(raw_name):
    """(metric_name, label_pairs) for one gauge — per-replica serving
    gauges fold into one labeled family per metric."""
    m = _REPLICA_RE.match(raw_name)
    if m:
        return (_name("serving/replica/" + m.group("metric")),
                [("replica", m.group("replica"))])
    m = _WORKER_RE.match(raw_name)
    if m:
        return (_name("serving/worker/" + m.group("metric")),
                [("worker", m.group("worker"))])
    return _name(raw_name), []


def render(snapshot, extra_gauges=None):
    """Prometheus text body from a sink snapshot dict. ``extra_gauges``:
    ``{raw_name: scalar}`` appended as plain gauges (the gateway passes its
    queue/occupancy stats so scrapers see one coherent surface)."""
    lines = []
    typed = set()

    def header(name, kind):
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    # group counter samples by RESOLVED metric name first: the text format
    # requires all samples of one metric to form a single contiguous group,
    # and sorting by raw name would interleave the labeled families
    # (comm/<op>/<group>/bytes) with unlabeled comm/* counters
    counter_groups = {}
    for raw, c in sorted(snapshot.get("counters", {}).items()):
        name, labels = _counter_series(raw)
        counter_groups.setdefault(name, []).append((labels, c["total"]))
    for name in sorted(counter_groups):
        header(name, "counter")
        for labels, total in counter_groups[name]:
            lines.append(f"{name}{_labels(labels)} {_fmt(total)}")

    all_gauges = dict(snapshot.get("gauges", {}))
    for raw, value in (extra_gauges or {}).items():
        if value is not None:
            all_gauges[raw] = value
    # group by RESOLVED name (same contiguity rule as counters: the
    # per-replica labeled families must not interleave with plain gauges)
    gauge_groups = {}
    for raw, value in sorted(all_gauges.items()):
        name, labels = _gauge_series(raw)
        gauge_groups.setdefault(name, []).append((labels, value))
    for name in sorted(gauge_groups):
        header(name, "gauge")
        for labels, value in gauge_groups[name]:
            lines.append(f"{name}{_labels(labels)} {_fmt(value)}")

    for raw, h in sorted(snapshot.get("histograms", {}).items()):
        name = _name(raw)
        header(name, "summary")
        for q in ("0.5", "0.95", "0.99"):
            key = "p" + q[2:].ljust(2, "0")  # 0.5 -> p50, 0.95 -> p95, 0.99 -> p99
            lines.append(f'{name}{{quantile="{q}"}} {_fmt(h[key])}')
        lines.append(f"{name}_sum {_fmt(h['sum'])}")
        lines.append(f"{name}_count {_fmt(h['count'])}")
        # native histogram alongside the summary (a metric can't be both
        # types, so the bucketed family rides a ``_hist`` suffix): lifetime
        # cumulative counts on the sink's fixed ladder — external alerting
        # computes its own quantiles over ANY window via rate(), which the
        # sliding-window summary can't offer
        buckets = h.get("buckets")
        if buckets:
            hname = name + "_hist"
            header(hname, "histogram")
            for le, cum in buckets:
                lines.append(f'{hname}_bucket{{le="{_fmt(le)}"}} {_fmt(cum)}')
            lines.append(f'{hname}_bucket{{le="+Inf"}} {_fmt(h["count"])}')
            lines.append(f"{hname}_sum {_fmt(h['sum'])}")
            lines.append(f"{hname}_count {_fmt(h['count'])}")

    uptime = snapshot.get("uptime_s")
    if uptime is not None:
        header(_PREFIX + "uptime_seconds", "gauge")
        lines.append(f"{_PREFIX}uptime_seconds {_fmt(uptime)}")
    return "\n".join(lines) + "\n"
